"""North-star benchmark: steady-state placement rounds, 1M tasks x 1k nodes.

BASELINE.json metric: "scheduler throughput (tasks/sec) + p50 placement
latency @1M tasks/1k nodes"; north star: schedule 1M pending tasks across a
1k-node simulated cluster in <50 ms p50 on one TPU, matching the CPU
HybridPolicy bit-for-bit.  vs_baseline = 50ms / measured_p50 (>1 beats it).

What is timed, per heartbeat round (the pipeline a raylet heartbeat runs):
  1. device water-fill over the scheduling-class batch (ray_tpu.ops),
  2. device->host transfer of the (classes x nodes) placement counts,
  3. host expansion of counts into per-node assignments for every task in
     each class queue (np.repeat per class — the runtime dispatches straight
     from per-class queues, matching the reference ClusterTaskManager's
     SchedulingClass-keyed queue).
Rounds run software-pipelined (dispatch all, then one batched fetch), which
is how a continuously-beating scheduler overlaps transfer with compute; the
fetch stacks all rounds on device and packs counts to int16 (provably safe:
a count is bounded by its class's queue depth < 2^15), halving bytes on the
host link — transfer is the dominant term, so this matters.  p50 is over
per-round wall time at steady state.  Scheduling-class *grouping* is
not timed: classes are interned at task submission (TaskSpec
.scheduling_class), identical to the reference.

Output contract (r08): the first stdout line is ALWAYS a CPU-backend
delta-heartbeat smoke record (run in a subprocess so a wedged TPU
tunnel cannot block it) — BENCH_r* is never empty again.  When the
device headline runs, its record prints LAST (the driver parses the
last JSON line) and embeds the same ``delta`` section: per-phase
breakdown (densify, host->HBM upload, dirty-row rescore, fused
water-fill+argmin, counts readback) and the delta-beat hit rate over
a churn workload driven through the real ClusterResourceManager dirty
journal (scheduling/cluster_resources.py delta_view ->
scheduling/policy.py DeltaScheduler).

r17 adds the ``budget_beat`` stage on every path (device, smoke, and
graceful skip): per-(class, node) lease budgets ride the beat's single
packed readback, the timed loop includes the board publish that feeds
the lease grantor, and the record carries the device-vs-CPU-oracle
budget parity gate plus ``readbacks_per_beat: 1``.
"""

import json
import sys
import time

import numpy as np

N_NODES = 1000
N_RES = 8
N_CLASSES = 64
N_TASKS = 1_000_000
ROUNDS = 20         # rounds per timed repetition (amortizes the tunnel RTT)
REPS = 9            # p50 over per-round means of these repetitions
# NOTE: measured p50 swings 15 ms..60 ms with DEV-TUNNEL congestion
# (a bare 1024^2 matmul round trip was observed at 1 ms and at 600 ms
# on the same day); the scheduler code is identical across those runs.
# Treat any regression against BENCH_r*.json as suspect until the
# tunnel RTT is checked.
TARGET_MS = 50.0


def build_problem(seed=0):
    rng = np.random.default_rng(seed)
    totals = rng.integers(400, 12800, size=(N_NODES, N_RES)).astype(np.int32)
    totals[rng.random(totals.shape) < 0.25] = 0
    used = (totals * rng.random(totals.shape) * 0.5).astype(np.int32)
    avail = totals - used
    node_mask = np.ones(N_NODES, dtype=bool)

    reqs = rng.integers(0, 400, size=(N_CLASSES, N_RES)).astype(np.int32)
    reqs[rng.random(reqs.shape) < 0.5] = 0
    counts = rng.multinomial(N_TASKS, np.full(N_CLASSES, 1 / N_CLASSES))
    return totals, avail, node_mask, reqs, counts.astype(np.int32)


def expand(counts_host, n_nodes):
    """Per-queue-position node assignment for every scheduling class.

    counts_host: (G, N+1).  Returns list of per-class int32 arrays (node row
    per task, -1 infeasible) — the order tasks are popped from each class
    queue.
    """
    cols = np.concatenate([np.arange(n_nodes, dtype=np.int32),
                           np.array([-1], dtype=np.int32)])
    return [np.repeat(cols, counts_host[g])
            for g in range(counts_host.shape[0])]


def measure_rtt(reps: int = 21) -> float:
    """Dev-tunnel control probe: p50 round trip of a TINY fixed transfer
    (64 int32).  The scheduler's measured p50 rides on this link — when
    the probe is slow, a regression in the headline number is tunnel
    congestion, not code (VERDICT r03: the bench must measure and
    report its own noise floor)."""
    import jax
    import jax.numpy as jnp
    f = jax.jit(lambda v: v + 1)
    x = jnp.zeros(64, jnp.int32)
    np.asarray(f(x))                    # warm/compile
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        np.asarray(f(x))
        ts.append((time.perf_counter() - t0) * 1e3)
    return float(np.percentile(ts, 50))


def measure_plane_throughput(mb: int = 32) -> float:
    """Object-plane transfer throughput (MB/s): one chunked
    arena-to-arena pull between two in-process stores over a real
    loopback RPC server — the wire path agents use
    (runtime/object_plane.py)."""
    import os
    import tempfile

    from ray_tpu.common.ids import ObjectID
    from ray_tpu.native import Arena
    from ray_tpu.rpc import RpcServer
    from ray_tpu.runtime.object_plane import ObjectPlane
    from ray_tpu.runtime.object_store import MemoryStore

    size = mb << 20
    tmp = tempfile.mkdtemp(prefix="bench_plane_")
    src_arena = Arena(os.path.join(tmp, "src"), size * 2, create=True)
    dst_arena = Arena(os.path.join(tmp, "dst"), size * 2, create=True)
    src = MemoryStore(arena=src_arena,
                      spill_dir=os.path.join(tmp, "s_spill"))
    dst = MemoryStore(arena=dst_arena,
                      spill_dir=os.path.join(tmp, "d_spill"))
    src_plane, dst_plane = ObjectPlane(src), ObjectPlane(dst)
    server = RpcServer(src_plane.handlers()).start()
    oid = ObjectID(os.urandom(28))
    src.put_serialized(oid, os.urandom(size))
    try:
        t0 = time.perf_counter()
        ok = dst_plane.pull_into_local(oid, size, server.address)
        dt = time.perf_counter() - t0
        assert ok, "plane transfer failed"
        return round(mb / dt, 1)
    finally:
        server.stop()
        src_plane.shutdown()
        dst_plane.shutdown()
        src_arena.close()
        dst_arena.close()
        import shutil
        shutil.rmtree(tmp, ignore_errors=True)


def delta_churn_bench(n_nodes: int = 256, n_classes: int = 32,
                      beats: int = 30, churn: int = 12,
                      seed: int = 0, shards: int = 1) -> dict:
    """Delta-scheduling heartbeat under node churn, on the REAL stack:
    a ClusterResourceManager takes random subtract/add_back mutations
    between beats and the DeltaScheduler syncs its HBM mirror from the
    dirty journal.  Returns hit rate, per-beat p50, the per-phase
    breakdown (profile mode inserts device syncs, so phase sums exceed
    the unprofiled beat wall time), and bit-parity of the final beat
    vs the CPU oracle.

    ``shards > 1`` runs the mesh-sharded engine instead (r14): node
    rows partitioned over the device mesh with the two-level ICI/DCN
    argmin reduce — same workload, same parity gate."""
    from ray_tpu.common.ids import NodeID
    from ray_tpu.common.resources import NodeResources, ResourceRequest
    from ray_tpu.scheduling import (ClusterResourceManager, DeltaScheduler,
                                    ShardedDeltaScheduler,
                                    schedule_grouped_oracle)

    rng = np.random.default_rng(seed)
    crm = ClusterResourceManager(capacity=n_nodes)
    for _ in range(n_nodes):
        crm.add_node(NodeID.from_random(), NodeResources(
            {"CPU": int(rng.integers(4, 64)),
             "memory": int(rng.integers(8, 256)),
             "TPU": int(rng.integers(0, 8))}))
    class_reqs = [ResourceRequest({"CPU": int(rng.integers(1, 4)),
                                   "memory": float(rng.integers(0, 8))})
                  for _ in range(n_classes)]
    t0 = time.perf_counter()
    vecs = np.stack([crm.intern_request(r) for r in class_reqs])
    densify_ms = (time.perf_counter() - t0) * 1e3
    counts = rng.integers(1, 40, size=n_classes).astype(np.int32)

    eng = ShardedDeltaScheduler(crm, shards) if shards > 1 \
        else DeltaScheduler(crm)
    eng.profile = True
    eng.phase_ms["densify"] += densify_ms
    churn_req = ResourceRequest({"CPU": 1})
    debts: list[int] = []
    got = eng.beat(vecs, counts)            # beat 1: the full sync
    per_beat = []
    for _ in range(beats):
        for _ in range(churn):
            if debts and rng.random() < 0.5:
                crm.add_back(debts.pop(), churn_req)
            else:
                row = int(rng.integers(0, n_nodes))
                crm.force_subtract(row, churn_req)
                debts.append(row)
        t0 = time.perf_counter()
        got = eng.beat(vecs, counts)
        per_beat.append((time.perf_counter() - t0) * 1e3)
    want = schedule_grouped_oracle(crm.snapshot(), vecs, counts)
    n_beats = eng.stats["beats"]
    return {
        "workload": f"{n_nodes} nodes x {n_classes} classes, "
                    f"{churn} dirty rows/beat x {beats} beats",
        "hit_rate": round(eng.hit_rate(), 4),
        "beat_p50_ms": round(float(np.percentile(per_beat, 50)), 3),
        "phases_ms_per_beat": {k: round(v / n_beats, 4)
                               for k, v in eng.phase_ms.items()},
        "oracle_parity": bool((got == want).all()),
        "shards": eng.stats.get("shards", 1),
        **{k: eng.stats[k] for k in ("beats", "delta_beats",
                                     "full_rescores", "clean_beats",
                                     "rows_uploaded")},
    }


# sharded-phase names for the r14 breakdown (ISSUE 14 satellite 1):
# the engine's phase timers keep the r08 keys; the record maps them to
# what each phase IS on the sharded path.
_SHARDED_PHASE_NAMES = {"h2d": "shard_upload", "score": "local_score",
                        "argmin": "cross_device_reduce",
                        "readback": "readback", "densify": "densify"}

# per-device HBM budget for the ceiling model (v5e: 16 GiB/chip)
_HBM_BYTES = 16 * (1 << 30)


def _hbm_ceiling_classes(n_nodes: int, n_res: int, shards: int,
                         budget: int = _HBM_BYTES) -> int:
    """Largest resident class count whose scheduling plane fits ONE
    device's HBM at S-way sharding, at ``n_nodes`` nodes (the contract
    caps nodes at MAX_NODES, so classes are the unbounded axis of the
    (tasks x nodes) problem).  Per device: its N/S key columns cost
    4*N/S bytes per class plus the replicated (C, R) request row; the
    node-state rows (totals/avail/masks) are class-independent.  Key
    columns dominate, so max C scales ~linearly with S."""
    rows = -(-n_nodes // shards)                # N/S, ceil
    per_class = 4 * rows + 4 * n_res
    fixed = rows * (8 * n_res + 2)
    return max((budget - fixed) // per_class, 0)


def sharded_delta_bench(n_nodes: int = 512, n_classes: int = 48,
                        beats: int = 25, churn: int = 24,
                        seed: int = 0, shards: int = 0) -> dict:
    """The r14 sharded-vs-fused stage: the SAME churn workload through
    the single-device engine and the mesh-sharded engine, with the
    sharded per-phase breakdown (shard upload / local score /
    cross-device reduce / readback) and the HBM-ceiling model showing
    how much larger a problem the mesh holds than one chip.

    Runs on whatever backend jax resolves — on the CPU fallback the
    phase numbers are still real engine phases (8 virtual devices),
    only the absolute times are not TPU times."""
    import jax

    from ray_tpu.ops.shard_reduce import resolve_shards
    s = resolve_shards(shards, len(jax.local_devices()))
    fused = delta_churn_bench(n_nodes, n_classes, beats, churn, seed,
                              shards=1)
    rec: dict = {"shards": s, "fused": fused}
    if s > 1:
        sharded = delta_churn_bench(n_nodes, n_classes, beats, churn,
                                    seed, shards=s)
        sharded["phases_ms_per_beat"] = {
            _SHARDED_PHASE_NAMES.get(k, k): v
            for k, v in sharded["phases_ms_per_beat"].items()}
        rec["sharded"] = sharded
        rec["bit_exact_fused_vs_sharded"] = bool(
            sharded["oracle_parity"] and fused["oracle_parity"])
    else:
        rec["sharded"] = None
        rec["note"] = "one device: single-chip fallback selected"
    # ONE counts fetch per beat by construction, at any shard count:
    # fused_beat gathers counts+argmin device-side and the host reads
    # one (G, N+1) buffer (scheduling/policy.py beat()).
    rec["readbacks_per_beat"] = 1
    # HBM ceiling model at the contract's full node axis (MAX_NODES,
    # 8 resource columns): how many resident scheduling classes — the
    # unbounded axis of the (tasks x nodes) problem — the aggregate
    # mesh holds vs one chip.
    from ray_tpu.scheduling import MAX_NODES
    kn, kr = MAX_NODES, 8
    single = _hbm_ceiling_classes(kn, kr, 1)
    sharded_c = _hbm_ceiling_classes(kn, kr, max(s, 1))
    rec["hbm_ceiling_model"] = {
        "nodes": kn, "resources": kr,
        "hbm_bytes_per_device": _HBM_BYTES,
        "max_classes_single_device": single,
        "max_classes_sharded": sharded_c,
        "problem_ratio": round(sharded_c / max(single, 1), 2),
    }
    return rec


def budget_beat_bench(n_nodes: int = 256, n_classes: int = 24,
                      beats: int = 20, churn: int = 16,
                      seed: int = 0, shards: int = 0) -> dict:
    """The r17 tentpole stage: the fused beat emits per-(class, node)
    lease budgets INSIDE its single packed readback, and the timed
    region covers the full loop a raylet heartbeat runs — churned
    beat, packed counts+budgets fetch, and the board publish that
    re-keys budget rows for the lease grantor.  Parity gate: the
    final beat's budget rows must be bit-identical to the CPU oracle
    twin (``contract.compute_budgets`` on the post-water-fill state).
    Runs fused always; when the backend has >1 device the same
    workload repeats on the mesh-sharded engine with the same gate."""
    import jax

    from ray_tpu.common.ids import NodeID
    from ray_tpu.common.resources import NodeResources, ResourceRequest
    from ray_tpu.leasing.board import BudgetBoard
    from ray_tpu.ops.shard_reduce import resolve_shards
    from ray_tpu.scheduling import (ClusterResourceManager, DeltaScheduler,
                                    ShardedDeltaScheduler,
                                    schedule_grouped_oracle)
    from ray_tpu.scheduling.contract import compute_budgets

    def one_engine(n_shards: int) -> dict:
        rng = np.random.default_rng(seed)
        crm = ClusterResourceManager(capacity=n_nodes)
        for _ in range(n_nodes):
            crm.add_node(NodeID.from_random(), NodeResources(
                {"CPU": int(rng.integers(4, 64)),
                 "memory": int(rng.integers(8, 256))}))
        class_reqs = [ResourceRequest(
            {"CPU": int(rng.integers(1, 4)),
             "memory": float(rng.integers(0, 8))})
            for _ in range(n_classes)]
        vecs = np.stack([crm.intern_request(r) for r in class_reqs])
        counts = rng.integers(1, 40, size=n_classes).astype(np.int32)
        eng = ShardedDeltaScheduler(crm, n_shards) if n_shards > 1 \
            else DeltaScheduler(crm)
        board = BudgetBoard()
        churn_req = ResourceRequest({"CPU": 1})
        debts: list[int] = []
        eng.beat(vecs, counts)              # beat 1: the full sync
        per_beat = []
        for _ in range(beats):
            for _ in range(churn):
                if debts and rng.random() < 0.5:
                    crm.add_back(debts.pop(), churn_req)
                else:
                    row = int(rng.integers(0, n_nodes))
                    crm.force_subtract(row, churn_req)
                    debts.append(row)
            t0 = time.perf_counter()
            eng.beat(vecs, counts)
            budgets = eng.last_budgets()
            board.publish(eng.budget_seq,
                          {str(i): budgets[i] for i in range(n_classes)})
            per_beat.append((time.perf_counter() - t0) * 1e3)
        st = crm.snapshot()
        schedule_grouped_oracle(st, vecs, counts)
        want = compute_budgets(st.totals, st.avail, vecs,
                               node_mask=st.node_mask)
        parity = all(
            np.array_equal(eng.budget_row_host(v), want[i])
            for i, v in enumerate(vecs))
        return {
            "workload": f"{n_nodes} nodes x {n_classes} classes, "
                        f"{churn} dirty rows/beat x {beats} beats",
            "beat_plus_publish_p50_ms":
                round(float(np.percentile(per_beat, 50)), 3),
            "budget_parity": parity,
            "budget_rows_per_beat": n_classes,
            "nonzero_budget_fraction":
                round(float((want[:, st.node_mask] > 0).mean()), 4),
            "board": board.stats(),
            "shards": eng.stats.get("shards", 1),
        }

    s = resolve_shards(shards, len(jax.local_devices()))
    rec: dict = {"fused": one_engine(1),
                 "sharded": one_engine(s) if s > 1 else None,
                 # budgets ride the beat's ONE sanctioned fetch: the
                 # packed (G + C, N+1) buffer (scheduling/policy.py)
                 "readbacks_per_beat": 1}
    rec["budget_parity"] = rec["fused"]["budget_parity"] and (
        rec["sharded"] is None or rec["sharded"]["budget_parity"])
    return rec


def dispatch_lease_bench(num_nodes: int = 10000, jobs: int = 1000,
                         tasks_per_job: int = 16, seed: int = 0,
                         kill_head_at: float | None = 60.0) -> dict:
    """The r15 tentpole surface: lease-plane dispatch throughput vs the
    head-only path on the identical seeded job stream, plus the
    hot-standby failover window (head SIGKILL mid-stream).  Pure
    simulation over modeled head service time (sim/dispatch_bench.py)
    — deterministic, replay-stable, no device needed."""
    from ray_tpu.sim.dispatch_bench import run_dispatch_comparison
    cmp_ = run_dispatch_comparison(num_nodes, jobs, tasks_per_job,
                                   seed=seed, kill_head_at=kill_head_at)
    rec = {
        "nodes": num_nodes, "jobs": jobs,
        "tasks": jobs * tasks_per_job, "seed": seed,
        "speedup_vs_head_only": cmp_["speedup"],
        "head_only_throughput_per_s":
            cmp_["head_only"]["dispatch_throughput_per_s"],
        "lease_throughput_per_s":
            cmp_["lease"]["dispatch_throughput_per_s"],
        "lease_hit_rate": cmp_["lease"]["lease_hit_rate"],
        "spillbacks": cmp_["lease"]["spillbacks"],
        "trace_hash_head_only": cmp_["head_only"]["trace_hash"],
        "trace_hash_lease": cmp_["lease"]["trace_hash"],
    }
    fo = cmp_.get("failover")
    if fo is not None:
        rec["failover"] = {
            "kill_head_at_s": kill_head_at,
            "promotions": fo["promotions"],
            "failover_ms": fo["failover_ms"],
            "jobs_completed": fo["jobs_completed"],
            "lease_hit_rate": fo["lease_hit_rate"],
            "lease_revocations": fo["lease_revocations"],
            "trace_hash": fo["trace_hash"],
        }
    return rec


def _emit_smoke() -> None:
    """The --smoke entry: CPU-backend delta churn, one JSON line.
    Runs FIRST (subprocess, JAX_PLATFORMS=cpu) so every bench round
    records a real heartbeat number even with the tunnel down."""
    delta = delta_churn_bench(n_nodes=128, n_classes=16, beats=25,
                              churn=8)
    sharded = sharded_delta_bench(n_nodes=128, n_classes=16, beats=12,
                                  churn=8)
    dispatch = dispatch_lease_bench(num_nodes=64, jobs=40,
                                    tasks_per_job=8, kill_head_at=None)
    budget = budget_beat_bench(n_nodes=128, n_classes=16, beats=12,
                               churn=8)
    ok = delta["oracle_parity"] and \
        sharded.get("bit_exact_fused_vs_sharded", True) and \
        budget["budget_parity"]
    print(json.dumps({
        "metric": "delta heartbeat smoke: CPU backend churn workload"
                  + ("" if ok else " [PARITY FAIL]"),
        "value": delta["beat_p50_ms"],
        "unit": "ms",
        "vs_baseline": 0.0,         # smoke line: not the headline metric
        "status": "smoke",
        "delta": delta,
        "sharded": sharded,
        "dispatch": dispatch,
        "budget_beat": budget,
    }), flush=True)


def _smoke_first() -> None:
    """Emit the smoke record from a disposable CPU-backend subprocess
    (a hung in-process backend cannot eat it); degrade to a marker
    record rather than printing nothing."""
    import os
    import subprocess
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--smoke"],
            capture_output=True, text=True, timeout=300, env=env)
        lines = [ln for ln in proc.stdout.splitlines() if ln.strip()]
        if proc.returncode == 0 and lines:
            print(lines[-1], flush=True)
            return
        err = f"rc={proc.returncode}: {proc.stderr.strip()[-300:]}"
    except subprocess.TimeoutExpired:
        err = "smoke subprocess exceeded 300s"
    print(json.dumps({
        "metric": f"delta heartbeat smoke FAILED [{err}]",
        "value": -1.0, "unit": "ms", "vs_baseline": 0.0,
        "status": "smoke_failed"}), flush=True)


def _last_good_record() -> dict | None:
    """Newest BENCH_r*.json next to this script whose recorded device
    measurement was real (value > 0): the number a skipped round
    carries forward so trend plots keep a device point."""
    import glob
    import os
    best = None
    here = os.path.dirname(os.path.abspath(__file__))
    for path in sorted(glob.glob(os.path.join(here, "BENCH_r*.json"))):
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        rec = doc.get("parsed") if isinstance(doc, dict) else None
        rec = rec if isinstance(rec, dict) else doc
        value = rec.get("value", -1.0) if isinstance(rec, dict) else -1.0
        if isinstance(value, (int, float)) and value > 0 \
                and rec.get("status") != "skipped":
            best = {"file": os.path.basename(path),
                    "round": doc.get("n") if isinstance(doc, dict) else None,
                    "value": value, "unit": rec.get("unit", "ms"),
                    "vs_baseline": rec.get("vs_baseline")}
    return best


def _cpu_fallback_p50(rounds: int = 5, reps: int = 3) -> float:
    """The same placement pipeline on the host CPU backend (reduced
    round count): proves the scheduler code path still runs end-to-end
    when the device is unreachable.  NOT comparable to the device
    headline — recorded as ``cpu_fallback_p50_ms`` only."""
    import os
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from ray_tpu.ops import schedule_grouped
    from ray_tpu.scheduling import threshold_fp

    totals, avail, node_mask, reqs, counts = build_problem()
    d = jnp.asarray
    args = (d(totals), d(avail), d(node_mask), d(reqs), d(counts),
            jnp.ones((N_CLASSES, N_NODES), dtype=bool),
            jnp.int32(threshold_fp(0.5)))

    @jax.jit
    def pack(outs):
        return jnp.stack(outs).astype(jnp.int16)

    np.asarray(pack([schedule_grouped(*args)[0]
                     for _ in range(rounds)]))    # warm/compile
    per_round = []
    for _ in range(reps):
        t0 = time.perf_counter()
        hosts = np.asarray(pack([schedule_grouped(*args)[0]
                                 for _ in range(rounds)]))
        for h in hosts:
            expand(h, N_NODES)
        per_round.append((time.perf_counter() - t0) * 1e3 / rounds)
    return float(np.percentile(per_round, 50))


def _emit_skipped(reason: str, cpu_p50: float | None = None,
                  delta: dict | None = None,
                  sharded: dict | None = None,
                  dispatch: dict | None = None,
                  budget: dict | None = None) -> None:
    """Graceful degradation for tunnel outages: one ``status:skipped``
    JSON line carrying the last-good device number (and the CPU
    fallback measurement when one ran) — instead of the old rc=3
    failure that recorded nothing usable."""
    last = _last_good_record()
    value = last["value"] if last else -1.0
    src = f"last-good {last['file']}" if last \
        else "no prior device record"
    print(json.dumps({
        "metric": "p50 heartbeat time: 1M tasks x 1k nodes "
                  f"[SKIPPED: {reason}; device value is {src}]",
        "value": round(value, 3) if value > 0 else -1.0,
        "unit": "ms",
        "vs_baseline": round(TARGET_MS / value, 2) if value > 0 else 0.0,
        "status": "skipped",
        "skip_reason": reason,
        "last_good": last,
        "cpu_fallback_p50_ms":
            round(cpu_p50, 3) if cpu_p50 is not None else None,
        "delta": delta,
        "sharded": sharded,
        "dispatch": dispatch,
        "budget_beat": budget,
    }), flush=True)


def _arm_watchdog(seconds: float = 600.0) -> None:
    """The dev-tunnel backend init can hang INDEFINITELY during tunnel
    outages (observed 2026-07-30: jax.devices() blocked >3h).  A hung
    bench records nothing; the watchdog emits the skipped record (the
    wedged in-process backend rules out a CPU fallback run here) and
    exits 0 so the harness keeps the record."""
    import os
    import threading

    def fire():
        _emit_skipped(f"backend init exceeded {seconds:.0f}s; "
                      "see rtt_control history")
        os._exit(0)
    t = threading.Timer(seconds, fire)
    t.daemon = True
    t.start()
    # disarm once the backend is live (main() replaces this no-op)
    _arm_watchdog.cancel = t.cancel


def _tunnel_probe(timeout_s: float = 90.0) -> bool:
    """Backend init in a SUBPROCESS: a hung init is unrecoverable
    in-process (observed 2026-07-30/31: jax.devices() blocked for
    hours), so probe disposable processes until one sees the chip."""
    import subprocess
    import sys
    try:
        proc = subprocess.run(
            [sys.executable, "-c",
             "import jax; jax.devices(); print('ok')"],
            capture_output=True, text=True, timeout=timeout_s)
        return proc.returncode == 0 and "ok" in proc.stdout
    except subprocess.TimeoutExpired:
        return False


def main():
    # invariant: one smoke record exists before anything can hang
    _smoke_first()
    # tunnel-flap resilience: probe up to ~7 minutes for a live
    # backend BEFORE importing jax here — an outage window that ends
    # mid-round still yields a real measurement instead of a marker
    probe_deadline = time.monotonic() + 420.0
    attempts = 0
    import os as _os
    force_skip = _os.environ.get("RT_BENCH_FORCE_SKIP") == "1"
    while True:
        attempts += 1
        if not force_skip and _tunnel_probe():
            break
        if force_skip or time.monotonic() >= probe_deadline:
            # graceful degradation: CPU-backend fallback run + the
            # last-good device number, as a skipped record (rc 0)
            reason = ("forced skip (RT_BENCH_FORCE_SKIP)" if force_skip
                      else f"TPU tunnel unreachable: {attempts} "
                           "subprocess probes over 7 min all hung")
            try:
                cpu_p50 = _cpu_fallback_p50()
            except Exception as e:   # noqa: BLE001 — record, don't die
                print(f"cpu fallback failed: {e!r}",
                      file=__import__("sys").stderr)
                cpu_p50 = None
            try:
                delta = delta_churn_bench(n_nodes=128, n_classes=16,
                                          beats=25, churn=8)
            except Exception as e:   # noqa: BLE001 — record, don't die
                print(f"delta churn fallback failed: {e!r}",
                      file=sys.stderr)
                delta = None
            try:
                sharded = sharded_delta_bench(n_nodes=256, n_classes=24,
                                              beats=15, churn=16)
            except Exception as e:   # noqa: BLE001 — record, don't die
                print(f"sharded delta fallback failed: {e!r}",
                      file=sys.stderr)
                sharded = None
            try:
                # full acceptance scale: the sim needs no device
                dispatch = dispatch_lease_bench(num_nodes=10000,
                                                jobs=1000,
                                                tasks_per_job=16,
                                                kill_head_at=60.0)
            except Exception as e:   # noqa: BLE001 — record, don't die
                print(f"dispatch lease fallback failed: {e!r}",
                      file=sys.stderr)
                dispatch = None
            try:
                # r17: budget emission + parity gate needs no device
                budget = budget_beat_bench(n_nodes=256, n_classes=24,
                                           beats=15, churn=16)
            except Exception as e:   # noqa: BLE001 — record, don't die
                print(f"budget beat fallback failed: {e!r}",
                      file=sys.stderr)
                budget = None
            _emit_skipped(reason, cpu_p50, delta, sharded, dispatch,
                          budget)
            return
        time.sleep(20.0)

    import jax
    import jax.numpy as jnp

    _arm_watchdog()

    from ray_tpu.ops import schedule_grouped
    from ray_tpu.scheduling import threshold_fp

    totals, avail, node_mask, reqs, counts = build_problem()
    thr = threshold_fp(0.5)
    # int16 packing safety: a per-node count never exceeds its class's
    # queue depth
    assert counts.max() < 2 ** 15, counts.max()

    d = jnp.asarray
    args = (d(totals), d(avail), d(node_mask), d(reqs), d(counts),
            jnp.ones((N_CLASSES, N_NODES), dtype=bool), jnp.int32(thr))

    @jax.jit
    def pack_rounds(outs):
        return jnp.stack(outs).astype(jnp.int16)

    # warmup/compile (np.asarray is the reliable sync on every backend)
    np.asarray(pack_rounds([schedule_grouped(*args)[0]
                            for _ in range(ROUNDS)]))
    _arm_watchdog.cancel()      # backend is live: measurements proceed

    rtt_before = measure_rtt()

    per_round = []
    for _ in range(REPS):
        t0 = time.perf_counter()
        outs = [schedule_grouped(*args)[0] for _ in range(ROUNDS)]
        hosts = np.asarray(pack_rounds(outs))   # one (R, G, N+1) fetch
        assignments = [expand(h, N_NODES) for h in hosts]
        dt = (time.perf_counter() - t0) * 1e3 / ROUNDS
        per_round.append(dt)
    p50 = float(np.percentile(per_round, 50))

    # compute-only: device rounds synced WITHOUT the counts fetch or the
    # host expansion — isolates kernel time from the transfer+host terms
    compute_rounds = []
    for _ in range(REPS):
        t0 = time.perf_counter()
        outs = [schedule_grouped(*args)[0] for _ in range(ROUNDS)]
        jax.block_until_ready(outs[-1])
        compute_rounds.append(
            (time.perf_counter() - t0) * 1e3 / ROUNDS)
    compute_ms = float(np.percentile(compute_rounds, 50))
    rtt_after = measure_rtt()
    rtt_ms = round(min(rtt_before, rtt_after), 3)

    total = int(hosts[-1].astype(np.int64).sum())
    assert total == N_TASKS, (total, N_TASKS)
    placed = int(hosts[-1][:, :-1].astype(np.int64).sum())  # excl. the
    #                                                 infeasible column
    assert placed > N_TASKS // 2, f"only {placed}/{N_TASKS} placeable"
    assert sum(a.shape[0] for a in assignments[-1]) == N_TASKS

    # bit-for-bit parity vs the CPU oracle over the FULL 64-class batch
    # (~3 s on host; the fixed-point short-cut in schedule_grouped_oracle
    # keeps the O(G·N·R) loop cheap)
    from ray_tpu.scheduling import ClusterState, schedule_grouped_oracle
    st = ClusterState(totals.copy(), avail.copy(), node_mask.copy())
    want = schedule_grouped_oracle(st, reqs, counts, spread_threshold=0.5)
    parity = bool((hosts[-1].astype(np.int32) == want).all())

    print(json.dumps({
        "metric": "p50 heartbeat time: 1M tasks x 1k nodes, bit-exact hybrid"
                  + ("" if parity else " [PARITY FAIL]"),
        "value": round(p50, 3),
        "unit": "ms",
        "vs_baseline": round(TARGET_MS / p50, 2),
        # controls: rtt_control_ms is the dev-tunnel noise floor (tiny
        # fixed transfer; min of probes before/after the timed section);
        # compute_only_ms excludes the counts fetch + host expansion.
        # p50 drift with a stable compute_only_ms and an elevated
        # rtt_control_ms is tunnel congestion, not a code regression.
        "rtt_control_ms": rtt_ms,
        "compute_only_ms": round(compute_ms, 3),
        # control-normalized headline: p50 minus the measured tunnel
        # noise floor — THIS is the number to compare across rounds
        # (r01-r03 drift attribution, VERDICT r04 next-step #1)
        "p50_minus_rtt_ms": round(max(p50 - rtt_ms, 0.0), 3),
        "plane_transfer_mbps": measure_plane_throughput(),
        # the r08 tentpole surface: device-resident delta heartbeat
        # under churn — phase breakdown + hit rate (module docstring)
        "delta": delta_churn_bench(n_nodes=N_NODES, n_classes=N_CLASSES,
                                   beats=30, churn=32),
        # the r14 tentpole surface: sharded-vs-fused beat + the
        # two-level reduce phase breakdown + the HBM-ceiling model
        "sharded": sharded_delta_bench(n_nodes=N_NODES,
                                       n_classes=N_CLASSES,
                                       beats=20, churn=32),
        # the r15 tentpole surface: lease-plane dispatch + failover
        # (pure sim — the same numbers with or without the device)
        "dispatch": dispatch_lease_bench(num_nodes=10000, jobs=1000,
                                         tasks_per_job=16,
                                         kill_head_at=60.0),
        # the r17 tentpole surface: budgets riding the beat's single
        # packed readback + board publish, with the oracle parity gate
        "budget_beat": budget_beat_bench(n_nodes=N_NODES,
                                         n_classes=N_CLASSES,
                                         beats=20, churn=32),
    }))


if __name__ == "__main__":
    if "--smoke" in sys.argv:
        _emit_smoke()
    else:
        main()
