"""Elastic training benchmark: a shared pool's diurnal day, survived.

Two stages, mirroring bench.py's smoke-first discipline (a JSON record
always lands, even if the live cluster hangs):

- **diurnal** (smoke stage, disposable subprocess): the 128-node
  simulated ``train_diurnal`` campaign — a gang-scheduled training run
  sharing the pool with a diurnal serve deployment while rolling
  SIGKILLs, drains, gray nodes and head kills land — against a no-fault
  control run of the same day.  The SLO report checks the elasticity
  bar (goodput >= 80% of the unfaulted control), that worker AND head
  SIGKILLs actually fired mid-day and the run still finished, that
  capacity loans flowed BOTH directions (serve borrowed idle batch rows
  at its peak; train borrowed a quiet serve node at its trough), that
  acked epochs never regressed, and that the whole day replays
  bit-identically from (seed, params).  Written to ``TRAIN_r19.json``.
- **live sigkill**: a real 2-worker ``ElasticTrainer`` gang on the
  local pool, one member SIGKILLed mid-allreduce, vs an unkilled
  control of the same run.  The kill must surface as a typed gang
  membership event (zero ``max_failures`` burned), the gang re-forms
  from the journaled epoch, and the run completes with monotone acked
  epochs.  ``RT_BENCH_FORCE_SKIP=1`` (or any live-stage exception)
  degrades to a skipped record with rc 0 — the smoke record survives.

Prints one JSON line per stage and writes the full round record to
``TRAIN_r19.json``.
"""

import json
import os
import sys
import tempfile
import threading
import time

SIM_NODES = 128
SIM_SEED = 19
SIM_FAULTS = 40
SIM_DURATION = 600.0
GOODPUT_BAR = 0.8       # faulted goodput vs no-fault control

LIVE_EPOCHS = 3
LIVE_EPOCH_S = 0.8      # per-epoch compute: wide enough to hit

RECORD = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "TRAIN_r19.json")


# -- diurnal sim campaign (the smoke stage) -----------------------------------

def diurnal_train_bench() -> dict:
    """The simulated day: faulted run (twice, for the replay hash) plus
    a no-fault control with an empty schedule — same seed, same arrival
    curve, so goodput deltas are pure fault cost."""
    from ray_tpu.sim import run_campaign

    kw = dict(seed=SIM_SEED, campaign="train_diurnal",
              faults=SIM_FAULTS, duration=SIM_DURATION)
    trace = tempfile.mktemp(suffix=".json")
    r1 = run_campaign(SIM_NODES, out=trace, **kw)
    r2 = run_campaign(SIM_NODES, **kw)
    ctl = run_campaign(SIM_NODES, seed=SIM_SEED,
                       campaign="train_diurnal", faults=0,
                       duration=SIM_DURATION, schedule=[])
    assert r1.ok and ctl.ok, (r1.violations, ctl.violations)

    ops: dict = {}
    with open(trace, encoding="utf-8") as f:
        for e in json.load(f)["events"]:
            if e.get("kind") == "fault":
                ops[e["op"]] = ops.get(e["op"], 0) + 1
    os.unlink(trace)

    t, c = r1.stats["train"], ctl.stats["train"]
    sv = r1.stats["serve"]
    ratio = t["goodput_sps"] / max(c["goodput_sps"], 1e-9)
    slo = {
        "goodput_ratio": round(ratio, 3),
        "goodput_ok": ratio >= GOODPUT_BAR,
        # the day actually bit: worker and head SIGKILLs landed and the
        # run still reached its terminal state
        "worker_sigkill_survived": (ops.get("kill_node", 0) > 0
                                    and t["state"] == "done"),
        "head_sigkill_survived": (ops.get("kill_head", 0) > 0
                                  and t["state"] == "done"),
        "gang_losses_recovered": (t["gang_losses"] > 0
                                  and t["epochs_committed"] > 0),
        # acked progress is monotone: every committed epoch was acked
        "epochs_never_regress": t["acked_epoch"] == t["epochs_committed"],
        # capacity flowed both ways across the one pool
        "loans_both_directions": (sv["loans_total"] > 0
                                  and t["borrows_total"] > 0),
        "borrows_all_settled": (t["borrows_returned"]
                                + t["borrows_lost"]
                                == t["borrows_total"]),
        "replay_bit_identical": r1.trace_hash == r2.trace_hash,
    }
    return {
        "nodes": SIM_NODES, "seed": SIM_SEED, "faults": SIM_FAULTS,
        "duration_s": SIM_DURATION, "fault_ops": ops,
        "faulted": t, "control": c, "serve": {
            k: sv[k] for k in ("loans_total", "reclaims_total",
                               "loans_lost", "accepted", "completed")},
        "trace_hash": r1.trace_hash,
        "slo": slo, "slo_pass": all(slo.values()),
    }


def _emit_smoke() -> None:
    """The --smoke entry: run the diurnal campaign trio in this
    disposable subprocess and print exactly one JSON line."""
    d = diurnal_train_bench()
    bad = [k for k, v in d["slo"].items() if not v]
    flags = "" if not bad else " [SLO FAIL: " + ", ".join(bad) + "]"
    t = d["faulted"]
    print(json.dumps({
        "metric": f"train diurnal {SIM_NODES}-node sim: goodput "
                  f"{d['slo']['goodput_ratio']}x no-fault control "
                  f"through {d['fault_ops'].get('kill_node', 0)} node + "
                  f"{d['fault_ops'].get('kill_head', 0)} head kills; "
                  f"{t['epochs_committed']} epochs, "
                  f"{t['gang_losses']} gang losses, "
                  f"{t['borrows_total']} borrows / "
                  f"{d['serve']['loans_total']} serve loans" + flags,
        "value": d["slo"]["goodput_ratio"],
        "unit": "x",
        "vs_baseline": d["slo"]["goodput_ratio"],
        "status": "smoke",
        "diurnal": d,
    }), flush=True)


def _smoke_first() -> dict | None:
    """Run the sim stage in a subprocess (a hung backend cannot eat the
    record), print its JSON line, and seed TRAIN_r19.json so the
    round's record exists before the live cluster starts."""
    import subprocess
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    err = ""
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--smoke"],
            capture_output=True, text=True, timeout=600, env=env)
        lines = [ln for ln in proc.stdout.splitlines() if ln.strip()]
        if proc.returncode == 0 and lines:
            print(lines[-1], flush=True)
            record = json.loads(lines[-1])
            _write_record(record.get("diurnal"), live=None)
            return record.get("diurnal")
        err = f"rc={proc.returncode}: {proc.stderr.strip()[-300:]}"
    except subprocess.TimeoutExpired:
        err = "smoke subprocess exceeded 600s"
    print(json.dumps({
        "metric": f"train sim smoke FAILED [{err}]",
        "value": -1.0, "unit": "x", "vs_baseline": 0.0,
        "status": "smoke_failed"}), flush=True)
    _write_record(None, live=None, error=err)
    return None


def _write_record(diurnal, live, error: str = "") -> None:
    doc = {"format": "ray_tpu-train-bench/1", "round": 19,
           "diurnal": diurnal, "live": live}
    if error:
        doc["error"] = error
    with open(RECORD, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")


# -- live experiment ----------------------------------------------------------

def _epoch_loop(last_epoch, sleep_s):
    import numpy as np

    from ray_tpu import train as rtrain
    from ray_tpu.train import Checkpoint

    def loop(config):
        ctx = rtrain.get_context()
        ck = rtrain.get_checkpoint()
        start = ck.to_dict()["epoch"] + 1 if ck is not None else 0
        for epoch in range(start, last_epoch + 1):
            ctx.allreduce({"g": np.ones(64)})
            time.sleep(sleep_s)
            rtrain.report({"epoch": epoch},
                          checkpoint=Checkpoint({"epoch": epoch}))
    return loop


def _run_fit(run_name: str, kill: bool) -> dict:
    from ray_tpu.train import ElasticTrainer, FailureConfig, ScalingConfig

    killed = threading.Event()

    def killer():
        import signal

        from ray_tpu.api import _get_runtime
        pool = _get_runtime().raylet.pool
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            with pool._lock:
                busy = [h for h in pool._workers
                        if not h.dead and h.dedicated]
            if len(busy) >= 2:
                time.sleep(1.0)     # let the gang get into an epoch
                try:
                    os.kill(busy[0].proc.pid, signal.SIGKILL)
                    killed.set()
                except OSError:     # won the race with completion
                    pass
                return
            time.sleep(0.1)

    th = None
    if kill:
        th = threading.Thread(target=killer, daemon=True)
        th.start()
    t = ElasticTrainer(
        _epoch_loop(LIVE_EPOCHS, LIVE_EPOCH_S),
        scaling_config=ScalingConfig(num_workers=2, min_workers=1),
        failure_config=FailureConfig(max_failures=0),
        run_name=run_name)
    t0 = time.perf_counter()
    res = t.fit(timeout=180)
    wall = time.perf_counter() - t0
    if th is not None:
        th.join(timeout=30)
    st = t.stats()
    epochs = [r["epoch"] for r in res.history]
    return {
        "wall_s": round(wall, 2),
        "final_epoch": res.metrics["epoch"],
        "gang_losses": st["gang_losses"],
        "failures": st["failures"],
        "ckpt_replications": st.get("ckpt_replications", 0),
        "epochs_monotone": epochs == sorted(epochs),
        "kill_landed": killed.is_set(),
    }


def live_sigkill_bench() -> dict:
    """A real gang, one member SIGKILLed mid-allreduce, vs an unkilled
    control: the membership loss must cost recovery time, never
    progress or a ``max_failures`` budget unit."""
    control = _run_fit("bench-train-control", kill=False)
    chaos = _run_fit("bench-train-sigkill", kill=True)
    slo = {
        "kill_landed": chaos["kill_landed"],
        "completed": chaos["final_epoch"] == LIVE_EPOCHS,
        "gang_loss_typed": chaos["gang_losses"] >= 1,
        "zero_failure_burn": chaos["failures"] == 0,
        "epochs_monotone": chaos["epochs_monotone"],
    }
    return {"control": control, "sigkill": chaos,
            "recovery_overhead_s": round(
                chaos["wall_s"] - control["wall_s"], 2),
            "slo": slo, "slo_pass": all(slo.values())}


def main():
    # invariant: the SLO record exists before anything can hang
    diurnal = _smoke_first()

    if os.environ.get("RT_BENCH_FORCE_SKIP") == "1":
        print(json.dumps({
            "metric": "train live sigkill SKIPPED "
                      "(RT_BENCH_FORCE_SKIP)",
            "value": 0.0, "unit": "x", "vs_baseline": 0.0,
            "status": "skipped"}), flush=True)
        _write_record(diurnal, live={"status": "skipped"})
        return

    import ray_tpu
    live = None
    err = ""
    try:
        # tight collective timeout at INIT so pre-spawned pool workers
        # bake it in: the SIGKILLed peer must surface in seconds
        ray_tpu.init(resources={"CPU": 8, "memory": 8}, num_workers=4,
                     system_config={"train_collective_timeout_s": 8.0})
        live = live_sigkill_bench()
    except Exception as e:   # noqa: BLE001 — record, don't die
        err = f"{type(e).__name__}: {e}"
    finally:
        try:
            ray_tpu.shutdown()
        except Exception:   # noqa: BLE001
            pass

    _write_record(diurnal, live, error=err)
    if live is None:
        print(json.dumps({
            "metric": f"train live sigkill FAILED [{err[:200]}]",
            "value": -1.0, "unit": "x", "vs_baseline": 0.0,
            "status": "live_failed"}), flush=True)
        return
    ch, ct = live["sigkill"], live["control"]
    print(json.dumps({
        "metric": f"train live: SIGKILL mid-allreduce recovered in "
                  f"+{live['recovery_overhead_s']}s over the "
                  f"{ct['wall_s']}s control — {ch['gang_losses']} gang "
                  f"loss, {ch['failures']} failures burned, epoch "
                  f"{ch['final_epoch']}/{LIVE_EPOCHS} committed"
                  + ("" if live["slo_pass"] else " [LIVE SLO FAIL]"),
        "value": live["recovery_overhead_s"],
        "unit": "s",
        "vs_baseline": 1.0 if live["slo_pass"] else 0.0,
    }))


if __name__ == "__main__":
    if "--smoke" in sys.argv:
        _emit_smoke()
    else:
        main()
