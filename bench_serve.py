"""Serve request-plane benchmark: diurnal scale, rolling updates,
batching, shedding.

Four experiments, mirroring bench.py's smoke-first discipline (a JSON
record always lands, even if the live cluster hangs):

- **diurnal** (smoke stage, disposable subprocess): the 1k-node
  simulated ``serve_diurnal`` campaign — a cosine day/night arrival
  curve with chaos faults — run twice, single-router vs 8-sharded
  routers, same seed.  The SLO report checks the sharding bar (sharded
  accepted QPS >= 3x single at equal-or-better p99), zero
  accepted-request loss, and that elastic capacity loans fired and
  reclaimed in well under a cold boot.  Written to ``SERVE_r18.json``.
- **rolling** (smoke stage): the 1k-node ``serve_rolling_update``
  campaign fires a weight rollout at t=75s — the diurnal peak — and
  must SEAL it: every replica flipped, zero accepted-request loss,
  run-level p99 no worse than 1.25x a control run without the rollout,
  no mixed-version session, and the whole run replays bit-identically.
- **rolling live**: a 16-replica deployment hot-swapped via
  ``versioning.rollout`` under closed-loop traffic (0 drops required,
  per-replica flip downtime under one health-probe period) against a
  cold restart (delete + redeploy) of the same deployment, which drops
  every in-flight and boot-window request.
- **batching**: a model that admits ONE inference stream (a lock around
  a fixed ~8 ms compute step) served unbatched vs through
  ``@serve.batch`` — the batcher amortizes the per-invocation cost
  across coalesced requests, so batched throughput must be >= 2x
  unbatched.
- **overload**: the HTTP ingress at ~2x sustainable load (16 closed-loop
  clients against 4 replica slots + a queue of 8).  Admission control
  must SHED the excess (503 + Retry-After) while the p99 latency of the
  ACCEPTED requests stays bounded by queue depth, not by offered load.

Prints one JSON line per stage (smoke, then the live headline) and
writes the full round record to ``SERVE_r18.json``.
"""

import json
import os
import sys
import threading
import time

N_REQUESTS = 160        # per throughput run
STEP_S = 0.008          # per-invocation model cost
HTTP_SECONDS = 2.5      # overload measurement window
HTTP_CLIENTS = 16

SIM_NODES = 1000
SIM_SEED = 3
SIM_FAULTS = 12
SIM_DURATION = 150.0
SHARD_CONFIGS = (1, 8)
ROLL_T = 75.0           # rollout start: the diurnal peak
ROLL_FAULTS = 1         # chaos alongside the mid-peak rollout
ROLL_REPLICAS = 16      # live hot-swap deployment size

RECORD = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "SERVE_r18.json")


# -- diurnal sim campaign (the smoke stage) -----------------------------------

def diurnal_bench() -> dict:
    """1k-node serve_diurnal campaign, single-router vs sharded, same
    seed/faults — the only variable is ``serve_router_shards``."""
    from ray_tpu.sim import run_campaign
    from ray_tpu.sim.serve import SimServeParams

    runs = {}
    for shards in SHARD_CONFIGS:
        r = run_campaign(
            SIM_NODES, seed=SIM_SEED, campaign="serve_diurnal",
            faults=SIM_FAULTS, duration=SIM_DURATION,
            serve={"params": SimServeParams(num_shards=shards)})
        assert r.ok, (shards, r.violations)
        runs[shards] = r.stats["serve"]
    single, sharded = runs[SHARD_CONFIGS[0]], runs[SHARD_CONFIGS[-1]]
    gain = sharded["accepted"] / max(single["accepted"], 1)
    slo = {
        "accepted_qps_gain": round(gain, 2),
        "qps_gain_ok": sharded["accepted"] >= 3 * single["accepted"],
        "p99_ok": sharded["p99_s"] <= single["p99_s"],
        # conservation: every admitted request completed (death requeues
        # count as redispatched, never as loss)
        "zero_accepted_loss": (
            sharded["accepted"] == sharded["completed"]
            and sharded["outstanding"] == 0),
        "loans_fired": (sharded["loans_total"] > 0
                        and sharded["reclaims_total"] > 0),
        # a reclaimed loaner is batch capacity again in under the time a
        # cold replacement node would still be booting
        "reclaim_beats_cold_start": (
            0.0 < sharded["mean_reclaim_s"] < sharded["cold_start_s"]),
    }
    return {
        "nodes": SIM_NODES, "seed": SIM_SEED, "faults": SIM_FAULTS,
        "duration_s": SIM_DURATION,
        "single_router": single, "sharded_router": sharded,
        "slo": slo, "slo_pass": all(slo.values()),
    }


def rolling_sim_bench() -> dict:
    """1k-node ``serve_rolling_update`` campaign with the rollout fired
    mid-peak, run twice (bit-identical replay) plus a no-rollout
    control run for the p99-flat comparison."""
    from ray_tpu.sim import run_campaign
    from ray_tpu.versioning import phases

    sched = [(ROLL_T, "rollout",
              {"artifact": "w-r18", "probe_fail_at": -1})]
    kw = dict(seed=SIM_SEED, campaign="serve_rolling_update",
              faults=ROLL_FAULTS, duration=SIM_DURATION)
    r1 = run_campaign(SIM_NODES, schedule=sched, **kw)
    r2 = run_campaign(SIM_NODES, schedule=sched, **kw)
    ctl = run_campaign(SIM_NODES, schedule=[], **kw)
    assert r1.ok and ctl.ok, (r1.violations, ctl.violations)

    ro = r1.stats["rollout"]["per_rollout"][0]
    sv, cv = r1.stats["serve"], ctl.stats["serve"]
    slo = {
        "sealed_mid_peak": (ro["phase"] == phases.SEALED
                            and 0 < ro["flipped"] == ro["replicas"]),
        "zero_accepted_loss": (sv["accepted"] == sv["completed"]
                               and sv["outstanding"] == 0),
        # run-level p99 against the no-rollout control (the latency
        # histogram quantizes to bucket edges, so the during-flip
        # delta cannot resolve ratios under 1.5x — the run-level
        # figure can, and must stay flat)
        "p99_flat": sv["p99_s"] <= 1.25 * cv["p99_s"],
        "replay_bit_identical": r1.trace_hash == r2.trace_hash,
        "no_mixed_version_session":
            r1.stats["rollout"]["mixed_served"] == 0,
    }
    return {
        "nodes": SIM_NODES, "seed": SIM_SEED, "faults": ROLL_FAULTS,
        "duration_s": SIM_DURATION, "rollout_at_s": ROLL_T,
        "rollout": {k: ro[k] for k in
                    ("phase", "flipped", "replicas", "pre_p99_s",
                     "during_p99_s", "seconds", "error")},
        "pin_migrations": r1.stats["rollout"]["migrations"],
        "p99_s": sv["p99_s"], "control_p99_s": cv["p99_s"],
        "accepted": sv["accepted"], "completed": sv["completed"],
        "trace_hash": r1.trace_hash,
        "slo": slo, "slo_pass": all(slo.values()),
    }


def _emit_smoke() -> None:
    """The --smoke entry: run the diurnal pair and the rolling-update
    campaign in this disposable subprocess and print exactly one JSON
    line."""
    d = diurnal_bench()
    r = rolling_sim_bench()
    bad = ([k for k, v in d["slo"].items() if not v]
           + [k for k, v in r["slo"].items() if not v])
    flags = "" if not bad else " [SLO FAIL: " + ", ".join(bad) + "]"
    print(json.dumps({
        "metric": f"serve diurnal 1k-node sim: {SHARD_CONFIGS[-1]}-shard "
                  f"accepted {d['slo']['accepted_qps_gain']}x single-"
                  f"router at p99 {d['sharded_router']['p99_s']}s vs "
                  f"{d['single_router']['p99_s']}s; mid-peak rollout "
                  f"{r['rollout']['phase']} {r['rollout']['flipped']}/"
                  f"{r['rollout']['replicas']} at p99 {r['p99_s']}s vs "
                  f"control {r['control_p99_s']}s" + flags,
        "value": d["slo"]["accepted_qps_gain"],
        "unit": "x",
        "vs_baseline": d["slo"]["accepted_qps_gain"],
        "status": "smoke",
        "diurnal": d,
        "rolling": r,
    }), flush=True)


def _smoke_first() -> tuple[dict | None, dict | None]:
    """Run the sim stages in a subprocess (a hung backend cannot eat
    the record), print their JSON line, and seed SERVE_r18.json so the
    round's record exists before the live cluster starts."""
    import subprocess
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    err = ""
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--smoke"],
            capture_output=True, text=True, timeout=600, env=env)
        lines = [ln for ln in proc.stdout.splitlines() if ln.strip()]
        if proc.returncode == 0 and lines:
            print(lines[-1], flush=True)
            record = json.loads(lines[-1])
            _write_record(record.get("diurnal"), record.get("rolling"),
                          live=None)
            return record.get("diurnal"), record.get("rolling")
        err = f"rc={proc.returncode}: {proc.stderr.strip()[-300:]}"
    except subprocess.TimeoutExpired:
        err = "smoke subprocess exceeded 600s"
    print(json.dumps({
        "metric": f"serve sim smoke FAILED [{err}]",
        "value": -1.0, "unit": "x", "vs_baseline": 0.0,
        "status": "smoke_failed"}), flush=True)
    _write_record(None, None, live=None, error=err)
    return None, None


def _write_record(diurnal, rolling, live, error: str = "") -> None:
    doc = {"format": "ray_tpu-serve-bench/1", "round": 18,
           "diurnal": diurnal, "rolling": rolling, "live": live}
    if error:
        doc["error"] = error
    with open(RECORD, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")


# -- live experiments ---------------------------------------------------------

def bench_rolling() -> dict:
    """Hot-swap a live 16-replica deployment under closed-loop traffic
    and compare against a cold restart of the same deployment.  The
    hot swap must drop nothing and keep each replica's out-of-routing
    window under one health-probe period; the cold restart drops every
    request that touches the teardown/boot window."""
    import ray_tpu
    from ray_tpu import serve, versioning
    from ray_tpu.common.config import get_config
    from ray_tpu.versioning import phases

    def _deploy():
        @serve.deployment(num_replicas=ROLL_REPLICAS)
        class Model:
            def __init__(self):
                self.tag = "cold"

            def __call__(self, x):
                return self.tag

            def reload(self, artifact):
                self.tag = bytes(artifact).decode()

        return serve.run(Model.bind())

    def _measure(swap) -> dict:
        box = [_deploy()]
        ray_tpu.get([box[0].remote(i) for i in range(32)], timeout=120)
        stop = threading.Event()
        drops: list = []
        served: list = []
        lock = threading.Lock()

        def traffic():
            while not stop.is_set():
                try:
                    r = ray_tpu.get(box[0].remote(0), timeout=30)
                    with lock:
                        served.append(r)
                except Exception as e:  # noqa: BLE001 — count as drop
                    with lock:
                        drops.append(type(e).__name__)

        threads = [threading.Thread(target=traffic, daemon=True)
                   for _ in range(4)]
        for t in threads:
            t.start()
        t0 = time.perf_counter()
        extra = swap(box)
        wall = time.perf_counter() - t0
        time.sleep(0.5)                 # catch straggler drops
        stop.set()
        for t in threads:
            t.join(timeout=30)
        serve.delete()
        return {"swap_wall_s": round(wall, 2), "dropped": len(drops),
                "served_during": len(served), **extra}

    def hot(box) -> dict:
        s = versioning.rollout(b"hot-v2", artifact_label="hot-v2")
        return {"phase": s["phase"], "flipped": s["flipped"],
                "max_flip_downtime_s": s["max_flip_downtime_s"]}

    def cold(box) -> dict:
        serve.delete()
        box[0] = _deploy()
        return {}

    hot_r = _measure(hot)
    cold_r = _measure(cold)
    probe_s = get_config().health_check_period_ms / 1000.0
    slo = {
        "hot_sealed": (hot_r.get("phase") == phases.SEALED
                       and hot_r.get("flipped") == ROLL_REPLICAS),
        "hot_zero_drops": hot_r["dropped"] == 0,
        "cold_drops": cold_r["dropped"] > 0,
        "flip_downtime_under_probe_period":
            hot_r.get("max_flip_downtime_s", probe_s) < probe_s,
    }
    return {"replicas": ROLL_REPLICAS, "hot_swap": hot_r,
            "cold_restart": cold_r,
            "health_probe_period_s": probe_s,
            "slo": slo, "slo_pass": all(slo.values())}


def _throughput(handle, n=N_REQUESTS) -> float:
    import ray_tpu
    t0 = time.perf_counter()
    out = ray_tpu.get([handle.remote(i) for i in range(n)], timeout=120)
    dt = time.perf_counter() - t0
    assert out == list(range(n)), "bad results"
    return n / dt


def bench_batching() -> tuple[float, float]:
    from ray_tpu import serve

    @serve.deployment(num_replicas=1, max_ongoing_requests=16)
    class Unbatched:
        def __init__(self):
            self._lock = threading.Lock()

        def __call__(self, x):
            with self._lock:            # one inference stream
                time.sleep(STEP_S)
            return x

    @serve.deployment(num_replicas=1, max_ongoing_requests=16)
    class Batched:
        @serve.batch(max_batch_size=16, batch_wait_timeout_s=0.005)
        def __call__(self, items):
            time.sleep(STEP_S)          # one step serves the batch
            return items

    handle = serve.run(Unbatched.bind())
    _throughput(handle, 32)             # warmup
    unbatched = _throughput(handle)
    serve.delete("default")

    handle = serve.run(Batched.bind())
    _throughput(handle, 32)             # warmup
    batched = _throughput(handle)
    serve.delete("default")
    return unbatched, batched


def bench_overload() -> dict:
    from urllib import error, request as urlreq

    from ray_tpu import serve

    @serve.deployment(num_replicas=1, max_ongoing_requests=4,
                      max_queued_requests=8)
    class Busy:
        def __call__(self, request):
            time.sleep(0.02)
            return "ok"

    serve.run(Busy.bind(), route_prefix="/bench")
    url = f"{serve.http_address()}/bench"
    ok_lat: list[float] = []
    shed = [0]
    retry_after = [0]
    lock = threading.Lock()
    stop = time.perf_counter() + HTTP_SECONDS

    def client():
        while time.perf_counter() < stop:
            t0 = time.perf_counter()
            try:
                with urlreq.urlopen(url, timeout=30) as r:
                    r.read()
                    code = r.status
            except error.HTTPError as e:
                e.read()
                code = e.code
                if e.headers.get("Retry-After"):
                    with lock:
                        retry_after[0] += 1
            dt = (time.perf_counter() - t0) * 1e3
            with lock:
                if code == 200:
                    ok_lat.append(dt)
                elif code == 503:
                    shed[0] += 1
            if code == 503:
                # brief backoff so the closed loop offers ~2x capacity
                # instead of a hot retry storm
                time.sleep(0.05)

    threads = [threading.Thread(target=client)
               for _ in range(HTTP_CLIENTS)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    serve.delete("default")

    ok_lat.sort()
    n = len(ok_lat)
    total = n + shed[0]
    return {
        "qps": n / wall,
        "p50_ms": ok_lat[n // 2] if n else 0.0,
        "p99_ms": ok_lat[min(n - 1, int(n * 0.99))] if n else 0.0,
        "shed_rate": shed[0] / total if total else 0.0,
        "retry_after_on_all_503s": retry_after[0] == shed[0],
    }


def main():
    # invariant: the SLO record exists before anything can hang
    diurnal, rolling = _smoke_first()

    import ray_tpu
    # 16-replica hot-swap needs room for the replica actors plus the
    # controller/ingress helpers
    ray_tpu.init(resources={"CPU": 24, "memory": 16}, num_workers=20)
    try:
        roll = bench_rolling()
        unbatched, batched = bench_batching()
        http = bench_overload()
    finally:
        from ray_tpu import serve
        serve.shutdown()
        ray_tpu.shutdown()

    speedup = batched / unbatched
    live = {
        "rolling": roll,
        "unbatched_rps": round(unbatched, 1),
        "batched_rps": round(batched, 1),
        "batching_speedup": round(speedup, 2),
        "overload": {k: round(v, 3) if isinstance(v, float) else v
                     for k, v in http.items()},
    }
    _write_record(diurnal, rolling, live)
    hs, cs = roll["hot_swap"], roll["cold_restart"]
    print(json.dumps({
        "metric": f"serve: {roll['replicas']}-replica hot-swap "
                  f"{hs['dropped']} drops (flip downtime "
                  f"{hs.get('max_flip_downtime_s', -1):.3f} s) vs "
                  f"cold restart {cs['dropped']} drops"
                  + ("" if roll["slo_pass"] else " [ROLLING SLO FAIL]")
                  + f"; unbatched {unbatched:.0f} | batched "
                  f"{batched:.0f} req/s"
                  + ("" if speedup >= 2 else " [SPEEDUP < 2x]")
                  + f"; 2x-overload ingress {http['qps']:.0f} QPS, "
                  f"p50 {http['p50_ms']:.0f} ms, "
                  f"p99 {http['p99_ms']:.0f} ms, "
                  f"shed {http['shed_rate'] * 100:.0f}%"
                  + ("" if http["retry_after_on_all_503s"]
                     else " [503 MISSING Retry-After]"),
        "value": round(batched, 1),
        "unit": "req/s",
        "vs_baseline": round(speedup, 2),
    }))


if __name__ == "__main__":
    if "--smoke" in sys.argv:
        _emit_smoke()
    else:
        main()
