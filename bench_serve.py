"""Serve request-plane benchmark: micro-batching and overload shedding.

Two experiments against a live single-node cluster:

- **batching**: a model that admits ONE inference stream (a lock around
  a fixed ~8 ms compute step) served unbatched vs through
  ``@serve.batch`` — the batcher amortizes the per-invocation cost
  across coalesced requests, so batched throughput must be >= 2x
  unbatched.
- **overload**: the HTTP ingress at ~2x sustainable load (16 closed-loop
  clients against 4 replica slots + a queue of 8).  Admission control
  must SHED the excess (503 + Retry-After) while the p99 latency of the
  ACCEPTED requests stays bounded by queue depth, not by offered load.

Prints exactly one JSON line.
"""

import json
import threading
import time

N_REQUESTS = 160        # per throughput run
STEP_S = 0.008          # per-invocation model cost
HTTP_SECONDS = 2.5      # overload measurement window
HTTP_CLIENTS = 16


def _throughput(handle, n=N_REQUESTS) -> float:
    import ray_tpu
    t0 = time.perf_counter()
    out = ray_tpu.get([handle.remote(i) for i in range(n)], timeout=120)
    dt = time.perf_counter() - t0
    assert out == list(range(n)), "bad results"
    return n / dt


def bench_batching() -> tuple[float, float]:
    import ray_tpu
    from ray_tpu import serve

    @serve.deployment(num_replicas=1, max_ongoing_requests=16)
    class Unbatched:
        def __init__(self):
            self._lock = threading.Lock()

        def __call__(self, x):
            with self._lock:            # one inference stream
                time.sleep(STEP_S)
            return x

    @serve.deployment(num_replicas=1, max_ongoing_requests=16)
    class Batched:
        @serve.batch(max_batch_size=16, batch_wait_timeout_s=0.005)
        def __call__(self, items):
            time.sleep(STEP_S)          # one step serves the batch
            return items

    handle = serve.run(Unbatched.bind())
    _throughput(handle, 32)             # warmup
    unbatched = _throughput(handle)
    serve.delete("default")

    handle = serve.run(Batched.bind())
    _throughput(handle, 32)             # warmup
    batched = _throughput(handle)
    serve.delete("default")
    return unbatched, batched


def bench_overload() -> dict:
    from urllib import error, request as urlreq

    from ray_tpu import serve

    @serve.deployment(num_replicas=1, max_ongoing_requests=4,
                      max_queued_requests=8)
    class Busy:
        def __call__(self, request):
            time.sleep(0.02)
            return "ok"

    serve.run(Busy.bind(), route_prefix="/bench")
    url = f"{serve.http_address()}/bench"
    ok_lat: list[float] = []
    shed = [0]
    retry_after = [0]
    lock = threading.Lock()
    stop = time.perf_counter() + HTTP_SECONDS

    def client():
        while time.perf_counter() < stop:
            t0 = time.perf_counter()
            try:
                with urlreq.urlopen(url, timeout=30) as r:
                    r.read()
                    code = r.status
            except error.HTTPError as e:
                e.read()
                code = e.code
                if e.headers.get("Retry-After"):
                    with lock:
                        retry_after[0] += 1
            dt = (time.perf_counter() - t0) * 1e3
            with lock:
                if code == 200:
                    ok_lat.append(dt)
                elif code == 503:
                    shed[0] += 1
            if code == 503:
                # brief backoff so the closed loop offers ~2x capacity
                # instead of a hot retry storm
                time.sleep(0.05)

    threads = [threading.Thread(target=client)
               for _ in range(HTTP_CLIENTS)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    serve.delete("default")

    ok_lat.sort()
    n = len(ok_lat)
    total = n + shed[0]
    return {
        "qps": n / wall,
        "p50_ms": ok_lat[n // 2] if n else 0.0,
        "p99_ms": ok_lat[min(n - 1, int(n * 0.99))] if n else 0.0,
        "shed_rate": shed[0] / total if total else 0.0,
        "retry_after_on_all_503s": retry_after[0] == shed[0],
    }


def main():
    import ray_tpu
    ray_tpu.init(resources={"CPU": 12, "memory": 8}, num_workers=6)
    try:
        unbatched, batched = bench_batching()
        http = bench_overload()
    finally:
        from ray_tpu import serve
        serve.shutdown()
        ray_tpu.shutdown()

    speedup = batched / unbatched
    print(json.dumps({
        "metric": f"serve: unbatched {unbatched:.0f} | batched "
                  f"{batched:.0f} req/s"
                  + ("" if speedup >= 2 else " [SPEEDUP < 2x]")
                  + f"; 2x-overload ingress {http['qps']:.0f} QPS, "
                  f"p50 {http['p50_ms']:.0f} ms, "
                  f"p99 {http['p99_ms']:.0f} ms, "
                  f"shed {http['shed_rate'] * 100:.0f}%"
                  + ("" if http["retry_after_on_all_503s"]
                     else " [503 MISSING Retry-After]"),
        "value": round(batched, 1),
        "unit": "req/s",
        "vs_baseline": round(speedup, 2),
    }))


if __name__ == "__main__":
    main()
