"""Control-plane scaling: cluster task throughput vs number of agent
nodes (VERDICT r04 next-step #4; reference bar: multi-node scheduling
throughput, BASELINE.md row 5).

Two modes per cluster size:

- ``head_dispatch``: the driver submits tiny tasks; every lease rides
  the head's scheduler and every frame transits its RPC server — this
  curve shows where the head-centric control plane saturates.
- ``agent_local``: one fan-out parent per agent node; children lease
  on their own machines through the autonomy fast path, so the head
  sees only batched agent_sync calls — this curve shows what
  raylet-per-host buys back.

Writes one JSON line; run standalone:
    python bench_scaling.py [--agents 1,2,4,8] [--tasks 240]

Caveat recorded in the artifact: everything shares one small machine
(agents are real processes-over-TCP but compete for the same cores),
so absolute numbers are lower bounds and the SHAPE of the curves is
the signal.
"""

import argparse
import json
import os
import time


def _run_cluster(n_agents: int, n_tasks: int) -> dict:
    import ray_tpu
    from ray_tpu.runtime.head import HeadNode
    from ray_tpu.runtime.node_agent import NodeAgent

    head = HeadNode(resources={"CPU": 2, "memory": 4}, num_workers=2)
    agents = []
    for i in range(n_agents):
        agents.append(NodeAgent(
            head.address,
            resources={"CPU": 2, "memory": 4, f"slot{i}": 2},
            num_workers=2))
    deadline = time.monotonic() + 120
    while len(ray_tpu.nodes()) != n_agents + 1:
        assert time.monotonic() < deadline, "cluster never formed"
        time.sleep(0.1)
    out = {}
    try:
        @ray_tpu.remote
        def noop():
            return None

        @ray_tpu.remote
        def fanout(n):
            refs = [noop.remote() for _ in range(n)]
            ray_tpu.get(refs, timeout=300)
            return n

        # warmup: boot every node's workers + fn caches
        ray_tpu.get([noop.remote() for _ in range(4 * (n_agents + 1))],
                    timeout=120)
        for i in range(n_agents):
            p = fanout.options(resources={"CPU": 1, f"slot{i}": 1})
            ray_tpu.get(p.remote(2), timeout=120)

        # mode 1: driver-submitted tiny tasks, head-placed
        t0 = time.perf_counter()
        ray_tpu.get([noop.remote() for _ in range(n_tasks)],
                    timeout=600)
        out["head_dispatch_tasks_per_s"] = round(
            n_tasks / (time.perf_counter() - t0), 1)

        # mode 2: one fan-out parent per agent, children lease locally
        per = n_tasks // max(n_agents, 1)
        t0 = time.perf_counter()
        parents = [
            fanout.options(resources={"CPU": 1, f"slot{i}": 1}).remote(
                per) for i in range(n_agents)]
        ray_tpu.get(parents, timeout=600)
        out["agent_local_tasks_per_s"] = round(
            (per * n_agents) / (time.perf_counter() - t0), 1)
    finally:
        for a in agents:
            a.stop()
        head.stop()
    return out


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--agents", default="1,2,4,8")
    ap.add_argument("--tasks", type=int, default=240)
    args = ap.parse_args()
    sizes = [int(s) for s in args.agents.split(",")]
    curve = {}
    for n in sizes:
        curve[str(n)] = _run_cluster(n, args.tasks)
    result = {
        "metric": "cluster_task_throughput_vs_agent_count",
        "unit": "tasks/s",
        "tasks_per_point": args.tasks,
        "hardware": {"nproc": os.cpu_count(),
                     "note": "single machine; agents are real "
                             "TCP-linked processes sharing the cores "
                             "— curve shape is the signal"},
        "curve": curve,
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
