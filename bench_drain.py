"""Graceful-drain benchmark: drain-to-empty latency under load.

Measures the DRAINING state machine end to end: a worker node running a
stream of short tasks receives a drain notice; the clock runs from
``drain_node`` returning (node already masked, zero new leases) to the
monitor declaring it empty and removing it — running tasks finishing,
queued work resubmitting elsewhere, and sole-copy objects migrating all
land inside the window.  ``vs_baseline`` compares against the blunt
alternative (killing the node and letting every in-flight task burn a
retry): the deadline a drain saves is the task tail it did NOT re-run.

Prints exactly one JSON line.
"""

import json
import time

import numpy as np

ROUNDS = 5
N_TASKS = 32
TASK_S = 0.05


def _one_round(ray_tpu, cluster, work):
    node = cluster.add_node(resources={"CPU": 4, "memory": 4},
                            num_workers=2)
    refs = [work.remote(i) for i in range(N_TASKS)]
    time.sleep(4 * TASK_S)              # the node is mid-backlog
    t0 = time.perf_counter()
    cluster.drain_node(node, reason="bench", deadline_s=60.0)
    fin = cluster.wait_for_drain(node, timeout=120)
    elapsed_ms = (time.perf_counter() - t0) * 1e3
    assert fin["outcome"] == "drained", fin
    out = ray_tpu.get(refs, timeout=120)
    assert out == list(range(N_TASKS))
    return elapsed_ms


def main():
    import ray_tpu
    from ray_tpu.api import _get_runtime

    ray_tpu.init(resources={"CPU": 4, "memory": 4}, num_workers=2)
    try:
        cluster = _get_runtime().cluster

        @ray_tpu.remote(num_cpus=1)
        def work(i):
            time.sleep(TASK_S)
            return i

        _one_round(ray_tpu, cluster, work)          # warm the pools
        times = [_one_round(ray_tpu, cluster, work)
                 for _ in range(ROUNDS)]
    finally:
        ray_tpu.shutdown()

    p50 = float(np.percentile(times, 50))
    # kill-instead-of-drain re-runs the node's in-flight tasks: with
    # ~half the backlog on the drained node, that is the work a drain
    # keeps instead of burning (lower bound; ignores retry scheduling)
    naive_ms = (N_TASKS / 2) * TASK_S * 1e3
    print(json.dumps({
        "metric": f"p50 drain-to-empty: node running {N_TASKS} short "
                  f"tasks ({int(TASK_S * 1e3)}ms each), zero task "
                  "failures",
        "value": round(p50, 3),
        "unit": "ms",
        "vs_baseline": round(naive_ms / p50, 2),    # x vs kill+retry
    }))


if __name__ == "__main__":
    main()
