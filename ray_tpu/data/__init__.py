"""ray_tpu.data — distributed datasets over object-store blocks.

Reference parity: ``ray.data`` (``python/ray/data/``) — a ``Dataset`` is
a list of object-store block references plus metadata; transforms
(``map/map_batches/filter/flat_map/repartition/random_shuffle/sort``)
run as tasks over blocks; ``groupby`` aggregations run as per-block
partials merged in a worker-side tree; ``read_text/read_csv`` map files
to blocks and ``write_json`` writes one part per block; consumers
(``take/count/iter_batches/split``) resolve refs (SURVEY.md §1 layer
14, §2.2; mount empty).

TPU-first: blocks are numpy-friendly lists or arrays living in the
shared-memory arena (zero-copy into workers), ``map_batches`` is the
primary compute hook so user code sees whole blocks (feed the MXU big
batches, not Python-loop rows), and ``split`` hands aligned shards to
``ray_tpu.train`` workers.
"""

from .aggregate import GroupedDataset, read_csv, read_text
from .block import (ColumnBlock, iter_block_files, read_block_file,
                    write_block_file, write_blocks)
from .dataset import Dataset, from_items, from_numpy, range  # noqa: A004
from .streaming import (DataStream, stream_block_files, stream_blocks,
                        stream_from_items, stream_range)

__all__ = ["ColumnBlock", "DataStream", "Dataset", "GroupedDataset",
           "from_items", "from_numpy", "iter_block_files", "range",
           "read_block_file", "read_csv", "read_text",
           "stream_block_files", "stream_blocks", "stream_from_items",
           "stream_range", "write_block_file", "write_blocks"]
