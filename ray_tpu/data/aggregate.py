"""Grouped aggregation + file IO for ray_tpu.data.

Reference parity: ``ray.data``'s ``Dataset.groupby(key).count()/sum()/
mean()/aggregate(AggregateFn)`` runs a distributed aggregation
(per-block partial accumulation, then a merge stage), and its read/write
layer maps files to blocks (``read_text``/``read_csv``/
``Dataset.write_json`` — ``python/ray/data/grouped_data.py``,
``read_api.py``; SURVEY.md §1 layer 14; mount empty).

Shapes here:
- partial aggregation is one task per block (dict: key -> accumulator),
- partials merge on workers in a binary tree (the driver never funnels
  the full key space),
- the result is a normal ``Dataset`` of ``(key, value)`` rows sorted by
  key, so further transforms compose.
"""

from __future__ import annotations

import builtins
import csv
import json
import os
from typing import Any, Callable


from .dataset import _api


# -- task bodies (run in workers) --------------------------------------------

def _partial_agg(key_fn, init, accumulate, block):
    out: dict = {}
    for row in block:
        k = key_fn(row) if key_fn is not None else row
        if k not in out:
            out[k] = init(k)
        out[k] = accumulate(out[k], row)
    return out


def _merge_partials(merge, a: dict, b: dict) -> dict:
    out = dict(a)
    for k, v in b.items():
        out[k] = merge(out[k], v) if k in out else v
    return out


def _read_text_file(path: str):
    with open(path, "r", encoding="utf-8") as f:
        return [line.rstrip("\r\n") for line in f]


def _read_csv_file(path: str):
    with open(path, "r", encoding="utf-8", newline="") as f:
        return [dict(row) for row in csv.DictReader(f)]


def _write_json_block(block, path: str):
    rows = [r.tolist() if hasattr(r, "tolist") else r for r in block]
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(rows, f)
    os.replace(tmp, path)
    return path


# -- grouped dataset ---------------------------------------------------------

class GroupedDataset:
    """What ``Dataset.groupby(key_fn)`` returns; finish with an
    aggregation."""

    def __init__(self, dataset, key_fn: Callable | None):
        self._ds = dataset
        self._key_fn = key_fn

    def aggregate(self, *, init: Callable[[Any], Any],
                  accumulate: Callable[[Any, Any], Any],
                  merge: Callable[[Any, Any], Any]):
        """General distributed aggregation (the AggregateFn shape):
        ``init(key)`` makes an accumulator, ``accumulate(acc, row)``
        folds a row in, ``merge(a, b)`` combines two partials."""
        from .dataset import Dataset, _from_rows
        rt = _api()
        partial = rt.remote(_partial_agg)
        partials = [partial.remote(self._key_fn, init, accumulate, b)
                    for b in self._ds._blocks]
        merger = rt.remote(_merge_partials)
        while len(partials) > 1:        # binary merge tree, on workers
            nxt = [merger.remote(merge, partials[i], partials[i + 1])
                   for i in builtins.range(0, len(partials) - 1, 2)]
            if len(partials) % 2:
                nxt.append(partials[-1])
            partials = nxt
        final = rt.get(partials[0], timeout=300) if partials else {}
        try:
            rows = sorted(final.items())
        except TypeError:       # mixed/unorderable keys: stable fallback
            rows = sorted(final.items(), key=lambda kv: repr(kv[0]))
        return _from_rows(rows, max(min(8, len(rows)), 1))

    def count(self):
        return self.aggregate(init=lambda k: 0,
                              accumulate=lambda acc, row: acc + 1,
                              merge=lambda a, b: a + b)

    def sum(self, fn: Callable | None = None):
        take = fn if fn is not None else (lambda row: row)
        return self.aggregate(init=lambda k: 0,
                              accumulate=lambda acc, row: acc + take(row),
                              merge=lambda a, b: a + b)

    def mean(self, fn: Callable | None = None):
        take = fn if fn is not None else (lambda row: row)
        sums = self.aggregate(
            init=lambda k: (0, 0),
            accumulate=lambda acc, row: (acc[0] + take(row), acc[1] + 1),
            merge=lambda a, b: (a[0] + b[0], a[1] + b[1]))
        return sums.map(lambda kv: (kv[0], kv[1][0] / kv[1][1]))


# -- file IO -----------------------------------------------------------------

def read_text(paths: str | list[str]):
    """One block per file, rows are lines."""
    return _read_files(paths, _read_text_file)


def read_csv(paths: str | list[str]):
    """One block per file, rows are header-keyed dicts."""
    return _read_files(paths, _read_csv_file)


def _read_files(paths, reader):
    from .dataset import Dataset
    rt = _api()
    if isinstance(paths, str):
        paths = [paths]
    expanded: list[str] = []
    for p in paths:
        if os.path.isdir(p):
            expanded.extend(
                full for name in sorted(os.listdir(p))
                if os.path.isfile(full := os.path.join(p, name)))
        else:
            expanded.append(p)
    if not expanded:
        raise ValueError("no input files")
    for p in expanded:
        if not os.path.isfile(p):
            raise FileNotFoundError(p)
    task = rt.remote(reader)
    return Dataset([task.remote(p) for p in expanded],
                   [-1] * len(expanded))


def write_json(dataset, directory: str) -> list[str]:
    """One ``part-NNNNN.json`` per block; returns the written paths.
    Stale parts from a previous larger write are cleared only AFTER the
    new writes all land — a failed write must not destroy the previous
    output (each part itself lands via atomic rename)."""
    rt = _api()
    os.makedirs(directory, exist_ok=True)
    writer = rt.remote(_write_json_block)
    refs = [writer.remote(b, os.path.join(directory, f"part-{i:05d}.json"))
            for i, b in enumerate(dataset._blocks)]
    written = rt.get(refs, timeout=300)
    keep = {os.path.basename(p) for p in written}
    for name in os.listdir(directory):
        if name.startswith("part-") and name.endswith(".json") \
                and name not in keep:
            os.unlink(os.path.join(directory, name))
    return written
