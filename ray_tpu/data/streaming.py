"""Streaming dataset execution: bounded-memory pipelines over generators.

Reference parity: upstream Data's streaming executor runs map stages
over blocks with bounded in-flight resources instead of materializing
every block (``python/ray/data/_internal/execution/`` — SURVEY.md §1
layer 14; mount empty).  The rebuild's shape: the SOURCE is a streaming
generator task (``num_returns="streaming"`` — the block producer pauses
on consumer backpressure), map stages are per-block tasks submitted
with a bounded window, and consumed block refs drop immediately so
reference counting reclaims them.  Peak store occupancy is
O(window + backpressure), not O(total blocks) — the property
``tests/test_streaming.py`` pins.
"""

from __future__ import annotations

from collections import deque
from typing import Any, Callable, Iterable, Iterator


def _api():
    import ray_tpu
    return ray_tpu


# adaptive-window defaults: keep roughly this many bytes of blocks in
# flight (upstream: the streaming executor's memory budget), clamped
# to a sane block-count range
_TARGET_INFLIGHT_BYTES = 32 * 1024 * 1024
_MIN_WINDOW, _MAX_WINDOW = 1, 32


class DataStream:
    """A lazy, bounded-memory block pipeline.

    Build with :func:`stream_range` / :func:`stream_from_items` /
    :func:`stream_blocks`, chain ``.map``/``.map_batches``/``.filter``,
    then drain with ``iter_blocks()`` / ``iter_rows()`` / ``take_all()``.
    Nothing executes until iteration starts.

    The in-flight window is ADAPTIVE by default (``window=None``):
    per-block size stats (plasma sizes probed before consumption,
    ``ColumnBlock.nbytes``/estimates after) feed a rolling average, and
    the window holds ``target_inflight_bytes`` of blocks in flight —
    big blocks shrink it, tiny blocks widen it (upstream: block
    metadata feeding the streaming executor's memory accounting).
    ``.window(n)`` pins a fixed count instead."""

    def __init__(self, source_fn: Callable[[], Iterable[list]],
                 stages: tuple = (), window: int | None = None,
                 target_inflight_bytes: int = _TARGET_INFLIGHT_BYTES):
        self._source_fn = source_fn
        self._stages = stages
        self._window = None if window is None else max(int(window), 1)
        self._target_bytes = max(int(target_inflight_bytes), 1)

    # -- transforms (lazy) ---------------------------------------------------
    def map(self, fn: Callable[[Any], Any]) -> "DataStream":
        return DataStream(self._source_fn,
                          self._stages + (("map", fn),), self._window,
                          self._target_bytes)

    def map_batches(self, fn: Callable[[list], list]) -> "DataStream":
        return DataStream(self._source_fn,
                          self._stages + (("map_batches", fn),),
                          self._window, self._target_bytes)

    def filter(self, fn: Callable[[Any], bool]) -> "DataStream":
        return DataStream(self._source_fn,
                          self._stages + (("filter", fn),),
                          self._window, self._target_bytes)

    def window(self, n: int) -> "DataStream":
        """Pin a fixed number of blocks in flight through the stages."""
        return DataStream(self._source_fn, self._stages, n,
                          self._target_bytes)

    def target_bytes(self, n: int) -> "DataStream":
        """Adaptive-window memory budget (bytes of blocks in flight)."""
        return DataStream(self._source_fn, self._stages, None, n)

    @staticmethod
    def _probe_size(ref) -> int | None:
        """Plasma size of an un-consumed block ref (exact, no get)."""
        try:
            from ray_tpu.api import _get_runtime
            store = getattr(_get_runtime(), "store", None)
            if store is None:
                return None
            kind, size = store.plasma_info(ref.id)
            return size if kind in ("shm", "spill") else None
        except Exception:   # noqa: BLE001 — stats only
            return None

    @staticmethod
    def _block_size(block) -> int:
        nb = getattr(block, "nbytes", None)
        if nb is not None:
            return int(nb)
        if isinstance(block, (list, tuple)) and block:
            import sys
            return len(block) * max(sys.getsizeof(block[0]), 1)
        return 1024

    # -- execution -----------------------------------------------------------
    def iter_blocks(self) -> Iterator[list]:
        """Drive the pipeline: blocks stream from the generator source,
        at most ``window`` are in the map stages at once, and each
        yielded block's refs drop before the next is requested."""
        ray = _api()
        stages = self._stages

        @ray.remote(num_returns="streaming")
        def _source(src):
            yield from src()

        @ray.remote
        def _apply(block, staged=stages):
            from .block import ColumnBlock
            for kind, fn in staged:
                if kind == "map_batches":
                    out = fn(block)
                    # columnar in, columnar out: a ColumnBlock result
                    # stays a block (don't iterate it into rows)
                    block = out if isinstance(out, ColumnBlock) \
                        else list(out)
                    continue
                rows = block.to_rows() \
                    if isinstance(block, ColumnBlock) else block
                if kind == "map":
                    block = [fn(r) for r in rows]
                else:
                    block = [r for r in rows if fn(r)]
            return block

        gen = _source.remote(self._source_fn)
        inflight: deque = deque()       # refs moving through the stages
        src_done = False
        sizes: deque = deque(maxlen=16)     # recent block size stats

        def allowed_window() -> int:
            if self._window is not None:
                return self._window
            if not sizes:
                return 2                # probe conservatively first
            avg = max(sum(sizes) // len(sizes), 1)
            return min(max(self._target_bytes // avg, _MIN_WINDOW),
                       _MAX_WINDOW)

        while inflight or not src_done:
            while not src_done and len(inflight) < allowed_window():
                try:
                    block_ref = next(gen)
                except StopIteration:
                    src_done = True
                    break
                probed = self._probe_size(block_ref)
                if probed:
                    sizes.append(probed)
                if stages:
                    inflight.append(_apply.remote(block_ref))
                    del block_ref       # the stage task owns it now
                else:
                    inflight.append(block_ref)
            if not inflight:
                break
            ref = inflight.popleft()
            block = ray.get(ref, timeout=300)
            del ref                     # consumed: reclaimable NOW
            sizes.append(self._block_size(block))
            yield block

    def iter_rows(self) -> Iterator[Any]:
        for block in self.iter_blocks():
            yield from block

    def take_all(self) -> list:
        return list(self.iter_rows())

    def count(self) -> int:
        return sum(len(b) for b in self.iter_blocks())


def stream_range(n: int, *, block_size: int = 1000,
                 window: int | None = None) -> DataStream:
    """A streaming source of ``range(n)`` in ``block_size`` blocks."""
    def source():
        for lo in range(0, n, block_size):
            yield list(range(lo, min(lo + block_size, n)))
    return DataStream(source, window=window)


def stream_from_items(items: list, *, block_size: int = 1000,
                      window: int | None = None) -> DataStream:
    items = list(items)

    def source():
        for lo in range(0, len(items), block_size):
            yield items[lo:lo + block_size]
    return DataStream(source, window=window)


def stream_blocks(make_blocks: Callable[[], Iterable[list]], *,
                  window: int | None = None) -> DataStream:
    """A streaming source from any block-yielding callable (runs INSIDE
    the generator task — e.g. read files lazily)."""
    return DataStream(make_blocks, window=window)


def stream_block_files(paths_or_dir, *,
                       window: int | None = None) -> DataStream:
    """Stream ``.rtb`` columnar block files (the read_parquet-
    equivalent local binary reader) — files are read lazily INSIDE the
    source generator task, one ColumnBlock per file, so peak memory
    follows the adaptive window, never the dataset size."""
    from .block import block_file_paths, read_block_file
    paths = block_file_paths(paths_or_dir)

    def source():
        for p in paths:
            yield read_block_file(p)
    return DataStream(source, window=window)
