"""Streaming dataset execution: bounded-memory pipelines over generators.

Reference parity: upstream Data's streaming executor runs map stages
over blocks with bounded in-flight resources instead of materializing
every block (``python/ray/data/_internal/execution/`` — SURVEY.md §1
layer 14; mount empty).  The rebuild's shape: the SOURCE is a streaming
generator task (``num_returns="streaming"`` — the block producer pauses
on consumer backpressure), map stages are per-block tasks submitted
with a bounded window, and consumed block refs drop immediately so
reference counting reclaims them.  Peak store occupancy is
O(window + backpressure), not O(total blocks) — the property
``tests/test_streaming.py`` pins.
"""

from __future__ import annotations

from collections import deque
from typing import Any, Callable, Iterable, Iterator


def _api():
    import ray_tpu
    return ray_tpu


class DataStream:
    """A lazy, bounded-memory block pipeline.

    Build with :func:`stream_range` / :func:`stream_from_items` /
    :func:`stream_blocks`, chain ``.map``/``.map_batches``/``.filter``,
    then drain with ``iter_blocks()`` / ``iter_rows()`` / ``take_all()``.
    Nothing executes until iteration starts."""

    def __init__(self, source_fn: Callable[[], Iterable[list]],
                 stages: tuple = (), window: int = 4):
        self._source_fn = source_fn
        self._stages = stages
        self._window = max(int(window), 1)

    # -- transforms (lazy) ---------------------------------------------------
    def map(self, fn: Callable[[Any], Any]) -> "DataStream":
        return DataStream(self._source_fn,
                          self._stages + (("map", fn),), self._window)

    def map_batches(self, fn: Callable[[list], list]) -> "DataStream":
        return DataStream(self._source_fn,
                          self._stages + (("map_batches", fn),),
                          self._window)

    def filter(self, fn: Callable[[Any], bool]) -> "DataStream":
        return DataStream(self._source_fn,
                          self._stages + (("filter", fn),), self._window)

    def window(self, n: int) -> "DataStream":
        """Bound the number of blocks in flight through the map stages."""
        return DataStream(self._source_fn, self._stages, n)

    # -- execution -----------------------------------------------------------
    def iter_blocks(self) -> Iterator[list]:
        """Drive the pipeline: blocks stream from the generator source,
        at most ``window`` are in the map stages at once, and each
        yielded block's refs drop before the next is requested."""
        ray = _api()
        stages = self._stages

        @ray.remote(num_returns="streaming")
        def _source(src):
            yield from src()

        @ray.remote
        def _apply(block, staged=stages):
            for kind, fn in staged:
                if kind == "map":
                    block = [fn(r) for r in block]
                elif kind == "map_batches":
                    block = list(fn(block))
                else:
                    block = [r for r in block if fn(r)]
            return block

        gen = _source.remote(self._source_fn)
        inflight: deque = deque()       # refs moving through the stages
        src_done = False
        while inflight or not src_done:
            while not src_done and len(inflight) < self._window:
                try:
                    block_ref = next(gen)
                except StopIteration:
                    src_done = True
                    break
                if stages:
                    inflight.append(_apply.remote(block_ref))
                    del block_ref       # the stage task owns it now
                else:
                    inflight.append(block_ref)
            if not inflight:
                break
            ref = inflight.popleft()
            block = ray.get(ref, timeout=300)
            del ref                     # consumed: reclaimable NOW
            yield block

    def iter_rows(self) -> Iterator[Any]:
        for block in self.iter_blocks():
            yield from block

    def take_all(self) -> list:
        return list(self.iter_rows())

    def count(self) -> int:
        return sum(len(b) for b in self.iter_blocks())


def stream_range(n: int, *, block_size: int = 1000,
                 window: int = 4) -> DataStream:
    """A streaming source of ``range(n)`` in ``block_size`` blocks."""
    def source():
        for lo in range(0, n, block_size):
            yield list(range(lo, min(lo + block_size, n)))
    return DataStream(source, window=window)


def stream_from_items(items: list, *, block_size: int = 1000,
                      window: int = 4) -> DataStream:
    items = list(items)

    def source():
        for lo in range(0, len(items), block_size):
            yield items[lo:lo + block_size]
    return DataStream(source, window=window)


def stream_blocks(make_blocks: Callable[[], Iterable[list]], *,
                  window: int = 4) -> DataStream:
    """A streaming source from any block-yielding callable (runs INSIDE
    the generator task — e.g. read files lazily)."""
    return DataStream(make_blocks, window=window)
