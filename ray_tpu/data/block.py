"""Columnar blocks: the binary block format + local block-file IO.

Reference parity: upstream Data's value is Arrow-backed blocks with
per-block metadata (size bytes, row count) feeding the streaming
executor's memory accounting, plus columnar file IO (``read_parquet``)
— ``python/ray/data/_internal/`` (SURVEY.md §1 layer 14; mount empty).

TPU-first shape: a block is a dict of dense NUMPY columns — the layout
jax consumes zero-copy (``jnp.asarray(col)``), so a pipeline feeding a
device mesh never row-pivots.  The on-disk format (``.rtb``) is the
``read_parquet``-equivalent local binary reader: a fixed magic, a JSON
header describing columns (name/dtype/shape), then each column's raw
little-endian buffer, contiguously.  No pickle anywhere in the file
path — blocks are readable by any language that can parse JSON and
memcpy.
"""

from __future__ import annotations

import json
import os
import struct
from typing import Iterable, Iterator

import numpy as np

_MAGIC = b"RTB1"


class ColumnBlock:
    """An immutable batch of rows stored as named dense columns.

    ``nbytes`` is the per-block size stat the streaming executor's
    adaptive window consumes (upstream: BlockMetadata.size_bytes)."""

    __slots__ = ("_cols", "_n")

    def __init__(self, columns: dict[str, np.ndarray]):
        cols = {}
        n = None
        for name, arr in columns.items():
            arr = np.asarray(arr)
            if n is None:
                n = arr.shape[0]
            elif arr.shape[0] != n:
                raise ValueError(
                    f"column {name!r} has {arr.shape[0]} rows, "
                    f"expected {n}")
            cols[str(name)] = arr
        self._cols = cols
        self._n = n or 0

    # -- stats ---------------------------------------------------------------
    @property
    def num_rows(self) -> int:
        return self._n

    @property
    def nbytes(self) -> int:
        return sum(a.nbytes for a in self._cols.values())

    @property
    def column_names(self) -> list[str]:
        return list(self._cols)

    def column(self, name: str) -> np.ndarray:
        return self._cols[name]

    def columns(self) -> dict[str, np.ndarray]:
        return dict(self._cols)

    def __len__(self) -> int:
        return self._n

    def __iter__(self):
        # row iteration: DataStream.iter_rows()/take_all() and plain
        # ``for row in block`` work on columnar blocks too
        return iter(self.to_rows())

    def __eq__(self, other) -> bool:
        return (isinstance(other, ColumnBlock)
                and self.column_names == other.column_names
                and all(np.array_equal(self._cols[k], other._cols[k])
                        for k in self._cols))

    def __repr__(self) -> str:
        cols = {k: f"{a.dtype}{list(a.shape[1:])}"
                for k, a in self._cols.items()}
        return f"ColumnBlock({self._n} rows, {cols})"

    # -- row <-> column pivots ----------------------------------------------
    @classmethod
    def from_rows(cls, rows: list[dict]) -> "ColumnBlock":
        if not rows:
            return cls({})
        names = list(rows[0])
        return cls({k: np.asarray([r[k] for r in rows])
                    for k in names})

    def to_rows(self) -> list[dict]:
        names = list(self._cols)
        cols = [self._cols[k] for k in names]
        return [{k: c[i].item() if c[i].shape == () else c[i]
                 for k, c in zip(names, cols)}
                for i in range(self._n)]

    # -- transforms ----------------------------------------------------------
    def select(self, names: list[str]) -> "ColumnBlock":
        return ColumnBlock({k: self._cols[k] for k in names})

    def take(self, mask_or_idx) -> "ColumnBlock":
        return ColumnBlock({k: a[mask_or_idx]
                            for k, a in self._cols.items()})

    def slice(self, lo: int, hi: int) -> "ColumnBlock":
        return ColumnBlock({k: a[lo:hi]
                            for k, a in self._cols.items()})

    # -- binary wire/file format --------------------------------------------
    def to_bytes(self) -> bytes:
        """MAGIC | u32 header_len | header JSON | column buffers.
        Column buffers are C-contiguous little-endian, in header
        order."""
        header = []
        buffers = []
        for name, arr in self._cols.items():
            a = np.ascontiguousarray(arr)
            if a.dtype.byteorder == ">":
                a = a.astype(a.dtype.newbyteorder("<"))
            if a.dtype.hasobject:
                raise TypeError(
                    f"column {name!r} has object dtype — the binary "
                    "block format holds dense numeric/bytes columns "
                    "only (strings: encode to fixed-width or bytes)")
            header.append({"name": name, "dtype": a.dtype.str,
                           "shape": list(a.shape)})
            buffers.append(a.tobytes())
        hdr = json.dumps({"columns": header,
                          "num_rows": self._n}).encode()
        out = bytearray()
        out += _MAGIC
        out += struct.pack("<I", len(hdr))
        out += hdr
        for b in buffers:
            out += b
        return bytes(out)

    @classmethod
    def from_bytes(cls, data: bytes) -> "ColumnBlock":
        if data[:4] != _MAGIC:
            raise ValueError("not an RTB1 block")
        (hlen,) = struct.unpack_from("<I", data, 4)
        hdr = json.loads(data[8:8 + hlen].decode())
        off = 8 + hlen
        cols = {}
        for c in hdr["columns"]:
            dt = np.dtype(c["dtype"])
            shape = tuple(c["shape"])
            n = int(np.prod(shape)) if shape else 1
            arr = np.frombuffer(data, dtype=dt, count=n,
                                offset=off).reshape(shape)
            off += n * dt.itemsize
            cols[c["name"]] = arr
        block = cls.__new__(cls)
        block._cols = cols
        block._n = int(hdr["num_rows"])
        return block

    def __reduce__(self):
        # blocks cross process boundaries in the binary format, not as
        # pickled ndarray graphs (stable wire layout, no pickle in the
        # data plane)
        return (ColumnBlock.from_bytes, (self.to_bytes(),))


# -- block files (the read_parquet-equivalent local reader) ------------------

def write_block_file(block: ColumnBlock, path: str) -> str:
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(block.to_bytes())
    os.replace(tmp, path)
    return path


def read_block_file(path: str) -> ColumnBlock:
    with open(path, "rb") as f:
        return ColumnBlock.from_bytes(f.read())


def write_blocks(blocks: Iterable[ColumnBlock], directory: str,
                 prefix: str = "part") -> list[str]:
    """One ``.rtb`` file per block (the write_parquet analogue)."""
    os.makedirs(directory, exist_ok=True)
    paths = []
    for i, b in enumerate(blocks):
        paths.append(write_block_file(
            b, os.path.join(directory, f"{prefix}-{i:05d}.rtb")))
    return paths


def block_file_paths(paths_or_dir) -> list[str]:
    if isinstance(paths_or_dir, str):
        if os.path.isdir(paths_or_dir):
            return sorted(
                os.path.join(paths_or_dir, n)
                for n in os.listdir(paths_or_dir)
                if n.endswith(".rtb"))
        return [paths_or_dir]
    return list(paths_or_dir)


def iter_block_files(paths_or_dir) -> Iterator[ColumnBlock]:
    for p in block_file_paths(paths_or_dir):
        yield read_block_file(p)
