"""Dataset: block-parallel transforms executed as tasks.

Blocks are plain lists (row datasets) or numpy arrays (tensor
datasets); each transform ships one task per block and the results stay
in the object store until consumed (reference: ``ray.data``'s
block/BlockMetadata model with task-based map stages — SURVEY.md §1
layer 14; mount empty).
"""

from __future__ import annotations

import builtins
from typing import Any, Callable, Iterable, Iterator

import numpy as np


def _api():
    import ray_tpu
    return ray_tpu


# -- block-level task bodies (top-level so cloudpickle ships cleanly) --------

def _map_block(fn, block):
    if isinstance(block, np.ndarray):
        return np.asarray([fn(row) for row in block])
    return [fn(row) for row in block]


def _map_batches_block(fn, block):
    return fn(block)


def _filter_block(fn, block):
    if isinstance(block, np.ndarray):
        mask = np.asarray([bool(fn(row)) for row in block])
        return block[mask]
    return [row for row in block if fn(row)]


def _flat_map_block(fn, block):
    out: list = []
    for row in block:
        out.extend(fn(row))
    return out


def _sort_block(block, key):
    if isinstance(block, np.ndarray):
        keys = np.asarray([key(r) for r in block]) if key is not None \
            else block
        return block[np.argsort(keys, kind="stable")]
    return sorted(block, key=key)


def _merge_sorted(blocks, key):
    import heapq
    rows: Iterable[Any]
    rows = heapq.merge(*[list(b) for b in blocks], key=key)
    return list(rows)


def _shuffle_partition(blocks, n_out: int, seed: int, salt: int):
    """Map stage of a distributed shuffle: split one block into n_out
    pseudo-random buckets (deterministic in (seed, salt, position))."""
    rng = np.random.default_rng((seed, salt))
    rows = list(blocks)
    dests = rng.integers(0, n_out, size=len(rows))
    return [[row for row, d in zip(rows, dests) if d == i]
            for i in builtins.range(n_out)]


def _shuffle_concat(seed: int, idx: int, *buckets):
    """Reduce stage: concatenate one bucket from every map output and
    locally shuffle the concatenation."""
    out: list = []
    for b in buckets:
        out.extend(b)
    rng = np.random.default_rng((seed, 10_000 + idx))
    rng.shuffle(out)
    return out


class Dataset:
    """A list of block ObjectRefs + row counts."""

    def __init__(self, block_refs: list, counts: list[int]):
        self._blocks = list(block_refs)
        self._counts = list(counts)

    # -- transforms (each = one task per block) ------------------------------
    def _per_block(self, body, fn) -> "Dataset":
        rt = _api()
        task = rt.remote(body)
        refs = [task.remote(fn, b) for b in self._blocks]
        return Dataset(refs, self._counts)

    def map(self, fn: Callable[[Any], Any]) -> "Dataset":
        return self._per_block(_map_block, fn)

    def map_batches(self, fn: Callable[[Any], Any]) -> "Dataset":
        """``fn`` sees a whole block (list or ndarray) and returns the
        transformed block — the TPU-friendly hook: batch work, not
        per-row Python."""
        ds = self._per_block(_map_batches_block, fn)
        ds._counts = [-1] * len(ds._blocks)     # fn may change row counts
        return ds

    def filter(self, fn: Callable[[Any], bool]) -> "Dataset":
        ds = self._per_block(_filter_block, fn)
        ds._counts = [-1] * len(ds._blocks)
        return ds

    def flat_map(self, fn: Callable[[Any], Iterable[Any]]) -> "Dataset":
        ds = self._per_block(_flat_map_block, fn)
        ds._counts = [-1] * len(ds._blocks)
        return ds

    def repartition(self, num_blocks: int) -> "Dataset":
        if num_blocks <= 0:
            raise ValueError("num_blocks must be positive")
        rows = self._materialize_rows()
        return _from_rows(rows, num_blocks)

    def random_shuffle(self, *, seed: int = 0) -> "Dataset":
        """Two-stage distributed shuffle: per-block bucket split (map
        tasks), then per-bucket concatenation (reduce tasks) — the
        all-to-all shape of the reference's push-based shuffle."""
        rt = _api()
        n = len(self._blocks)
        reduce_task = rt.remote(_shuffle_concat)
        if n <= 1:
            return Dataset(
                [reduce_task.remote(seed, 0, b) for b in self._blocks],
                [-1] * n)
        # map stage emits n SEPARATE return objects per block, so each
        # reduce task pulls only its bucket refs — nothing funnels
        # through the driver (the all-to-all stays in the object store)
        split = rt.remote(_shuffle_partition).options(num_returns=n)
        part_refs = [split.remote(b, n, seed, i)
                     for i, b in enumerate(self._blocks)]
        refs = [reduce_task.remote(seed, j, *[pr[j] for pr in part_refs])
                for j in builtins.range(n)]
        return Dataset(refs, [-1] * n)

    def sort(self, key: Callable | None = None) -> "Dataset":
        rt = _api()
        sort_task = rt.remote(_sort_block)
        sorted_refs = [sort_task.remote(b, key) for b in self._blocks]
        blocks = rt.get(sorted_refs, timeout=300)
        merged = _merge_sorted(blocks, key)
        return _from_rows(merged, max(len(self._blocks), 1))

    def groupby(self, key_fn: Callable | None = None):
        """Group rows by ``key_fn(row)`` (identity when None); finish
        with ``.count()/.sum()/.mean()/.aggregate(...)`` — distributed
        per-block partials + a worker-side merge tree."""
        from .aggregate import GroupedDataset
        return GroupedDataset(self, key_fn)

    def union(self, other: "Dataset") -> "Dataset":
        return Dataset(self._blocks + other._blocks,
                       self._counts + other._counts)

    def write_json(self, directory: str) -> list[str]:
        """One ``part-NNNNN.json`` per block; returns written paths."""
        from .aggregate import write_json
        return write_json(self, directory)

    def split(self, n: int) -> list["Dataset"]:
        """N aligned shards (for per-worker ingest in ray_tpu.train)."""
        rows = self._materialize_rows()
        shards = np.array_split(np.arange(len(rows)), n)
        return [_from_rows([rows[i] for i in shard], 1)
                for shard in shards]

    # -- consumers -----------------------------------------------------------
    def _materialize(self) -> list:
        return _api().get(list(self._blocks), timeout=300)

    def _materialize_rows(self) -> list:
        rows: list = []
        for block in self._materialize():
            rows.extend(list(block))
        return rows

    def count(self) -> int:
        if all(c >= 0 for c in self._counts):
            return sum(self._counts)
        return sum(len(b) for b in self._materialize())

    def take(self, k: int = 20) -> list:
        out: list = []
        rt = _api()
        for ref in self._blocks:
            out.extend(list(rt.get(ref, timeout=300)))
            if len(out) >= k:
                return out[:k]
        return out

    def take_all(self) -> list:
        return self._materialize_rows()

    def sum(self):
        vals = self._materialize_rows()
        return sum(vals)

    def to_numpy(self) -> np.ndarray:
        blocks = [np.asarray(b) for b in self._materialize()]
        return np.concatenate([b for b in blocks if b.size]) \
            if blocks else np.empty(0)

    def iter_batches(self, *, batch_size: int = 256) \
            -> Iterator[np.ndarray]:
        """Stream fixed-size numpy batches across block boundaries —
        the training-ingest hook (pad/drop is the caller's choice)."""
        carry: list = []
        for block in self._materialize():
            carry.extend(list(block))
            while len(carry) >= batch_size:
                yield np.asarray(carry[:batch_size])
                carry = carry[batch_size:]
        if carry:
            yield np.asarray(carry)

    def iter_torch_batches(self, *, batch_size: int = 256,
                           dtype=None, device=None):
        """``iter_batches`` as torch tensors (reference:
        ``Dataset.iter_torch_batches``) — the torch-training ingest
        hook; conversion is zero-copy from the numpy batch where
        dtypes allow."""
        import torch
        for batch in self.iter_batches(batch_size=batch_size):
            # iter_batches yields fresh contiguous arrays; one fused
            # .to(device, dtype) avoids a second full-batch copy
            t = torch.from_numpy(batch)
            if dtype is not None or device is not None:
                t = t.to(device=device, dtype=dtype)
            yield t

    def num_blocks(self) -> int:
        return len(self._blocks)

    def __repr__(self) -> str:
        return f"Dataset(num_blocks={len(self._blocks)})"


# -- constructors ------------------------------------------------------------

def _from_rows(rows: list, num_blocks: int) -> Dataset:
    rt = _api()
    chunks = np.array_split(np.arange(len(rows)), num_blocks)
    refs, counts = [], []
    for chunk in chunks:
        block = [rows[i] for i in chunk]
        refs.append(rt.put(block))
        counts.append(len(block))
    return Dataset(refs, counts)


def from_items(items: Iterable[Any], *, parallelism: int = 8) -> Dataset:
    rows = list(items)
    return _from_rows(rows, max(min(parallelism, len(rows)), 1))


def range(n: int, *, parallelism: int = 8) -> Dataset:  # noqa: A001
    return from_items(builtins.range(n), parallelism=parallelism)


def from_numpy(arr: np.ndarray, *, parallelism: int = 8) -> Dataset:
    rt = _api()
    arr = np.asarray(arr)
    chunks = [c for c in np.array_split(arr, parallelism) if len(c)] \
        or [arr]
    return Dataset([rt.put(c) for c in chunks],
                   [len(c) for c in chunks])
