"""Programmatic autoscaler control.

Reference parity: ``ray.autoscaler.sdk.request_resources``
(``python/ray/autoscaler/sdk.py`` — SURVEY.md §1 layer 11; mount
empty): command the cluster to scale so the given resource bundles
could be scheduled, immediately and regardless of current load.  Each
call replaces the previous request; ``request_resources()`` with no
arguments clears it.
"""

from __future__ import annotations


def request_resources(num_cpus: int | None = None,
                      bundles: list[dict] | None = None) -> None:
    from ray_tpu.api import _get_runtime
    reqs: list[dict] = []
    if num_cpus:
        reqs.extend({"CPU": 1} for _ in range(int(num_cpus)))
    for b in bundles or []:
        reqs.append(dict(b))
    rt = _get_runtime()
    cluster = getattr(rt, "cluster", None)
    if cluster is not None:                 # in-process driver
        asc = cluster.autoscaler
        if asc is None:
            raise RuntimeError(
                "no autoscaler is running — start one with "
                "cluster.start_autoscaler(node_types)")
        asc.request_resources(reqs)
        return
    if hasattr(rt, "request_resources"):    # client mode: head RPC
        rt.request_resources(reqs)
        return
    raise RuntimeError(
        "request_resources is callable from the driver or a connected "
        "client; worker-side calls are not supported")
