"""Programmatic autoscaler control.

Reference parity: ``ray.autoscaler.sdk.request_resources``
(``python/ray/autoscaler/sdk.py`` — SURVEY.md §1 layer 11; mount
empty): command the cluster to scale so the given resource bundles
could be scheduled, immediately and regardless of current load.  Each
call replaces the previous request; ``request_resources()`` with no
arguments clears it.
"""

from __future__ import annotations


def request_resources(num_cpus: int | None = None,
                      bundles: list[dict] | None = None) -> None:
    from ray_tpu.api import _get_runtime
    rt = _get_runtime()
    cluster = getattr(rt, "cluster", None)
    asc = getattr(cluster, "autoscaler", None) if cluster else None
    if asc is None:
        raise RuntimeError(
            "no autoscaler is running — start one with "
            "cluster.start_autoscaler(node_types)")
    reqs: list[dict] = []
    if num_cpus:
        reqs.extend({"CPU": 1} for _ in range(int(num_cpus)))
    for b in bundles or []:
        reqs.append(dict(b))
    asc.request_resources(reqs)
