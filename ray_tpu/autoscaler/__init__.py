from .demand import (FIRST_FIT_THRESHOLD, NodeTypeSpec, fit_existing,
                     get_nodes_to_launch, pack_one_node)

__all__ = ["FIRST_FIT_THRESHOLD", "NodeTypeSpec", "fit_existing",
           "get_nodes_to_launch", "pack_one_node"]
