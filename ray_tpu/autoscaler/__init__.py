from .autoscaler import NODE_TYPE_LABEL, StandardAutoscaler
from .demand import (FIRST_FIT_THRESHOLD, NodeTypeSpec, fit_existing,
                     get_nodes_to_launch, pack_one_node)

__all__ = ["FIRST_FIT_THRESHOLD", "NODE_TYPE_LABEL", "NodeTypeSpec",
           "StandardAutoscaler", "fit_existing", "get_nodes_to_launch",
           "pack_one_node"]
