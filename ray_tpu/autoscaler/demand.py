"""Autoscaler demand packing — CPU reference oracle (north-star config #5).

Reference parity: upstream's ``ResourceDemandScheduler`` (autoscaler v1,
``python/ray/autoscaler/_private/resource_demand_scheduler.py``; same
semantics in v2's ``Scheduler``) answers "given pending resource demands and
the available node types, how many nodes of each type to launch": it first
bin-packs demands onto existing nodes' free capacity, then greedily adds
virtual nodes of the type scoring best by a utilization scorer until demands
are met or per-type quotas are hit.  [SURVEY.md §1 layer 11 / §2.2 / §4
autoscaler tier; reference mount empty — the exact scorer and traversal are
re-derived as the deterministic contract below, which the TPU kernel in
ray_tpu/ops/binpack_kernel.py matches bit-for-bit.]

The contract
------------
Inputs: existing cluster state, demand classes ``(G, R)`` with counts
``(G,)``, node types ``(K, R)`` capacities with launch quotas ``(K,)``.

Phase 1 — fit on existing nodes: FIRST-FIT in node-row order, demands in
class order.  This is exactly the hybrid contract with the spread threshold
above the maximum possible score (every available node ties at eff 0 and
wins by traversal index) and ``require_available`` semantics (an unfit
demand is a leftover, never queued) — so phase 1 IS the water-fill kernel.

Phase 2 — launch loop over leftovers, repeated until done/stuck:
  1. For each type k with quota left, FIRST-FIT one fresh node of type k
     over the remaining classes in class order -> packed counts p_k (G,),
     utilization score s_k = max_i (used_i * SCALE) // cap_i.
  2. Choose the type maximizing (s_k, -k) among those packing > 0 units
     (best packing; deterministic low-index tie-break).
  3. Batch-repeat: launch t = min(quota_k, min_{g: p_g>0} remaining_g // p_g,
     floored at 1) identical nodes at once; subtract t * p_k.
The batch-repeat factor is part of the contract (both implementations take
it), bounding the loop at O(G*K + G + K) iterations regardless of demand
counts — that is what makes 1M pending demands a device-friendly problem.

All-zero demand rows never launch nodes (dropped up front).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from ..scheduling.contract import SCALE
from ..scheduling.oracle import ClusterState, schedule_grouped_oracle

# The smallest spread threshold above the max score (2*SCALE = 2x
# utilization) turns the hybrid policy into first-fit-by-traversal-order.
# Exactly 2*SCALE + 1 in fixed point: any higher (e.g. 4*SCALE) pushes
# (L+1)*totals in the kernel's slot-count inversion past int31 for max-cap
# nodes (contract.py width audit).
FIRST_FIT_THRESHOLD = (2 * SCALE + 1) / SCALE


@dataclass(frozen=True)
class NodeTypeSpec:
    """One launchable node type (resources in user units, quota in nodes)."""

    name: str
    resources: dict[str, float]
    max_workers: int


def fit_existing(state: ClusterState, demand_reqs: np.ndarray,
                 demand_counts: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Phase 1. Returns (fit counts (G, N+1), leftover per class (G,)).

    Mutates ``state.avail`` (the fitted demands hold those resources).
    """
    counts = schedule_grouped_oracle(
        state, demand_reqs, demand_counts,
        spread_threshold=FIRST_FIT_THRESHOLD, require_available=True)
    return counts, counts[:, -1].copy()


def pack_one_node(cap: np.ndarray, demand_reqs: np.ndarray,
                  remaining: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """First-fit one fresh node: (packed (G,), used (R,))."""
    R = cap.shape[0]
    used = np.zeros(R, dtype=np.int64)
    packed = np.zeros(remaining.shape[0], dtype=np.int64)
    for g in range(demand_reqs.shape[0]):
        if remaining[g] <= 0:
            continue
        req = demand_reqs[g].astype(np.int64)
        pos = req > 0
        if not pos.any():
            continue                       # zero demands never pack
        fit = ((cap.astype(np.int64) - used)[pos] // req[pos]).min()
        fit = min(max(fit, 0), int(remaining[g]))
        used += fit * req
        packed[g] = fit
    return packed, used


def _type_score(cap: np.ndarray, used: np.ndarray) -> int:
    """Fixed-point critical-resource utilization of a packed node."""
    pos = cap > 0
    if not pos.any():
        return 0
    return int(((used[pos] * SCALE) // cap[pos]).max())


def get_nodes_to_launch(state: ClusterState, demand_reqs: np.ndarray,
                        demand_counts: np.ndarray, type_caps: np.ndarray,
                        type_quotas: np.ndarray
                        ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Full demand-scheduler pass.

    demand_reqs: (G, R) int32 cu.  demand_counts: (G,) int.
    type_caps: (K, R) int32 cu.  type_quotas: (K,) int.
    Returns (launches (K,), fit counts (G, N+1), unmet (G,)).
    Mutates ``state.avail`` for phase-1 fits.
    """
    demand_reqs = np.asarray(demand_reqs, dtype=np.int32)
    type_caps = np.asarray(type_caps, dtype=np.int32)
    fit_counts, remaining = fit_existing(state, demand_reqs, demand_counts)
    remaining = remaining.astype(np.int64)

    K = type_caps.shape[0]
    launches = np.zeros(K, dtype=np.int64)
    quota = np.asarray(type_quotas, dtype=np.int64).copy()
    zero_rows = ~(demand_reqs > 0).any(axis=1)
    remaining[zero_rows] = 0

    while remaining.sum() > 0:
        best_k, best_score, best_packed = -1, -1, None
        for k in range(K):
            if quota[k] <= 0:
                continue
            packed, used = pack_one_node(type_caps[k], demand_reqs,
                                         remaining)
            if packed.sum() == 0:
                continue
            score = _type_score(type_caps[k].astype(np.int64), used)
            if score > best_score:
                best_k, best_score, best_packed = k, score, packed
        if best_k < 0:
            break
        p = best_packed
        nz = p > 0
        t = int(min(quota[best_k], (remaining[nz] // p[nz]).min()))
        t = max(t, 1)
        launches[best_k] += t
        quota[best_k] -= t
        remaining = remaining - t * p
        np.clip(remaining, 0, None, out=remaining)

    return launches.astype(np.int32), fit_counts, remaining
