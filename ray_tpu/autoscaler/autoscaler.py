"""Autoscaler runtime loop: live demand in, node launches/terminations out.

Reference parity: upstream's ``StandardAutoscaler.update()`` (``python/ray/
autoscaler/_private/autoscaler.py``) periodically collects pending resource
demands (infeasible tasks + pending placement groups) from the load
metrics, asks ``ResourceDemandScheduler.get_nodes_to_launch`` how many
nodes of each available type to add, launches them through the node
provider, and terminates nodes idle past ``idle_timeout_minutes``
(SURVEY.md §1 layer 11; mount empty).

TPU-first: the packing math is the bin-pack kernel — large demand rounds
run ``ops.binpack_kernel.autoscale`` on device (bit-identical to the CPU
oracle in ``autoscaler.demand``), so a 1M-pending-demand round costs one
dense device pass (north-star config #5).  The loop itself is
event-driven: raylets kick it when a scheduling round parks infeasible
tasks, with ``autoscaler_update_interval_ms`` as the fallback tick.
"""

from __future__ import annotations

import threading

import numpy as np

from ..common.config import get_config
from ..common.resources import ResourceRequest
from .demand import NodeTypeSpec, get_nodes_to_launch
from ..common import clock as _clk

NODE_TYPE_LABEL = "node-type"       # CRM label carrying the launch type


class StandardAutoscaler:
    """The runtime loop around the demand-packing math.

    ``update()`` is one synchronous round (tests call it directly);
    ``start()`` runs rounds on a daemon thread, woken early by ``kick()``
    (raylets call it when tasks park infeasible, placement-group manager
    when a group cannot place).
    """

    def __init__(self, cluster, node_types: list[NodeTypeSpec],
                 min_workers: int = 0, workers_per_node: int = 2,
                 idle_timeout_s: float | None = None,
                 interval_ms: int | None = None):
        cfg = get_config()
        self._cluster = cluster
        self._types = list(node_types)
        self._min_workers = min_workers
        self._workers_per_node = workers_per_node
        self._idle_timeout = (idle_timeout_s if idle_timeout_s is not None
                              else cfg.autoscaler_idle_timeout_s)
        self._interval = (interval_ms if interval_ms is not None
                          else cfg.autoscaler_update_interval_ms) / 1000.0
        self._device_min = cfg.autoscaler_device_batch_min
        self._use_device = cfg.scheduler_device_backend
        self._wake = threading.Event()
        self._stop = False
        self._thread: threading.Thread | None = None
        self._idle_since: dict = {}             # NodeID -> monotonic time
        self._surplus_since: dict = {}          # NodeID -> monotonic time
        self._migrating: set = set()            # sole-copy pulls in flight
        self._lock = threading.Lock()           # one update at a time
        # stats
        self.num_launched = 0
        self.num_terminated = 0
        self.num_drained = 0
        self.migrations_started = 0
        self.migrations_completed = 0
        self.migrations_failed = 0
        self.device_rounds = 0
        self.oracle_rounds = 0
        self.last_unmet = 0

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="autoscaler")
        self._thread.start()

    def kick(self) -> None:
        """Wake the loop early (infeasible task / pending PG arrival)."""
        self._wake.set()

    def shutdown(self) -> None:
        """Stop AND join (an in-flight update must not race teardown)."""
        self._stop = True
        self._wake.set()
        if self._thread is not None and self._thread.is_alive():
            self._thread.join(timeout=5.0)

    def _loop(self) -> None:
        while not self._stop:
            self._wake.wait(timeout=self._interval)
            self._wake.clear()
            if self._stop:
                return
            try:
                self.update()
            except Exception:   # noqa: BLE001 — a bad round must not kill
                import traceback
                traceback.print_exc()

    # -- one round -----------------------------------------------------------
    def update(self) -> dict:
        """Collect demand, launch what packing says, retire idle nodes.
        Returns the round's summary (launches by type, unmet classes)."""
        with self._lock:
            launches = self._scale_up()
            terminated = self._scale_down()
        # capacity loaning rides the autoscaler beat: batch pressure
        # (unmet demand) is the reclaim trigger, serve backlog the loan
        # trigger — both are read inside the manager's own tick
        loans = getattr(self._cluster, "loans", None)
        if loans is not None:
            loans.tick(unmet=self.last_unmet)
        return {"launches": launches, "terminated": terminated,
                "unmet": self.last_unmet}

    def request_resources(self, bundles: list[dict]) -> None:
        """Explicit demand floor (reference:
        ``ray.autoscaler.sdk.request_resources``): the cluster sizes so
        these bundles COULD schedule, immediately and independent of
        live task load.  Each call REPLACES the previous request; an
        empty list clears it."""
        reqs = [ResourceRequest(b) for b in bundles]
        with self._lock:
            self._requested = reqs
        self.kick()

    def _pending_demand(self) -> tuple[list[ResourceRequest], list[int]]:
        """Per-class pending demand: infeasible/queued tasks from every
        raylet plus the bundles of pending placement groups (reference:
        ``LoadMetrics`` resource_demand + pending_placement_groups)
        plus any explicit ``request_resources`` floor."""
        by_class: dict = {}
        for raylet in list(self._cluster.raylets.values()):
            for req in raylet.pending_demand():
                ent = by_class.setdefault(req.key(), [req, 0])
                ent[1] += 1
        for req in self._cluster.pg_manager.pending_bundle_demand():
            ent = by_class.setdefault(req.key(), [req, 0])
            ent[1] += 1
        for req in getattr(self, "_requested", ()):
            ent = by_class.setdefault(req.key(), [req, 0])
            ent[1] += 1
        reqs = [e[0] for e in by_class.values()]
        counts = [e[1] for e in by_class.values()]
        return reqs, counts

    def _live_type_counts(self) -> dict[str, int]:
        crm = self._cluster.crm
        out: dict[str, int] = {}
        for row in list(self._cluster.raylets):
            t = crm.labels_of(row).get(NODE_TYPE_LABEL)
            if t is not None:
                out[t] = out.get(t, 0) + 1
        return out

    def _scale_up(self) -> dict[str, int]:
        reqs, counts = self._pending_demand()
        if not reqs or not self._types:
            self.last_unmet = 0
            return {}
        crm = self._cluster.crm
        for r in reqs:
            crm.intern_request(r)
        type_reqs = [ResourceRequest(t.resources) for t in self._types]
        for r in type_reqs:
            crm.intern_request(r)
        snapshot = crm.snapshot()
        width = snapshot.totals.shape[1]
        demand_reqs = np.stack(
            [r.dense(crm.resource_index, width) for r in reqs])
        demand_counts = np.asarray(counts, dtype=np.int64)
        type_caps = np.stack(
            [r.dense(crm.resource_index, width) for r in type_reqs])
        live = self._live_type_counts()
        quotas = np.asarray(
            [max(t.max_workers - live.get(t.name, 0), 0)
             for t in self._types], dtype=np.int64)

        if self._use_device and int(demand_counts.sum()) >= self._device_min:
            from ..ops.binpack_kernel import autoscale_np
            self.device_rounds += 1
            launches, _fit, unmet, _avail = autoscale_np(
                snapshot.totals, snapshot.avail, snapshot.node_mask,
                demand_reqs, demand_counts.astype(np.int32), type_caps,
                quotas.astype(np.int32))
        else:
            self.oracle_rounds += 1
            launches, _fit, unmet = get_nodes_to_launch(
                snapshot, demand_reqs, demand_counts, type_caps, quotas)
        self.last_unmet = int(np.asarray(unmet).sum())

        launched: dict[str, int] = {}
        for k, n in enumerate(np.asarray(launches)):
            for _ in range(int(n)):
                self._cluster.add_node(
                    resources=dict(self._types[k].resources),
                    num_workers=self._workers_per_node,
                    labels={NODE_TYPE_LABEL: self._types[k].name},
                    wait=False)
                self.num_launched += 1
                launched[self._types[k].name] = \
                    launched.get(self._types[k].name, 0) + 1
        if launched:
            self._cluster.events.emit("autoscaler", "nodes_launched",
                                      launches=launched,
                                      unmet=self.last_unmet)
        return launched

    def _scale_down(self) -> list:
        """Terminate nodes idle past the timeout (never the head; never
        below ``min_workers`` worker nodes).  With
        ``autoscaler_drain_busy`` on, BUSY nodes whose capacity the
        cluster no longer needs are gracefully drained instead of
        waiting (possibly forever) for idleness."""
        cluster = self._cluster
        cfg = get_config()
        now = _clk.monotonic()
        totals, avail, mask = cluster.crm.arrays()
        drain_mask = cluster.crm.draining
        loan_mask = cluster.crm.loaned
        terminated = []
        rows = [(row, r) for row, r in list(cluster.raylets.items())
                if row != cluster._head_row]
        live_workers = len(rows)
        # nodes already DRAINING are on their way out: skip them below,
        # but count them as leaving so this round keeps min_workers
        leaving = sum(1 for row, _ in rows if drain_mask[row])
        requested = list(getattr(self, "_requested", ()))
        for row, raylet in rows:
            if drain_mask[row] or loan_mask[row]:
                # LOANED rows belong to the serve plane until the loan
                # manager reclaims them: neither idle-terminate nor
                # surplus-drain may take them out from under a replica
                self._idle_since.pop(raylet.node_id, None)
                self._surplus_since.pop(raylet.node_id, None)
                continue
            fully_free = bool(mask[row]) and \
                (avail[row] == totals[row]).all()
            if fully_free and requested and \
                    not self._fits_without(row, requested):
                # an explicit request_resources floor still needs this
                # node's capacity: terminating would relaunch it next
                # round (flap) and break the floor contract
                self._idle_since.pop(raylet.node_id, None)
                continue
            if fully_free and raylet.is_idle():
                self._surplus_since.pop(raylet.node_id, None)
                sole = cluster.directory.sole_copies_on(row)
                if sole:
                    # the node holds the only copy of live objects:
                    # terminating would destroy them (or burn lineage
                    # retries).  Migrate to the head first; the node
                    # retires on a later round once a FRESH sole-copy
                    # scan comes back empty — i.e. the copies actually
                    # landed (reference: drain-before-terminate).
                    self._migrate_off(sole, row)
                    continue
                t0 = self._idle_since.setdefault(raylet.node_id, now)
                if (now - t0 >= self._idle_timeout and
                        live_workers - len(terminated) - leaving
                        > self._min_workers):
                    cluster.events.emit(
                        "autoscaler", "idle_node_terminated", node_row=row,
                        node_id=raylet.node_id.hex(),
                        idle_seconds=now - t0)
                    cluster.remove_node(raylet.node_id)
                    self._idle_since.pop(raylet.node_id, None)
                    self.num_terminated += 1
                    terminated.append(raylet.node_id)
            else:
                self._idle_since.pop(raylet.node_id, None)
                # busy-but-surplus: the cluster fits all explicit demand
                # without this node and nothing is unmet — hand its work
                # off gracefully instead of waiting for idleness
                # (Aryl-style preemption-aware scale-down)
                if (cfg.autoscaler_drain_busy and bool(mask[row])
                        and self.last_unmet == 0
                        and live_workers - len(terminated) - leaving
                        > self._min_workers
                        and self._fits_without(row, requested)):
                    t0 = self._surplus_since.setdefault(raylet.node_id,
                                                        now)
                    if now - t0 >= cfg.autoscaler_drain_surplus_s:
                        self._surplus_since.pop(raylet.node_id, None)
                        cluster.drain_node(
                            raylet.node_id,
                            reason="autoscaler: busy-but-surplus "
                                   "scale-down")
                        self.num_drained += 1
                        leaving += 1
                else:
                    self._surplus_since.pop(raylet.node_id, None)
        return terminated

    def _fits_without(self, row: int, requested) -> bool:
        """Would the explicit request floor still fit on AVAILABLE
        capacity if ``row`` were terminated?  Greedy per-node bundle
        fit (same granularity the launch packer uses)."""
        cluster = self._cluster
        _totals, avail, mask = cluster.crm.arrays()
        drain_mask = cluster.crm.draining
        width = avail.shape[1]
        remaining = {r: avail[r].astype(np.int64).copy()
                     for r in cluster.raylets
                     if r != row and mask[r] and not drain_mask[r]}
        for req in requested:
            vec = req.dense(cluster.crm.resource_index, width)
            placed = False
            for r, cap in remaining.items():
                if (cap[:vec.shape[0]] >= vec).all():
                    cap[:vec.shape[0]] -= vec
                    placed = True
                    break
            if not placed:
                return False
        return True

    def _migrate_off(self, object_ids, row: int) -> None:
        """Pull sole-copy objects to the head so the node becomes safe
        to retire.  Completion-tracked: every plasma kind a directory
        entry can carry (shm, spill, AND agent-plane ``remote``)
        migrates, callbacks record landings/failures, and in-flight
        pulls are not re-requested.  The node only retires once a fresh
        ``sole_copies_on`` scan comes back empty — i.e. the directory
        saw each copy land on the head."""
        from ..runtime.object_store import PLASMA_KINDS
        from ..runtime.pull_manager import PullPriority
        cluster = self._cluster
        head_row = cluster._head_row
        store = cluster.store
        for oid in object_ids:
            if oid in self._migrating:
                continue                    # pull already in flight
            kind, size = store.plasma_info(oid)
            if kind not in PLASMA_KINDS:
                continue                    # reclaimed since the scan
            self._migrating.add(oid)
            self.migrations_started += 1
            if cluster.pull_manager.request_pull(
                    oid, size, head_row, PullPriority.TASK_ARG,
                    callback=lambda ok, o=oid:
                    self._migration_done(o, ok)):
                self._migration_done(oid, True)     # already satisfied

    def _migration_done(self, oid, ok: bool) -> None:
        self._migrating.discard(oid)
        if ok:
            self.migrations_completed += 1
        else:
            self.migrations_failed += 1
            self._cluster.events.emit("autoscaler", "migration_failed",
                                      object_id=oid.hex())

    def stats(self) -> dict:
        return {"num_launched": self.num_launched,
                "num_terminated": self.num_terminated,
                "num_drained": self.num_drained,
                "migrations_started": self.migrations_started,
                "migrations_completed": self.migrations_completed,
                "migrations_failed": self.migrations_failed,
                "device_rounds": self.device_rounds,
                "oracle_rounds": self.oracle_rounds,
                "last_unmet": self.last_unmet}
