"""Head-side lease grantor: the single source of truth.

Carves bounded per-class budgets for nodes, stamps every node's grant
set with a monotonically-increasing **epoch**, routes repeat-class
submissions to nodes already holding a matching lease (round-robin over
the class's holders), and revokes a node's entire grant set by bumping
its epoch — on death, drain, quarantine, or a leased task going quiet
past the TTL.

Revocations are journaled through an injected callback so the persisted
epoch table survives a head kill: the hot-standby restores it on
promotion, which is why outstanding leases survive failover — grant
authority already lives at the raylets, and the promoted head agrees
with them about which epochs are current.
"""

from __future__ import annotations

__all__ = ["LeaseGrantor"]


class LeaseGrantor:
    def __init__(self, budget_per_class: int, max_classes: int = 64,
                 journal=None):
        self.budget_per_class = max(1, int(budget_per_class))
        self.max_classes = max(1, int(max_classes))
        self._journal = journal          # fn(node, epoch) -> None
        self._epochs: dict[str, int] = {}
        self._grants: dict[str, dict[str, int]] = {}
        # class_key -> [holder nodes, insertion order]; rr cursor per class
        self._class_nodes: dict[str, list[str]] = {}
        self._class_rr: dict[str, int] = {}
        # epoch each node's grant set was last stamped under: origin_for
        # must not route to a holder whose epoch was bumped by revoke/
        # drop_node after its last grant — its raylet will fence every
        # admission and spill the whole batch back (the one-cycle
        # spillback storm).  A fresh grant() re-stamps and re-admits.
        self._granted_epoch: dict[str, int] = {}
        self.leases_issued = 0
        self.revocations = 0

    # -- grants --------------------------------------------------------------
    def epoch(self, node: str) -> int:
        return self._epochs.get(node, 0)

    def grant(self, node: str, class_key: str,
              budget: int | None = None) -> tuple[int, dict]:
        """Lease ``class_key`` to ``node``; returns (epoch, grant set)."""
        grants = self._grants.setdefault(node, {})
        if class_key not in grants:
            if len(grants) >= self.max_classes:
                evicted = next(iter(grants))
                del grants[evicted]
                self._unlink(evicted, node)
            holders = self._class_nodes.setdefault(class_key, [])
            if node not in holders:
                holders.append(node)
            self.leases_issued += 1
        grants[class_key] = int(budget or self.budget_per_class)
        self._granted_epoch[node] = self._epochs.get(node, 0)
        return self._epochs.get(node, 0), dict(grants)

    def snapshot_for(self, node: str) -> tuple[int, dict]:
        return self._epochs.get(node, 0), dict(self._grants.get(node, {}))

    def holds(self, node: str, class_key: str) -> bool:
        return class_key in self._grants.get(node, ())

    # -- revocation ----------------------------------------------------------
    def revoke(self, node: str, reason: str = "") -> int:
        """Bump the node's epoch: every grant stamped below it is dead.
        Returns the new epoch (journaled for failover)."""
        epoch = self._epochs.get(node, 0) + 1
        self._epochs[node] = epoch
        self.revocations += 1
        if self._journal is not None:
            self._journal(node, epoch)
        return epoch

    def drop_node(self, node: str, reason: str = "dead") -> int:
        """Node left the cluster: revoke and forget its grant set."""
        epoch = self.revoke(node, reason)
        for class_key in self._grants.pop(node, {}):
            self._unlink(class_key, node)
        self._granted_epoch.pop(node, None)
        return epoch

    def restore(self, epochs: dict) -> None:
        """Promotion path: adopt the journaled epoch table so the new
        head never re-issues an epoch the old head already revoked."""
        for node, epoch in epochs.items():
            if int(epoch) > self._epochs.get(node, 0):
                self._epochs[node] = int(epoch)

    def _unlink(self, class_key: str, node: str) -> None:
        holders = self._class_nodes.get(class_key)
        if holders and node in holders:
            holders.remove(node)
            if not holders:
                self._class_nodes.pop(class_key, None)
                self._class_rr.pop(class_key, None)

    # -- origin routing ------------------------------------------------------
    def origin_for(self, class_key: str, eligible=None) -> str | None:
        """A node already holding a lease for ``class_key`` (round-robin
        over holders, filtered by ``eligible``), or None — the caller
        falls back to global scheduling and grants the class there.

        Holders whose epoch was bumped since their last grant (revoked
        but not yet re-granted) are skipped: their grant set is fenced
        raylet-side, so routing repeat-class traffic there can only
        spill back.  They rejoin the rotation on the next ``grant``.
        """
        holders = self._class_nodes.get(class_key)
        if not holders:
            return None
        rr = self._class_rr.get(class_key, 0)
        n = len(holders)
        for off in range(n):
            node = holders[(rr + off) % n]
            if self._epochs.get(node, 0) > self._granted_epoch.get(node, -1):
                continue        # revoked since last grant: fenced
            if eligible is None or eligible(node):
                self._class_rr[class_key] = (rr + off + 1) % n
                return node
        return None

    # -- introspection -------------------------------------------------------
    def stats(self) -> dict:
        return {
            "leases_issued": self.leases_issued,
            "lease_revocations": self.revocations,
            "nodes_with_grants": len(self._grants),
            "classes_tracked": len(self._class_nodes),
        }
