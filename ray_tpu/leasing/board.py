"""The budget board: the beat -> grantor seam of the closed dispatch loop.

The fused scheduling beat prices per-(class, node) lease budgets on
device (``ops.hybrid_kernel.fused_beat`` / ``ShardPlane.fused_beat``)
and the raylet's delta engine lands them host-side in the beat's single
readback (``DeltaScheduler.last_budgets``).  The head's ``AgentHub``,
which sizes ``LeaseGrantor.grant`` calls, runs in the same process as a
raylet in every colocated deployment (and always in the sim) — so the
seam between them is a process-wide board, not an RPC:

    beat (device) -> packed readback -> raylet publishes rows here
                                   -> AgentHub.sync looks up (class, row)
                                   -> grantor.grant(node, class, budget)
                                   -> raylet LocalLeaseCache admits

Rows are keyed by the lease class-key string (the sorted
``name:count`` join of ``runtime.node_agent._lease_class_key``) and
indexed by CRM row — both sides of the seam already speak those
coordinates.  When the head is NOT colocated with a beat-running raylet
the board simply never fills and ``AgentHub`` falls back to the host
heuristic (the ``lease_budget_source`` knob's documented fallback).

Thread safety: the raylet's scheduler loop publishes while head RPC
threads read; everything is behind one lock and ``publish`` replaces
the whole row map atomically (readers never see a half-written beat).
"""

from __future__ import annotations

import threading

import numpy as np

__all__ = ["BudgetBoard", "budget_board"]


class BudgetBoard:
    """Process-wide (class-key -> per-CRM-row budget vector) board."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._seq = 0
        self._rows: dict[str, np.ndarray] = {}
        self.publishes = 0
        self.hits = 0
        self.misses = 0

    def publish(self, seq: int, rows: dict[str, np.ndarray]) -> None:
        """Replace the board with one beat's budget rows.

        ``seq`` is the publishing engine's beat sequence; the board
        keeps the max seen (several engines may publish — last beat
        wins, which is correct because every beat prices ALL resident
        classes from the full mirror).
        """
        with self._lock:
            self._seq = max(self._seq, int(seq))
            self._rows = dict(rows)
            self.publishes += 1

    def budget_for(self, class_key: str, row: int) -> int | None:
        """Beat-emitted budget for one (lease class, CRM row), or None
        when the board has no opinion (class not resident on the beat,
        row out of the beat's range, or no beat has published)."""
        with self._lock:
            vec = self._rows.get(class_key)
            if vec is None or not 0 <= int(row) < len(vec):
                self.misses += 1
                return None
            self.hits += 1
            return int(vec[int(row)])

    def seq(self) -> int:
        with self._lock:
            return self._seq

    def clear(self) -> None:
        """Drop all rows and counters (test isolation)."""
        with self._lock:
            self._seq = 0
            self._rows = {}
            self.publishes = self.hits = self.misses = 0

    def stats(self) -> dict:
        with self._lock:
            return {"budget_board_seq": self._seq,
                    "budget_board_classes": len(self._rows),
                    "budget_board_publishes": self.publishes,
                    "budget_board_hits": self.hits,
                    "budget_board_misses": self.misses}


_BOARD = BudgetBoard()


def budget_board() -> BudgetBoard:
    """The process singleton (head and raylet sides share it)."""
    return _BOARD
