"""The lease plane: decentralized steady-state dispatch.

Upstream Ray moves steady-state scheduling off the GCS with a two-level
core-worker -> raylet lease scheme (SURVEY.md §1): a raylet holding a
lease for a resource class grants repeat submissions locally, and only
misses travel to the head.  This package is that scheme's kernel,
shared by the live runtime (``runtime/node_agent.py`` +
``runtime/head.py``) and the simulator (``sim/cluster.py``):

- :class:`LeaseGrantor` — head-side single source of truth: carves
  bounded, **epoch-stamped** per-class budgets out of CRM availability,
  routes repeat-class submissions to nodes already holding a lease, and
  **revokes by epoch bump** when a node goes quiet, drains, or dies.
- :class:`LocalLeaseCache` — raylet-side grant authority: admits tasks
  against the leased budgets without touching the head, spills misses
  and conflicts back, and **self-fences** when head contact is lost for
  the death-declaration horizon (so a revoked epoch can never race a
  fresh local grant past the grace window).
- :class:`BudgetBoard` — the beat -> grantor seam: the scheduling
  beat's device-priced per-(class, node) budgets, published by the
  raylet's delta engine and read by the head when sizing grants
  (``lease_budget_source = "beat"``).

Both sides are pure state machines over injected timestamps — no clock
reads, no transport — which is what lets the simulator drive them at
10k nodes under chaos and the live agents reuse them verbatim.

Process-wide stats registry: components register a callable returning
their counters; ``/metrics``, the dashboard and ``ray_tpu status``
aggregate whatever is live in this process.
"""

from __future__ import annotations

import threading

from .board import BudgetBoard, budget_board
from .grantor import LeaseGrantor
from .local import LocalLeaseCache

__all__ = ["BudgetBoard", "LeaseGrantor", "LocalLeaseCache",
           "budget_board", "register_stats", "unregister_stats",
           "aggregate_stats"]

_STATS_LOCK = threading.Lock()
_STATS_SOURCES: dict[str, object] = {}

_COUNTER_KEYS = ("leases_granted_local", "spillbacks",
                 "lease_revocations", "leases_issued",
                 "lease_epoch_discards", "submit_batches",
                 "submit_batched_frames")


def register_stats(name: str, fn) -> None:
    """Register a zero-arg callable returning a lease-stats dict."""
    with _STATS_LOCK:
        _STATS_SOURCES[name] = fn


def unregister_stats(name: str) -> None:
    with _STATS_LOCK:
        _STATS_SOURCES.pop(name, None)


def aggregate_stats() -> dict:
    """Fold every registered source's counters into one dict (the
    ``/metrics`` + ``/api/leases`` + ``ray_tpu status`` surface)."""
    with _STATS_LOCK:
        sources = list(_STATS_SOURCES.items())
    agg: dict = {k: 0 for k in _COUNTER_KEYS}
    agg["sources"] = {}
    for name, fn in sources:
        try:
            s = dict(fn())
        except Exception:   # noqa: BLE001 — a dying source never
            continue        # breaks the scrape
        agg["sources"][name] = s
        for k in _COUNTER_KEYS:
            if isinstance(s.get(k), (int, float)):
                agg[k] += s[k]
    hits, misses = agg["leases_granted_local"], agg["spillbacks"]
    agg["lease_hit_rate"] = round(hits / (hits + misses), 4) \
        if hits + misses else 0.0
    return agg
