"""Raylet-side lease cache: the local grant authority.

A node holds one epoch-stamped snapshot of per-class budgets leased to
it by the head.  ``try_grant`` admits a task entirely locally — no head
RPC — when the snapshot covers its class with headroom; everything else
is a spillback (the caller ships the task to the head, which remains
the single source of truth).

Fencing is the safety half of revocation: once the node has gone
``fence_after_s`` without a *confirmed* head contact (the same horizon
after which the head declares it dead and revokes its epoch), the cache
refuses every grant.  Because a node's last confirmed contact is never
later than the head's last observed heartbeat, the node always fences
at or before the moment the head revokes — a grant under a revoked
epoch can only start inside the revocation grace window, never after
it.  The simulator's ``no double-executed lease`` invariant checks
exactly this.

Pure state machine: timestamps are injected, so the simulator drives it
on virtual time and the agents on the monotonic clock seam.
"""

from __future__ import annotations

from collections import OrderedDict

__all__ = ["LocalLeaseCache"]


class LocalLeaseCache:
    """Per-node lease snapshot + admission counters."""

    def __init__(self, capacity: int, fence_after_s: float,
                 overcommit: float = 2.0, max_classes: int = 64):
        self.capacity = max(1, int(capacity))
        self.fence_after_s = float(fence_after_s)
        self.overcommit = float(overcommit)
        self.max_classes = max(1, int(max_classes))
        self.epoch = 0
        # class_key -> [budget, admitted]; ordered for LRU eviction
        self._classes: OrderedDict[str, list] = OrderedDict()
        self._last_contact = 0.0
        self._admitted_total = 0
        # counters (the observability satellite's node-side half)
        self.local_grants = 0
        self.spillbacks = 0
        self.epoch_discards = 0
        self.fenced_denials = 0

    # -- head contact / epoch ------------------------------------------------
    def on_head_contact(self, now: float) -> None:
        """A round trip to the head *confirmed* (reply received)."""
        self._last_contact = now

    def fenced(self, now: float) -> bool:
        return now - self._last_contact > self.fence_after_s

    def observe_epoch(self, epoch: int) -> bool:
        """Fold the head's current epoch for this node.  Returns True
        when it advanced past ours — the head revoked: the caller must
        discard locally-queued, not-yet-started grants (the head has
        already requeued them) before granting again."""
        if epoch <= self.epoch:
            return False
        self.epoch = epoch
        self.epoch_discards += 1
        for entry in self._classes.values():
            entry[1] = 0            # head requeued everything unstarted
        self._admitted_total = 0
        return True

    # -- snapshot installation -----------------------------------------------
    def install(self, grants: dict, epoch: int) -> None:
        """Merge a head-issued grant set ``{class_key: budget}`` stamped
        with ``epoch`` (>= ours; the head never time-travels)."""
        if epoch > self.epoch:
            self.epoch = epoch
        for class_key, budget in grants.items():
            entry = self._classes.get(class_key)
            if entry is None:
                while len(self._classes) >= self.max_classes:
                    self._classes.popitem(last=False)   # LRU eviction
                self._classes[class_key] = [int(budget), 0]
            else:
                entry[0] = int(budget)
                self._classes.move_to_end(class_key)

    def holds(self, class_key: str) -> bool:
        return class_key in self._classes

    def held_classes(self) -> list[str]:
        return list(self._classes)

    # -- admission -----------------------------------------------------------
    def try_grant(self, class_key: str, now: float) -> bool:
        """Admit one task of ``class_key`` locally; False == spillback."""
        if self.fenced(now):
            self.fenced_denials += 1
            self.spillbacks += 1
            return False
        entry = self._classes.get(class_key)
        if entry is None or entry[1] >= entry[0] or \
                self._admitted_total >= int(self.capacity *
                                            self.overcommit):
            self.spillbacks += 1
            return False
        entry[1] += 1
        self._admitted_total += 1
        self._classes.move_to_end(class_key)
        self.local_grants += 1
        return True

    def release(self, class_key: str) -> None:
        """A locally-admitted task finished (or was handed back)."""
        entry = self._classes.get(class_key)
        if entry is not None and entry[1] > 0:
            entry[1] -= 1
        if self._admitted_total > 0:
            self._admitted_total -= 1

    # -- introspection -------------------------------------------------------
    def stats(self) -> dict:
        return {
            "leases_granted_local": self.local_grants,
            "spillbacks": self.spillbacks,
            "lease_epoch_discards": self.epoch_discards,
            "fenced_denials": self.fenced_denials,
            "epoch": self.epoch,
            "classes_held": len(self._classes),
            "admitted": self._admitted_total,
        }
