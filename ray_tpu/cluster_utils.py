"""Simulated multi-node cluster — the ``cluster_utils.Cluster`` analogue.

Reference parity: upstream's ``python/ray/cluster_utils.py::Cluster`` starts
N real raylets + one GCS on a single machine with fabricated ``--resources``
JSON; all multi-node scheduling/spillback/PG/failure tests run against it
(SURVEY.md §4 simulated multi-node tier; mount empty).

Here a node = one ``Raylet`` (its own worker-process pool + its row in the
shared ``ClusterResourceManager``).  The process-local shared CRM/store IS
the GCS + ray_syncer of the single-host form: every raylet schedules
against the same authoritative resource view, so spillback converges in one
hop (the policy is deterministic in global row order — the destination
raylet recomputes the same answer and dispatches locally).
"""

from __future__ import annotations

import os
import tempfile
import threading
import uuid

import numpy as np

from .common.config import get_config
from .common.ids import NodeID
from .common.resources import NodeResources
from .runtime.object_directory import ObjectDirectory
from .runtime.object_ref import install_counter, uninstall_counter
from .runtime.object_store import MemoryStore, ObjectLostError
from .runtime.placement_group_manager import PlacementGroupManager
from .runtime.pull_manager import PullManager
from .runtime.raylet import Raylet
from .runtime.recovery import ObjectRecoveryManager
from .runtime.reference_counter import ReferenceCounter
from .runtime.task_manager import TaskManager
from .scheduling.cluster_resources import ClusterResourceManager
from .common import clock as _clk

# default simulated link rates (MB/s): same-node "transfers" are free;
# inter-node defaults to a 10 GB/s ICI-class link until overridden via
# set_node_bandwidth
LOCAL_BW_MBPS = 1_000_000
DEFAULT_BW_MBPS = 10_000


def reap_stale_arenas(shm_dir: str = "/dev/shm") -> int:
    """Unlink arena files left by dead sessions (a killed owner never runs
    ``Arena.close``; upstream similarly cleans stale per-session state at
    startup).  Arena names embed the owner pid: ``rt_arena_<pid>_<tag>``.
    Returns the number of files reaped."""
    reaped = 0
    try:
        names = os.listdir(shm_dir)
    except OSError:
        return 0
    for name in names:
        if not name.startswith("rt_arena_"):
            continue
        parts = name.split("_")
        try:
            pid = int(parts[2])
        except (IndexError, ValueError):
            continue
        if pid == os.getpid():
            continue
        try:
            os.kill(pid, 0)             # owner alive?
        except ProcessLookupError:
            try:
                os.unlink(os.path.join(shm_dir, name))
                reaped += 1
            except OSError:
                pass
        except PermissionError:
            pass                        # alive, owned by another user
    return reaped


def _make_arena(session_dir: str):
    """Create the shared-memory arena backing the object store (plasma
    analogue); /dev/shm when available, session dir otherwise."""
    from .native import Arena
    cfg = get_config()
    capacity = cfg.object_store_memory_mb * 1024 * 1024
    name = f"rt_arena_{os.getpid()}_{uuid.uuid4().hex[:8]}"
    try:
        reap_stale_arenas("/dev/shm")
        return Arena(os.path.join("/dev/shm", name), capacity, create=True)
    except OSError:
        return Arena(os.path.join(session_dir, name), capacity, create=True)


class Cluster:
    def __init__(self):
        self._lock = threading.RLock()
        self.crm = ClusterResourceManager()
        self.session_dir = tempfile.mkdtemp(prefix="ray_tpu_session_")
        self.arena = _make_arena(self.session_dir)
        spill_dir = get_config().object_spilling_dir or \
            os.path.join(self.session_dir, "spill")
        self.store = MemoryStore(arena=self.arena, spill_dir=spill_dir)
        self.task_manager = TaskManager()     # ownership is driver-central
        self.fn_registry: dict[str, bytes] = {}
        self.raylets: dict[int, Raylet] = {}  # row -> raylet
        self.actor_manager = None             # attached by the runtime
        self.pg_manager = PlacementGroupManager(self)
        self.directory = ObjectDirectory()
        # GCS control-plane siblings: namespaced KV + pubsub broker
        from .runtime.kv_pubsub import KVStore, PubSub
        self.kv = KVStore()
        self.pubsub = PubSub()
        from .runtime.runtime_env import RuntimeEnvManager
        self.runtime_env_manager = RuntimeEnvManager(self.session_dir)
        self.job_runtime_env = None           # set by api.init(runtime_env=)
        self.on_job_env_change = None         # AgentHub policy push hook
        self.default_namespace = ""           # set by api.init(namespace=):
        #   worker-side named-actor ops inherit it (workers carry no
        #   namespace of their own)
        # node-bandwidth matrix (MB/s) — the pull cost model's input;
        # grows with the CRM row space
        self.bandwidth_mbps = np.zeros((0, 0), dtype=np.int32)
        # wire-level object plane: this process's endpoint (serves the
        # head store once a server attaches it) + row -> remote plane
        # address for agent nodes (None = shares the head store)
        from .runtime.object_plane import ObjectPlane
        self.plane = ObjectPlane(self.store)
        self.planes: dict[int, str | None] = {}
        self.pull_manager = PullManager(self)
        # 1->N sibling of the pull manager: relay-tree weight
        # distribution over the same bandwidth matrix
        from .broadcast import BroadcastManager
        self.broadcasts = BroadcastManager(self)
        self.recovery = ObjectRecoveryManager(self)
        # owner-side reference counting: ObjectRefs created in this
        # (driver) process drive reclamation of out-of-scope objects
        self.ref_counter = ReferenceCounter()
        self.ref_counter.attach(self._reclaim_object, self.store.contains,
                                self.store.on_ready, self._expects_seal)
        install_counter(self.ref_counter)
        self.autoscaler = None          # attached by start_autoscaler
        from .runtime.events import EventLog
        self.events = EventLog(self.session_dir)
        from .runtime.health import HealthCheckManager
        self.health = HealthCheckManager(self)
        self.health.start()
        # elastic serve<->batch capacity loaning (LOANED rows atop the
        # CRM); ticked from the autoscaler round and the health round
        from .serve.loaning import CapacityLoanManager
        self.loans = CapacityLoanManager(self)
        port = get_config().metrics_export_port
        self.metrics = None
        if port:
            from .runtime.metrics import MetricsExporter
            self.metrics = MetricsExporter(self, port)
        dash_port = get_config().dashboard_port
        self.dashboard = None
        if dash_port:
            from .runtime.dashboard import Dashboard
            self.dashboard = Dashboard(self, dash_port,
                                       host=get_config().dashboard_host)
        self._head_row: int | None = None
        self._stack_waits: dict[str, tuple] = {}    # live stack dumps
        # node drain lifecycle (ALIVE -> DRAINING -> removed/dead):
        # NodeID -> status dict; completed drains stay for status queries
        self._drains: dict[NodeID, dict] = {}

    def _reclaim_object(self, oid) -> None:
        """Refcount hit zero cluster-wide: free the object everywhere and
        release producing-task lineage once all its returns are dead."""
        rows = self.directory.locations(oid)
        self.store.delete([oid])
        self.directory.drop([oid])
        # copies on agent planes free over the wire (best-effort, off
        # the refcount thread)
        for row in rows:
            addr = self.planes.get(row)
            if addr is not None:
                self.plane.free_on(addr, [oid])
        self.task_manager.on_return_reclaimed(oid)

    # -- live worker stack sampling (SURVEY §5.1(c): the dashboard's
    # py-spy integration, rebuilt on the worker reader thread) ---------------
    def dump_worker_stacks(self, row: int | None = None,
                           timeout: float = 5.0) -> dict:
        """Ask every live worker (one node's with ``row``) what it is
        doing RIGHT NOW: {(row, worker_index): all-thread stack text}.
        Workers answer from their reader thread, so one wedged in user
        code still reports — that wedge is exactly what this shows."""
        import uuid
        req = uuid.uuid4().hex
        ev = threading.Event()
        out: dict = {}
        expected = [0]
        self._stack_waits[req] = (ev, out, expected)
        try:
            with self._lock:
                targets = [(r, ry) for r, ry in self.raylets.items()
                           if row is None or r == row]
            sent = 0
            for r, raylet in targets:
                with raylet.pool._lock:
                    workers = list(raylet.pool._workers)
                for w in workers:
                    if not w.dead and w.ready and \
                            w.send(("dump_stacks", req)):
                        sent += 1
            expected[0] = sent
            if sent and len(out) < sent:
                ev.wait(timeout)
            return dict(out)
        finally:
            self._stack_waits.pop(req, None)

    def _on_stacks_reply(self, req: str, row: int, index: int,
                         text: str) -> None:
        entry = self._stack_waits.get(req)
        if entry is None:
            return          # late reply after timeout: drop
        ev, out, expected = entry
        out[(row, index)] = text
        if expected[0] and len(out) >= expected[0]:
            ev.set()

    def set_job_runtime_env(self, env: dict | None) -> None:
        """Install the job-level default runtime_env and notify any
        attached agent hub: autonomous agents are env-blind, so a job
        env appearing must gate their fast path off."""
        self.job_runtime_env = env
        hook = self.on_job_env_change
        if hook is not None:
            hook(env)

    def _expects_seal(self, oid) -> bool:
        """Will an absent object ever seal?  Only a pending task return
        can; puts and deleted markers never re-seal."""
        if oid.is_put():
            return False
        rec = self.task_manager.get(oid.task_id())
        return rec is not None and not rec.done

    # -- topology -----------------------------------------------------------
    def add_node(self, resources: dict[str, float] | None = None,
                 num_workers: int = 2,
                 labels: dict[str, str] | None = None,
                 wait: bool = True, spawner=None,
                 inline_objects: bool = False,
                 plane_address: str | None = None) -> NodeID:
        resources = resources or {"CPU": 2, "memory": 2}
        node_id = NodeID.from_random()
        with self._lock:
            row = self.crm.add_node(node_id,
                                    NodeResources(resources, labels))
            self._grow_bandwidth(row + 1)
            if plane_address is not None:
                self.planes[row] = plane_address
            raylet = Raylet(node_id, self, num_workers, spawner=spawner,
                            inline_objects=inline_objects,
                            plane_address=plane_address)
            raylet.actor_manager = self.actor_manager
            self.raylets[row] = raylet
            if self._head_row is None:
                self._head_row = row
        try:
            raylet.start()
        except BaseException:
            # a remote spawner can fail mid-start (agent gone): unwind
            # the CRM row so the scheduler never places onto a node
            # whose raylet never ran
            with self._lock:
                self.raylets.pop(row, None)
                self.planes.pop(row, None)
                self.crm.remove_node(node_id)
                if self._head_row == row:
                    self._head_row = None
            raise
        self.events.emit("node", "node_added", node_row=row,
                         node_id=node_id.hex(), resources=resources)
        self.pubsub.publish("node", {"event": "added", "row": row,
                                     "node_id": node_id.hex()})
        if wait and num_workers:
            raylet.pool.wait_ready(num_workers, timeout=60.0)
        # wake every existing raylet: tasks parked as infeasible may now
        # have a feasible node (reference: node arrival triggers a
        # scheduling round on every raylet via the resource broadcast)
        self.wake_raylets(exclude=raylet)
        return node_id

    def add_remote_node(self, resources: dict[str, float] | None = None,
                        num_workers: int = 2, spawner=None,
                        labels: dict[str, str] | None = None,
                        plane_address: str | None = None) -> NodeID:
        """A node whose worker processes live behind a node agent on
        another machine (``runtime/node_agent.py``): same raylet, same
        scheduling row — only the process transport differs.  The
        agent ALWAYS runs its own arena (``plane_address`` is
        mandatory): objects move arena-to-arena over the object plane,
        exec/get frames carry by-reference descriptors the agent
        resolves locally.  The legacy relay-only mode (every payload
        in-band through the head) is gone — one data-plane code path."""
        if plane_address is None:
            raise ValueError(
                "remote nodes require a plane_address: relay-only "
                "agents (payloads in-band through the head) were "
                "removed — run a NodeAgent, which always serves an "
                "object plane")
        return self.add_node(resources=resources, num_workers=num_workers,
                             labels=labels, spawner=spawner,
                             inline_objects=True,
                             plane_address=plane_address)

    def _grow_bandwidth(self, n: int) -> None:
        """Extend the bandwidth matrix to cover ``n`` rows (caller holds
        the lock)."""
        old = self.bandwidth_mbps.shape[0]
        if n <= old:
            return
        bw = np.full((n, n), DEFAULT_BW_MBPS, dtype=np.int32)
        np.fill_diagonal(bw, LOCAL_BW_MBPS)
        bw[:old, :old] = self.bandwidth_mbps
        self.bandwidth_mbps = bw

    def set_node_bandwidth(self, src_row: int, dst_row: int,
                           mbps: int, symmetric: bool = True) -> None:
        """Override a link rate in the pull cost model (tests/operators)."""
        with self._lock:
            self.bandwidth_mbps[src_row, dst_row] = mbps
            if symmetric:
                self.bandwidth_mbps[dst_row, src_row] = mbps

    def register_location(self, oid, row: int) -> None:
        """Record that a freshly sealed plasma-routed object was born on
        ``row`` (in-band values have no locations — they ship with specs)."""
        kind, _ = self.store.plasma_info(oid)
        if kind in ("shm", "spill"):
            self.directory.add_location(oid, row)

    def seal_serialized(self, oid, data, row: int) -> None:
        """Seal a serialized payload born on ``row`` with the directory
        entry registered BEFORE the seal: sealing wakes dependent-task
        placement and driver gets, which read the directory for locality
        — registering after would race an empty entry."""
        plasma = self.store.routes_to_plasma(len(data))
        if plasma:
            self.directory.add_location(oid, row)
        self.store.put_serialized(oid, data)
        if plasma and self.store.plasma_info(oid)[0] not in ("shm",
                                                            "spill"):
            self.directory.drop([oid])  # store-full in-band fallback
        elif not plasma:
            self.register_location(oid, row)

    def remove_node(self, node_id: NodeID) -> None:
        """Simulate node death: resources vanish, running tasks retried
        elsewhere (or failed), queued tasks re-routed, actors restarted or
        declared dead, plasma objects whose only copy lived there are LOST
        (SURVEY §5.3 failure semantics)."""
        with self._lock:
            row = self.crm.row_of(node_id)
            if row is None or row == self._head_row:
                raise ValueError("cannot remove head node or unknown node")
            raylet = self.raylets.pop(row)
            self.planes.pop(row, None)
            self.crm.remove_node(node_id)
        self.events.emit("node", "node_removed", node_row=row,
                         node_id=node_id.hex())
        self.pubsub.publish("node", {"event": "removed", "row": row,
                                     "node_id": node_id.hex()})
        lost = self.directory.on_node_removed(row)
        self.pull_manager.on_objects_lost(lost)
        from .runtime.serialization import RayTaskError
        for oid in lost:
            # a lost object sealed on an agent plane left a metadata-only
            # RemoteEntry in the head store: drop it BEFORE re-driving
            # lineage so readers wait for the fresh seal (or see the
            # poison below) instead of materializing stale metadata
            self.store.drop_remote_entry(oid)
            # lineage first: reconstructable objects re-execute their
            # producing task and re-seal; only unrecoverable ones poison
            # (SURVEY §5.3 — reconstruction, else ObjectLostError)
            if self.recovery.recover(oid):
                continue
            self.store.poison(oid, RayTaskError(
                "object", f"object {oid.hex()[:12]} is lost: the node "
                "holding its only copy died", ObjectLostError(
                    f"object {oid.hex()[:12]} lost with node "
                    f"{node_id.hex()[:12]}")))
        self.pg_manager.on_node_removed(row)
        raylet.drain_for_removal(self.head())
        # wake every SURVIVING raylet: a task parked infeasible behind
        # a pin/label on the removed node must re-reach placement so
        # the dead-node fail-fast (or a re-place) fires — membership
        # changes re-trigger scheduling in both directions, like
        # add_node's wake (reference: the resource broadcast)
        self.wake_raylets()

    # -- graceful drain (ALIVE -> DRAINING -> removed/dead) ------------------
    def drain_node(self, node_id: NodeID, reason: str = "",
                   deadline_s: float | None = None) -> dict:
        """Gracefully retire a node (preemption notice, scale-down).

        The node is masked out of every placement view immediately (no
        new leases or PG bundles land on it), its queued/pipelined work
        re-enters global scheduling, its PG bundles re-place atomically
        elsewhere, and sole-copy plasma objects migrate to the head.
        Running tasks finish normally.  A monitor thread removes the
        node once it is empty — or at ``deadline_s``, whichever comes
        first; a node that DIES mid-drain converges through the health
        manager's dead path.  Returns the drain status dict immediately
        (poll ``drain_status`` or join via ``wait_for_drain``)."""
        if deadline_s is None:
            deadline_s = get_config().drain_deadline_s
        with self._lock:
            row = self.crm.row_of(node_id)
            if row is None or row == self._head_row:
                raise ValueError("cannot drain head node or unknown node")
            st = self._drains.get(node_id)
            if st is not None and st["state"] == "DRAINING":
                return self._drain_view(st)     # idempotent
            self.crm.set_draining(node_id, True)
            st = {"node_id": node_id.hex(), "row": row, "reason": reason,
                  "deadline_s": float(deadline_s), "state": "DRAINING",
                  "outcome": None, "started": _clk.monotonic(),
                  "migrated_objects": 0, "displaced_groups": 0}
            self._drains[node_id] = st
            raylet = self.raylets.get(row)
        self.events.emit("node", "node_draining", node_row=row,
                         node_id=node_id.hex(), reason=reason,
                         deadline_s=deadline_s)
        # drain notice BEFORE displacing work: subscribers (the elastic
        # trainer) get the chance to checkpoint-and-resize proactively
        self.pubsub.publish("node", {"event": "draining", "row": row,
                                     "node_id": node_id.hex(),
                                     "reason": reason,
                                     "deadline_s": deadline_s})
        st["displaced_groups"] = self.pg_manager.on_node_draining(row)
        if raylet is not None:
            raylet.start_graceful_drain()
            # remote node: tell its agent to stop autonomous local
            # dispatch and hand queued leases back (best-effort — a
            # dead agent converges via the health manager anyway)
            sp = getattr(raylet.pool, "_spawner", None)
            if sp is not None and hasattr(sp, "drain_remote"):
                try:
                    sp.drain_remote()
                except Exception:   # noqa: BLE001
                    pass
        self.wake_raylets()         # requeued backlog needs a round
        thread = threading.Thread(target=self._drain_monitor,
                                  args=(node_id, st), daemon=True,
                                  name=f"drain-{row}")
        st["_thread"] = thread
        thread.start()
        return self._drain_view(st)

    def _drain_monitor(self, node_id: NodeID, st: dict) -> None:
        poll = max(get_config().drain_poll_ms, 1) / 1000.0
        deadline = st["started"] + st["deadline_s"]
        row = st["row"]
        from .runtime.pull_manager import PullPriority
        inflight: dict = {}         # oid -> pull in flight
        mlock = threading.Lock()

        def _migrated(oid):
            def cb(ok: bool) -> None:
                with mlock:
                    inflight.pop(oid, None)
                    if ok:
                        st["migrated_objects"] += 1
            return cb

        while True:
            with self._lock:
                gone = self.crm.row_of(node_id) is None
                raylet = self.raylets.get(row)
            if gone:        # died mid-drain: health manager removed it
                self._finish_drain(node_id, st, "dead")
                return
            # migrate sole copies to the head — re-scanned every tick
            # because RUNNING tasks keep sealing new objects mid-drain
            sole = self.directory.sole_copies_on(row)
            for oid in sole:
                with mlock:
                    if oid in inflight:
                        continue
                    inflight[oid] = True
                _kind, size = self.store.plasma_info(oid)
                if self.pull_manager.request_pull(
                        oid, size, self._head_row, PullPriority.TASK_ARG,
                        callback=_migrated(oid)):
                    with mlock:     # already at the head
                        inflight.pop(oid, None)
            with mlock:
                migrating = bool(inflight)
            if raylet is None or (raylet.drain_empty() and not migrating
                                  and not sole):
                outcome = "drained"
            elif _clk.monotonic() >= deadline:
                outcome = "deadline"    # grace expired: forced removal
            else:
                _clk.sleep(poll)
                continue
            try:
                self.remove_node(node_id)
            except (ValueError, KeyError):
                outcome = "dead"        # node death raced the removal
            self._finish_drain(node_id, st, outcome)
            return

    def _finish_drain(self, node_id: NodeID, st: dict,
                      outcome: str) -> None:
        st["outcome"] = outcome
        st["state"] = "DEAD" if outcome == "dead" else "REMOVED"
        st["elapsed_s"] = round(_clk.monotonic() - st["started"], 3)
        self.events.emit("node", "node_drain_finished",
                         node_row=st["row"], node_id=st["node_id"],
                         outcome=outcome, elapsed_s=st["elapsed_s"],
                         migrated_objects=st["migrated_objects"],
                         displaced_groups=st["displaced_groups"])

    @staticmethod
    def _drain_view(st: dict) -> dict:
        return {k: v for k, v in st.items() if not k.startswith("_")}

    def drain_status(self, node_id: NodeID | None = None):
        """Status dict for one node's drain (None if never drained), or
        every drain this cluster has seen."""
        with self._lock:
            if node_id is not None:
                st = self._drains.get(node_id)
                return None if st is None else self._drain_view(st)
            return [self._drain_view(st) for st in self._drains.values()]

    def is_draining(self, node_id: NodeID) -> bool:
        with self._lock:
            st = self._drains.get(node_id)
            return st is not None and st["state"] == "DRAINING"

    def wait_for_drain(self, node_id: NodeID,
                       timeout: float | None = None) -> dict | None:
        """Block until a started drain finishes; returns its status."""
        with self._lock:
            st = self._drains.get(node_id)
        if st is None:
            return None
        thread = st.get("_thread")
        if thread is not None:
            thread.join(timeout)
        return self._drain_view(st)

    def wake_raylets(self, exclude=None) -> None:
        """Re-trigger every raylet's scheduling loop (cluster
        membership/resource events): snapshot under the lock, notify
        outside it."""
        with self._lock:
            raylets = [r for r in self.raylets.values()
                       if r is not exclude]
        for r in raylets:
            r._notify_dirty()

    def start_autoscaler(self, node_types, **kwargs) -> "StandardAutoscaler":
        """Attach + start the autoscaler runtime loop (reference:
        the monitor process running StandardAutoscaler.update)."""
        from .autoscaler.autoscaler import StandardAutoscaler
        self.autoscaler = StandardAutoscaler(self, node_types, **kwargs)
        self.autoscaler.start()
        return self.autoscaler

    def head(self) -> Raylet:
        return self.raylets[self._head_row]

    def raylet_of_row(self, row: int) -> Raylet | None:
        with self._lock:
            return self.raylets.get(row)

    def stream_ack(self, task_id, consumed: int) -> None:
        """Route a streaming-generator consumption ack to whichever
        worker runs the producer — a task's raylet or a streaming actor
        call's dedicated worker (best-effort)."""
        if self.actor_manager is not None and \
                self.actor_manager.stream_ack(task_id, consumed):
            return
        with self._lock:
            raylets = list(self.raylets.values())
        for r in raylets:
            if r.stream_ack(task_id, consumed):
                return

    def stream_close(self, task_id, consumed: int) -> None:
        """Consumer finished/abandoned a stream: cancel the producer
        cooperatively (it stops yielding at its next backpressure
        check) and reclaim sealed-but-unconsumed items everywhere."""
        orphans = self.task_manager.stream_close(task_id, consumed)
        cancelled = (self.actor_manager is not None
                     and self.actor_manager.stream_cancel(task_id))
        if not cancelled:
            with self._lock:
                raylets = list(self.raylets.values())
            for r in raylets:
                if r.stream_cancel(task_id):
                    break
        for oid in orphans:
            if self.store.contains(oid):
                # through the counter, not _reclaim_object directly:
                # refs pickled INSIDE sealed-but-unconsumed items must
                # release with them (contained-entry bookkeeping)
                self.ref_counter.force_reclaim(oid)

    def cancel_task(self, task_id, force: bool = False) -> bool:
        """Cancel wherever the task lives — any node's queues, running
        set, or agent lease (drivers and the client-mode head RPC both
        route here)."""
        head = self.head()
        if head.cancel(task_id, force=force):
            return True
        with self._lock:
            raylets = list(self.raylets.values())
        for r in raylets:
            if r is not head and r.cancel(task_id, force=force):
                return True
        return False

    # -- routing (spillback) ------------------------------------------------
    def route_local(self, row: int, task_id) -> bool:
        """Deliver a PLACED task into the target node's local dispatch
        queue (the task is scheduled exactly once)."""
        return self.route_local_batch(row, [task_id])

    def route_local_batch(self, row: int, task_ids: list) -> bool:
        """Deliver a beat's whole per-node lease group in one call (the
        fused schedule->lease->dispatch hand-off: no per-task boundary
        crossing between the placement readback and the target's
        dispatch queue)."""
        target = self.raylet_of_row(row)
        if target is None:
            return False
        target.enqueue_local_batch(list(task_ids))
        return True

    # -- GCS persistence -----------------------------------------------------
    def save_gcs_snapshot(self, path: str) -> str:
        """Persist the GCS metadata plane — KV table, function/class
        registry, live named-actor creation specs — the reference's
        Redis-backed GCS fault tolerance (``RedisStoreClient``,
        SURVEY.md §5.4).  Object-store contents and running tasks are
        NOT persisted (upstream behaves the same: objects re-derive
        from lineage or are lost; detached actors RESTART)."""
        import pickle
        # actor specs BEFORE the registry copy: create_actor registers
        # class bytes before the record becomes visible, so every spec
        # captured here is guaranteed resolvable in the later registry
        # snapshot (the reverse order can capture an actor whose bytes
        # missed the copy)
        named = (self.actor_manager.named_actor_specs()
                 if self.actor_manager else [])
        snap = {"named_actors": named,
                "fn_registry": dict(self.fn_registry),
                "kv": self.kv.snapshot()}
        # writer-unique tmp name: two concurrent savers (persist tick
        # vs final stop snapshot) must not truncate each other's file
        # and promote a torn pickle
        import threading as _threading
        tmp = f"{path}.tmp.{os.getpid()}.{_threading.get_ident()}"
        with open(tmp, "wb") as f:
            pickle.dump(snap, f)
        os.replace(tmp, path)       # atomic: no torn snapshot
        return path

    def restore_gcs_snapshot(self, path: str) -> None:
        """Load a snapshot into THIS cluster: KV + registry restore
        in-place; named actors are RE-CREATED (fresh incarnation, ctor
        re-runs — reference detached-actor restart semantics).
        Requires an attached actor_manager and at least one node."""
        import pickle

        from .common.ids import ActorID, JobID
        from .runtime.serialization import deserialize
        with open(path, "rb") as f:
            snap = pickle.load(f)
        # validate EVERYTHING before mutating anything: a failed restore
        # must not leave the cluster half its own state, half snapshot
        if self.actor_manager is None and snap["named_actors"]:
            raise RuntimeError("attach an actor manager (ray_tpu.init) "
                               "before restoring named actors")
        for spec in snap["named_actors"]:
            if spec["cls_id"] not in snap["fn_registry"]:
                raise RuntimeError(
                    f"snapshot is missing class bytes for named actor "
                    f"{spec['name']!r}")
        self.kv.restore(snap["kv"])
        for fn_id, fn_bytes in snap["fn_registry"].items():
            self.fn_registry.setdefault(fn_id, fn_bytes)
        job_id = JobID.next()
        skipped = []
        for spec in snap["named_actors"]:
            ns = spec.get("namespace", "")
            if self.actor_manager.get_by_name(spec["name"],
                                              ns) is not None:
                skipped.append(spec["name"])    # live actor wins
                continue
            args, kwargs = deserialize(spec["init"])
            self.actor_manager.create_actor(
                ActorID.of(job_id), spec["cls_id"],
                self.fn_registry.get(spec["cls_id"]), args, kwargs,
                spec["max_restarts"], spec["max_task_retries"],
                spec["name"], resources=spec["resources"],
                runtime_env=spec["runtime_env"],
                namespace=ns, lifetime=spec.get("lifetime"))
        if skipped:
            self.events.emit("gcs", "restore_skipped_actors",
                             names=skipped)

    # -- teardown -----------------------------------------------------------
    def stop(self) -> None:
        self.health.shutdown()
        if self.autoscaler is not None:
            self.autoscaler.shutdown()
        uninstall_counter(self.ref_counter)
        self.ref_counter.shutdown()
        self.pg_manager.shutdown()
        self.pull_manager.shutdown()
        self.broadcasts.shutdown()
        self.plane.shutdown()
        with self._lock:
            raylets = list(self.raylets.values())
            self.raylets.clear()
        for r in raylets:
            r.stop()
        if self.metrics is not None:
            self.metrics.shutdown()
        if self.dashboard is not None:
            self.dashboard.shutdown()
        self.events.close()
        self.arena.close()
        import shutil
        shutil.rmtree(self.session_dir, ignore_errors=True)
