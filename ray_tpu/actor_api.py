"""Actor API — placeholder; full actor runtime lands with the actor
milestone (SURVEY.md §3.4)."""

from __future__ import annotations


def make_actor_class(cls, options):
    raise NotImplementedError(
        "actor support is not wired up yet (next milestone)")
