"""Actor API: @remote classes, handles, ordered method calls.

Reference parity: ``python/ray/actor.py`` — ``ActorClass`` (from decorating
a class), ``ActorHandle`` with dynamic method accessors, ``.options(...)``
(name, max_restarts, max_task_retries), named-actor lookup
(``ray.get_actor``), graceful ``__ray_terminate__`` — SURVEY.md §3.4;
mount empty.  The lifecycle/ordering machinery lives in
``runtime/actor_manager.py``.
"""

from __future__ import annotations

import os
from typing import Any

from .common.ids import ActorID, JobID, ObjectID, TaskID
from .runtime.object_ref import ObjectRef
from .runtime.serialization import serialize


def _runtime():
    from . import api
    return api._get_runtime()


class ActorMethod:
    def __init__(self, handle: "ActorHandle", name: str,
                 num_returns: int = 1,
                 concurrency_group: str | None = None):
        self._handle = handle
        self._name = name
        self._num_returns = num_returns
        self._group = concurrency_group

    def options(self, *, num_returns: int | None = None,
                concurrency_group: str | None = None) -> "ActorMethod":
        return ActorMethod(
            self._handle, self._name,
            num_returns if num_returns is not None
            else self._num_returns,
            concurrency_group if concurrency_group is not None
            else self._group)

    def remote(self, *args, **kwargs):
        from .util.tracing import context_for_new_task
        rt = _runtime()
        actor_id = self._handle._actor_id
        job_id = actor_id.job_id()
        task_id = TaskID.for_task(job_id, actor_id)
        trace_ctx = context_for_new_task(task_id)
        # "streaming": the method is a generator; items seal
        # incrementally and the caller gets an ObjectRefGenerator
        # (reference: streaming actor calls share the generator protocol)
        num_returns = -1 if self._num_returns == "streaming" \
            else self._num_returns
        if rt.is_driver:
            rt.actor_manager.submit(actor_id, task_id, self._name, args,
                                    kwargs, num_returns,
                                    trace_ctx=trace_ctx,
                                    concurrency_group=self._group)
        else:
            rt.submit_actor_call(actor_id, task_id, self._name, args,
                                 kwargs, num_returns, trace_ctx,
                                 concurrency_group=self._group)
        if num_returns == -1:
            from .runtime.object_ref import ObjectRefGenerator
            return ObjectRefGenerator(task_id, rt)
        refs = [ObjectRef(ObjectID.for_task_return(task_id, i + 1))
                for i in range(num_returns)]
        return refs[0] if num_returns == 1 else refs

    def __call__(self, *a, **k):
        raise TypeError(
            f"actor method {self._name} cannot be called directly; "
            "use .remote()")


class ActorHandle:
    def __init__(self, actor_id: ActorID):
        self._actor_id = actor_id

    def __getattr__(self, name: str) -> ActorMethod:
        if name.startswith("_"):
            raise AttributeError(name)
        return ActorMethod(self, name)

    def __reduce__(self):
        return (ActorHandle, (self._actor_id,))

    def __repr__(self):
        return f"ActorHandle({self._actor_id.hex()[:12]}…)"

    def __ray_terminate__(self):
        """Graceful stop: queued behind pending method calls."""
        return ActorMethod(self, "__ray_terminate__").remote()


class ActorClass:
    def __init__(self, cls: type | None, cls_bytes: bytes | None = None,
                 name: str | None = None, cls_id: str | None = None,
                 options: dict[str, Any] | None = None):
        self._cls = cls
        self._cls_bytes = cls_bytes
        self._cls_name = name or getattr(cls, "__name__", "Actor")
        self._cls_id = cls_id or os.urandom(16).hex()
        self._options = dict(options or {})

    def options(self, **options) -> "ActorClass":
        merged = dict(self._options)
        merged.update(options)
        return ActorClass(self._cls, self._cls_bytes, self._cls_name,
                          self._cls_id, merged)

    def _materialize(self) -> tuple[str, bytes | None]:
        if self._cls_bytes is None and self._cls is not None:
            self._cls_bytes = serialize(self._cls)
        return self._cls_id, self._cls_bytes

    def __reduce__(self):
        # descriptor stub, mirroring RemoteFunction.__reduce__
        # (capability-keyed: driver and client runtimes expose a
        # registry; workers do not)
        from . import api
        registry = getattr(api._runtime, "fn_registry", None)
        if self._cls is not None and registry is not None:
            cls_id, cls_bytes = self._materialize()
            registry.setdefault(cls_id, cls_bytes)
        return (ActorClass, (None, None, self._cls_name, self._cls_id,
                             self._options))

    def __call__(self, *a, **k):
        raise TypeError(
            f"actor class {self._cls_name} cannot be instantiated "
            "directly; use .remote()")

    def remote(self, *args, **kwargs) -> ActorHandle:
        from .common.config import get_config
        from .common.resources import ResourceRequest
        rt = _runtime()
        opts = self._options
        max_restarts = opts.get(
            "max_restarts", get_config().actor_max_restarts_default)
        max_task_retries = opts.get("max_task_retries", 0)
        name = opts.get("name")
        res = dict(opts.get("resources") or {})
        if "num_cpus" in opts:
            res["CPU"] = opts["num_cpus"]
        if "num_gpus" in opts:
            res["GPU"] = opts["num_gpus"]
        # default: actors hold no resources while alive (reference default
        # is num_cpus=0 for an actor's lifetime)
        from .api import _resolve_strategy_options
        from .common.task_spec import (DEFAULT_STRATEGY,
                                       SchedulingStrategyKind)
        strategy = _resolve_strategy_options(
            opts.get("scheduling_strategy"), opts.get("placement_group"),
            opts.get("placement_group_bundle_index", -1), DEFAULT_STRATEGY)
        if strategy.kind is SchedulingStrategyKind.PLACEMENT_GROUP:
            from .runtime.placement_group_manager import shape_request
            res = shape_request(res, strategy.placement_group_id.hex(),
                                strategy.bundle_index)
        resources = ResourceRequest(res)
        # concurrency model (reference: max_concurrency for threaded
        # actors — async actors default to 1000 worker-side — and named
        # concurrency_groups with per-group limits)
        concurrency = None
        if opts.get("max_concurrency") or opts.get("concurrency_groups"):
            concurrency = {
                "max_concurrency": opts.get("max_concurrency"),
                "concurrency_groups": opts.get("concurrency_groups"),
            }
        elif self._cls is not None:
            # async actors default to max_concurrency=1000 (reference):
            # detect here so the HEAD's pipelining window widens too —
            # worker-side detection alone would cap effective
            # concurrency at the default window
            import inspect
            if any(inspect.iscoroutinefunction(m)
                   or inspect.isasyncgenfunction(m) for _n, m in
                   inspect.getmembers(self._cls) if callable(m)):
                concurrency = {"max_concurrency": 1000,
                               "concurrency_groups": None}
        cls_id, cls_bytes = self._materialize()
        if rt.is_driver:
            actor_id = ActorID.of(rt.job_id)
        else:
            cur = rt.current_task_id
            job_id = cur.job_id() if cur else JobID.from_int(0)
            actor_id = ActorID.of(job_id)
        lifetime = opts.get("lifetime")
        if lifetime not in (None, "ephemeral", "detached"):
            raise ValueError(
                f"lifetime must be 'detached', 'ephemeral', or "
                f"omitted; got {lifetime!r}")
        namespace = opts.get("namespace")
        if namespace is None:
            # None (not "") from a WORKER runtime: the raylet fills in
            # the job's default namespace cluster-side
            namespace = getattr(rt, "namespace", None)
        get_if_exists = bool(opts.get("get_if_exists"))
        if get_if_exists:
            # get-or-create (reference: options(get_if_exists=True)):
            # reuse a live actor under this name, else create; races
            # resolve by re-looking-up the REGISTRY's winner below
            if not name:
                raise ValueError("get_if_exists requires a name")
            existing = _lookup_existing(name, namespace)
            if existing is not None:
                return existing
        try:
            rt.create_actor(actor_id, cls_id, cls_bytes, args, kwargs,
                            max_restarts, max_task_retries, name,
                            resources, strategy,
                            opts.get("runtime_env"),
                            concurrency=concurrency,
                            namespace=namespace, lifetime=lifetime)
        except Exception:
            # a name-collision loss surfaces as ValueError in-process
            # but as RemoteRpcError through a client — any failure
            # under get_if_exists resolves to the winner if one exists
            if get_if_exists:
                existing = _lookup_existing(name, namespace)
                if existing is not None:
                    return existing
            raise
        if get_if_exists:
            # async runtimes (a worker's create frame is fire-and-
            # forget): the NAME registry is the authority on who won a
            # race — return whatever it resolves to once registration
            # lands, which is our own handle in the common case
            win = _await_named(name, namespace, timeout=10.0)
            if win is not None:
                return win
        return ActorHandle(actor_id)


def _lookup_existing(name: str, namespace) -> "ActorHandle | None":
    from . import api
    try:
        return api.get_actor(name, namespace=namespace)
    except ValueError:
        return None


def _await_named(name: str, namespace,
                 timeout: float = 10.0) -> "ActorHandle | None":
    import time as _time
    deadline = _time.monotonic() + timeout
    while True:
        got = _lookup_existing(name, namespace)
        if got is not None or _time.monotonic() >= deadline:
            return got
        _time.sleep(0.05)


def make_actor_class(cls: type, options: dict[str, Any]) -> ActorClass:
    # max_restarts=-1 (infinite) passes through unchanged; the restart
    # budget check in ActorManager.on_worker_death treats != 0 as usable
    return ActorClass(cls, options=dict(options))
