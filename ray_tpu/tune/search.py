"""Search-space primitives + config sampling.

Reference parity: ``ray.tune`` sampling domains — ``grid_search`` takes
the cross product (repeated ``num_samples`` times), stochastic domains
(``choice/uniform/loguniform/randint``) draw per sample
(``python/ray/tune/search/``; mount empty).
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass
from typing import Any, Sequence

import numpy as np


@dataclass(frozen=True)
class GridSearch:
    values: tuple

    def __iter__(self):
        return iter(self.values)


@dataclass(frozen=True)
class Domain:
    kind: str
    a: Any = None
    b: Any = None
    values: tuple = ()

    def sample(self, rng: np.random.Generator):
        if self.kind == "choice":
            return self.values[rng.integers(0, len(self.values))]
        if self.kind == "uniform":
            return float(rng.uniform(self.a, self.b))
        if self.kind == "loguniform":
            return float(np.exp(rng.uniform(np.log(self.a),
                                            np.log(self.b))))
        if self.kind == "randint":
            return int(rng.integers(self.a, self.b))
        raise ValueError(self.kind)


def grid_search(values: Sequence) -> GridSearch:
    return GridSearch(tuple(values))


def choice(values: Sequence) -> Domain:
    return Domain("choice", values=tuple(values))


def uniform(a: float, b: float) -> Domain:
    return Domain("uniform", a, b)


def loguniform(a: float, b: float) -> Domain:
    return Domain("loguniform", a, b)


def randint(a: int, b: int) -> Domain:
    return Domain("randint", a, b)


def expand(param_space: dict, num_samples: int, seed: int) -> list[dict]:
    """Concrete trial configs: the grid cross-product, each point
    repeated ``num_samples`` times with stochastic domains re-drawn."""
    rng = np.random.default_rng(seed)
    grid_keys = [k for k, v in param_space.items()
                 if isinstance(v, GridSearch)]
    grids = [list(param_space[k].values) for k in grid_keys]
    points = list(itertools.product(*grids)) if grid_keys else [()]
    configs: list[dict] = []
    for _ in range(num_samples):
        for point in points:
            cfg = {}
            for k, v in param_space.items():
                if isinstance(v, GridSearch):
                    cfg[k] = point[grid_keys.index(k)]
                elif isinstance(v, Domain):
                    cfg[k] = v.sample(rng)
                else:
                    cfg[k] = v
            configs.append(cfg)
    return configs
