"""ray_tpu.tune — hyperparameter search over parallel trials.

Reference parity: ``ray.tune`` (``python/ray/tune/``) — a ``Tuner``
samples configs from a param space (``grid_search/choice/uniform/
loguniform/randint``), runs trials in parallel on the cluster, collects
per-iteration ``tune.report`` metrics, schedules with FIFO, ASHA
successive halving, or Population Based Training (exploit + explore
over trial checkpoints), checkpoints trial state, and returns a
``ResultGrid`` with ``get_best_result`` (SURVEY.md §1 layer 14; mount
empty).
"""

from ..train.checkpoint import Checkpoint
from .search import choice, grid_search, loguniform, randint, uniform
from .tuner import (ASHAScheduler, FIFOScheduler,
                    PopulationBasedTraining, ResultGrid, TrialResult,
                    TuneConfig, Tuner, get_checkpoint, report, run)

__all__ = ["ASHAScheduler", "Checkpoint", "FIFOScheduler",
           "PopulationBasedTraining", "ResultGrid", "TrialResult",
           "TuneConfig", "Tuner", "choice", "get_checkpoint",
           "grid_search", "loguniform", "randint", "report", "run",
           "uniform"]
