"""Tuner: trial execution, FIFO + ASHA scheduling, result grid.

Reference parity: ``ray.tune.Tuner``/``tune.run`` — trials run as
cluster tasks, function trainables report per-iteration metrics through
the session (``tune.report``), ASHA promotes the top ``1/eta`` of each
rung to the next iteration budget using trial checkpoints, and the
ResultGrid exposes ``get_best_result`` (``python/ray/tune/``,
SURVEY.md §1 layer 14; mount empty).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any, Callable

from ..train.checkpoint import Checkpoint

_session = threading.local()


class _TrialSession:
    def __init__(self, checkpoint: Checkpoint | None):
        self.reports: list[dict] = []
        self.checkpoint_in = checkpoint
        self.checkpoint_out: Checkpoint | None = None


def report(metrics: dict, checkpoint: Checkpoint | None = None) -> None:
    s = getattr(_session, "value", None)
    if s is None:
        raise RuntimeError("tune.report called outside a trial")
    s.reports.append(dict(metrics))
    if checkpoint is not None:
        s.checkpoint_out = checkpoint


def get_checkpoint() -> Checkpoint | None:
    s = getattr(_session, "value", None)
    if s is None:
        raise RuntimeError("tune.get_checkpoint called outside a trial")
    return s.checkpoint_in


def _run_trial(fn_bytes: bytes, config: dict,
               ckpt_state: dict | None) -> tuple:
    """Task body: execute the trainable under a session."""
    from ..runtime.serialization import deserialize
    s = _TrialSession(Checkpoint(ckpt_state)
                      if ckpt_state is not None else None)
    _session.value = s
    try:
        deserialize(fn_bytes)(config)
    finally:
        _session.value = None
    out_state = s.checkpoint_out.to_dict() \
        if s.checkpoint_out is not None else None
    return s.reports, out_state


@dataclass
class TrialResult:
    config: dict
    metrics: dict
    history: list[dict]
    checkpoint: Checkpoint | None

    def metric(self, name: str):
        return self.metrics.get(name)


class ResultGrid:
    def __init__(self, results: list[TrialResult], metric: str,
                 mode: str):
        self._results = results
        self._metric = metric
        self._mode = mode

    def __iter__(self):
        return iter(self._results)

    def __len__(self) -> int:
        return len(self._results)

    def get_best_result(self, metric: str | None = None,
                        mode: str | None = None) -> TrialResult:
        metric = metric or self._metric
        mode = mode or self._mode
        scored = [r for r in self._results if metric in r.metrics]
        if not scored:
            raise ValueError(f"no trial reported metric {metric!r}")
        key = lambda r: r.metrics[metric]           # noqa: E731
        return max(scored, key=key) if mode == "max" \
            else min(scored, key=key)

    def get_dataframe(self) -> list[dict]:
        """Rows of config+final metrics (list of dicts — no pandas
        dependency)."""
        return [{**{f"config/{k}": v for k, v in r.config.items()},
                 **r.metrics} for r in self._results]


@dataclass
class FIFOScheduler:
    """Run every trial to completion (the reference default)."""


@dataclass
class ASHAScheduler:
    """Async successive halving: rung ``i`` runs
    ``grace_period * eta**i`` iterations, the top ``1/eta`` by metric
    promote (resumed from their rung checkpoint)."""
    max_t: int = 32
    grace_period: int = 1
    reduction_factor: int = 4


@dataclass
class PopulationBasedTraining:
    """PBT (reference ``tune.schedulers.PopulationBasedTraining``): the
    population trains in intervals; after each interval the bottom
    quantile EXPLOITS a top-quantile peer (copies its checkpoint and
    config) and EXPLORES by mutating the listed hyperparameters —
    resample from the domain with ``resample_probability``, else scale
    numeric values by 0.8/1.2 (the reference's perturbation factors).

    ``hyperparam_mutations``: name -> ``Domain`` / list of choices.
    Total iterations = ``perturbation_interval * num_intervals``, the
    same cumulative ``tune_iterations`` contract as ASHA."""

    perturbation_interval: int = 1
    num_intervals: int = 4
    quantile_fraction: float = 0.25
    resample_probability: float = 0.25
    hyperparam_mutations: dict = field(default_factory=dict)


@dataclass
class TuneConfig:
    metric: str = "loss"
    mode: str = "min"
    num_samples: int = 1
    scheduler: Any = field(default_factory=FIFOScheduler)
    seed: int = 0
    resources_per_trial: dict = field(
        default_factory=lambda: {"CPU": 1})


class Tuner:
    def __init__(self, trainable: Callable[[dict], None], *,
                 param_space: dict,
                 tune_config: TuneConfig | None = None):
        self._fn = trainable
        self._space = dict(param_space)
        self._cfg = tune_config or TuneConfig()

    def fit(self, timeout: float = 600.0) -> ResultGrid:
        from ..runtime.serialization import serialize
        from .search import expand
        configs = expand(self._space, self._cfg.num_samples,
                         self._cfg.seed)
        fn_bytes = serialize(self._fn)
        sched = self._cfg.scheduler
        if isinstance(sched, ASHAScheduler):
            results = self._fit_asha(fn_bytes, configs, sched, timeout)
        elif isinstance(sched, PopulationBasedTraining):
            results = self._fit_pbt(fn_bytes, configs, sched, timeout)
        else:
            results = self._fit_fifo(fn_bytes, configs, timeout)
        return ResultGrid(results, self._cfg.metric, self._cfg.mode)

    # -- schedulers ----------------------------------------------------------
    def _task(self):
        import ray_tpu
        res = self._cfg.resources_per_trial
        return ray_tpu.remote(_run_trial).options(
            num_cpus=res.get("CPU", 1), resources=dict(res))

    def _fit_fifo(self, fn_bytes, configs, timeout) -> list[TrialResult]:
        import ray_tpu
        task = self._task()
        refs = [task.remote(fn_bytes, dict(cfg), None)
                for cfg in configs]
        outs = ray_tpu.get(refs, timeout=timeout)
        return [self._result(cfg, reports, state)
                for cfg, (reports, state) in zip(configs, outs)]

    @staticmethod
    def _run_round(task, fn_bytes, trials, budget, timeout) -> None:
        """One synchronized round: every trial resumes from its
        checkpoint, runs to ``budget`` TOTAL iterations, and folds its
        reports/checkpoint back in (shared by ASHA rungs and PBT
        intervals)."""
        import ray_tpu
        refs = []
        for trial in trials:
            cfg = dict(trial.config)
            cfg["tune_iterations"] = budget
            state = trial.checkpoint.to_dict() \
                if trial.checkpoint is not None else None
            refs.append(task.remote(fn_bytes, cfg, state))
        outs = ray_tpu.get(refs, timeout=timeout)
        for trial, (reports, state) in zip(trials, outs):
            trial.history.extend(reports)
            if reports:
                trial.metrics = reports[-1]
            if state is not None:
                trial.checkpoint = Checkpoint(state)

    def _fit_asha(self, fn_bytes, configs, sched,
                  timeout) -> list[TrialResult]:
        """Rung r: survivors run ``grace*eta**r`` TOTAL iterations
        (resumed from their previous rung's checkpoint via
        ``tune.get_checkpoint``); the top 1/eta promote."""
        metric, mode = self._cfg.metric, self._cfg.mode
        task = self._task()
        alive = [TrialResult(dict(cfg), {}, [], None) for cfg in configs]
        finished: list[TrialResult] = []
        budget = min(sched.grace_period, sched.max_t)
        while alive:
            self._run_round(task, fn_bytes, alive, budget, timeout)
            if budget >= sched.max_t:
                finished.extend(alive)      # final rung ran at max_t
                break
            scored = [t for t in alive if metric in t.metrics]
            # trials that never reported the metric cannot compete for
            # promotion but MUST stay in the result grid — silently
            # vanishing configs would look like they never ran
            finished.extend(t for t in alive if metric not in t.metrics)
            scored.sort(key=lambda t: t.metrics[metric],
                        reverse=(mode == "max"))
            keep = max(len(scored) // sched.reduction_factor, 1)
            finished.extend(scored[keep:])  # stopped at this rung
            alive = scored[:keep]
            # the ladder clamps to max_t so the survivors' last rung
            # always runs the full budget
            budget = min(budget * sched.reduction_factor, sched.max_t)
        return finished + [t for t in alive if t not in finished]

    def _fit_pbt(self, fn_bytes, configs, sched,
                 timeout) -> list[TrialResult]:
        """Interval k: every trial resumes from its checkpoint and runs
        to ``perturbation_interval * k`` total iterations; then the
        bottom quantile exploits + explores (see scheduler docstring)."""
        import numpy as np
        if not 0.0 < sched.quantile_fraction <= 0.5:
            raise ValueError("quantile_fraction must be in (0, 0.5]: "
                             f"{sched.quantile_fraction}")
        metric, mode = self._cfg.metric, self._cfg.mode
        task = self._task()
        pop = [TrialResult(dict(cfg), {}, [], None) for cfg in configs]
        for k in range(1, sched.num_intervals + 1):
            self._run_round(task, fn_bytes, pop,
                            sched.perturbation_interval * k, timeout)
            if k == sched.num_intervals:
                continue
            # quantiles over the trials that actually REPORTED, sized so
            # top and bottom never overlap (an overlap would exploit a
            # well-performing trial with its own mutated copy)
            scored = [t for t in pop if metric in t.metrics]
            q = max(1, int(len(scored) * sched.quantile_fraction))
            if len(scored) < 2 * q or len(scored) < 2:
                continue
            scored.sort(key=lambda t: t.metrics[metric],
                        reverse=(mode == "max"))
            top, bottom = scored[:q], scored[-q:]
            rng = np.random.default_rng(self._cfg.seed * 1000 + k)
            for trial in bottom:
                peer = top[int(rng.integers(len(top)))]
                # exploit: the peer's weights and hyperparameters
                trial.checkpoint = peer.checkpoint
                trial.config = self._explore(dict(peer.config), sched,
                                             rng)
        return pop

    @staticmethod
    def _explore(config: dict, sched, rng) -> dict:
        """Mutate the listed hyperparameters of an exploited config.
        Continuous domains perturb by 0.8/1.2 (or resample); list
        domains step to an ADJACENT entry (or resample) — a perturbed
        value must stay inside the candidate set, the reference's PBT
        list-mutation rule."""
        from .search import Domain
        for name, domain in sched.hyperparam_mutations.items():
            if name not in config:
                continue
            resample = rng.random() < sched.resample_probability
            if isinstance(domain, Domain):
                if resample:
                    config[name] = domain.sample(rng)
                    continue
            elif isinstance(domain, (list, tuple)):
                choices = list(domain)
                cur = config[name]
                if resample or cur not in choices:
                    config[name] = choices[int(rng.integers(
                        len(choices)))]
                else:
                    i = choices.index(cur)
                    step = 1 if rng.random() < 0.5 else -1
                    config[name] = choices[min(max(i + step, 0),
                                               len(choices) - 1)]
                continue
            value = config[name]
            if isinstance(value, (int, float)) and \
                    not isinstance(value, bool):
                factor = 1.2 if rng.random() < 0.5 else 0.8
                config[name] = type(value)(value * factor) \
                    if isinstance(value, int) else value * factor
        return config

    @staticmethod
    def _result(cfg, reports, state) -> TrialResult:
        return TrialResult(
            dict(cfg), reports[-1] if reports else {}, reports,
            Checkpoint(state) if state is not None else None)


def run(trainable: Callable[[dict], None], *, param_space: dict,
        **tune_kwargs) -> ResultGrid:
    """``tune.run`` convenience wrapper over ``Tuner``."""
    return Tuner(trainable, param_space=param_space,
                 tune_config=TuneConfig(**tune_kwargs)).fit()
