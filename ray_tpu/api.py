"""Public API: init/shutdown, @remote, get/put/wait/cancel.

Reference parity: ``python/ray/_private/worker.py`` (init/get/put/wait),
``python/ray/remote_function.py`` (the ``@ray.remote`` decorator and
``.remote()``/``.options()``), SURVEY.md §1 layer 9 / §3.1–§3.3; mount
empty.  One front end serves both the driver process (full runtime) and
worker processes (``WorkerApiContext`` shim installed by ``worker_main``).
"""

from __future__ import annotations

import os
import threading
from typing import Any, Callable

from .common.config import Config, get_config
from .common.ids import JobID, TaskID
from .common.resources import ResourceRequest, from_cu
from .common.task_spec import DEFAULT_STRATEGY, TaskSpec, TaskType
from .runtime.object_ref import ObjectRef
from .runtime.serialization import serialize

_lock = threading.RLock()
_runtime: "DriverRuntime | Any | None" = None   # driver or WorkerApiContext


def _set_runtime(rt) -> None:
    global _runtime
    _runtime = rt


def _get_runtime():
    if _runtime is None:
        raise RuntimeError("ray_tpu.init() has not been called")
    return _runtime


class DriverRuntime:
    """The in-driver runtime: a (possibly one-node) simulated cluster."""

    is_driver = True

    def __init__(self, job_id: JobID,
                 resources: dict[str, float] | None = None,
                 num_workers: int | None = None, cluster=None):
        from .cluster_utils import Cluster
        from .runtime.actor_manager import ActorManager
        self.job_id = job_id
        self.driver_task_id = TaskID.for_task(job_id)
        self._put_index = 0
        self._put_lock = threading.Lock()
        self._owns_cluster = cluster is None
        if cluster is None:
            cluster = Cluster()
            self.actor_manager = ActorManager(cluster)
            cluster.actor_manager = self.actor_manager
            cluster.add_node(resources=resources, num_workers=num_workers)
        else:
            if cluster.actor_manager is None:
                cluster.actor_manager = ActorManager(cluster)
                for raylet in cluster.raylets.values():
                    raylet.actor_manager = cluster.actor_manager
            self.actor_manager = cluster.actor_manager
        self.cluster = cluster
        self.store = cluster.store
        self.fn_registry = cluster.fn_registry
        self.crm = cluster.crm
        self.raylet = cluster.head()
        self.node_id = self.raylet.node_id

    # -- API ----------------------------------------------------------------
    # The ref-based wrappers sit on *_raw methods that work on bare
    # ObjectIDs: the head daemon serves remote clients through the raw
    # forms so no server-side ObjectRefs are created for client-held
    # objects — a transient counted ref here would hit zero when the
    # handler returned and reclaim a result the client still holds
    # (clients get the worker-frame "conservative leak" ownership).
    def get(self, refs: list[ObjectRef], timeout: float | None = None):
        return self.get_raw([r.id for r in refs], timeout)

    def get_raw(self, oids, timeout: float | None = None):
        from .runtime.object_store import GetTimeoutError
        from .runtime.pull_manager import PullPriority
        # locality: remote plasma objects pull to the driver's node first
        # (reference: a driver get goes through the local plasma store +
        # PullManager at get priority)
        if not self.cluster.pull_manager.pull_blocking(
                oids, self.raylet.row, PullPriority.GET, timeout,
                self.store):
            raise GetTimeoutError(
                f"get timed out; objects not ready within {timeout}s")
        return self.store.get(oids, timeout)

    def put(self, value) -> ObjectRef:
        return ObjectRef(self.put_raw(value))

    def put_raw(self, value):
        with self._put_lock:
            self._put_index += 1
            idx = self._put_index
        from .common.ids import ObjectID
        oid = ObjectID.for_put(self.driver_task_id, idx)
        # size-routed like the reference: large serialized payloads seal
        # into the shared arena (location pre-registered — see
        # Cluster.seal_serialized); small values stay in-band
        from .common.ids import ObjectID as _OID
        from .runtime.object_ref import serialize_collecting
        data, contained = serialize_collecting(value)
        if self.store.routes_to_plasma(len(data)):
            if contained:
                # arena payloads hold no Python refs: register the refs
                # pickled inside so their objects outlive the holder's
                # own copies while this blob is alive
                self.cluster.ref_counter.add_contained(
                    oid, [_OID(b) for b in contained])
            self.cluster.seal_serialized(oid, data, self.raylet.row)
        else:
            self.store.put(oid, value)
        return oid

    # -- streaming generators ------------------------------------------------
    def stream_wait(self, task_id, index: int,
                    timeout: float | None = None):
        return self.cluster.task_manager.wait_stream(task_id, index,
                                                     timeout)

    def stream_ack(self, task_id, consumed: int) -> None:
        self.cluster.stream_ack(task_id, consumed)

    def stream_close(self, task_id, consumed: int) -> None:
        self.cluster.stream_close(task_id, consumed)

    def wait(self, refs, num_returns, timeout):
        ready_ids, not_ready_ids = self.wait_raw(
            [r.id for r in refs], num_returns, timeout)
        by_id = {r.id: r for r in refs}
        return ([by_id[i] for i in ready_ids],
                [by_id[i] for i in not_ready_ids])

    def wait_raw(self, oids, num_returns, timeout):
        return self.store.wait(oids, num_returns, timeout)

    def submit_spec(self, spec: TaskSpec, fn_id: str,
                    fn_bytes: bytes | None) -> None:
        if fn_bytes is not None and fn_id not in self.fn_registry:
            self.fn_registry[fn_id] = fn_bytes
        self.raylet.submit(spec)

    def create_actor(self, actor_id, cls_id, cls_bytes, args, kwargs,
                     max_restarts, max_task_retries, name,
                     resources=None, strategy=None,
                     runtime_env=None, concurrency=None,
                     namespace="", lifetime=None) -> None:
        self.actor_manager.create_actor(actor_id, cls_id, cls_bytes, args,
                                        kwargs, max_restarts,
                                        max_task_retries, name,
                                        resources=resources,
                                        strategy=strategy,
                                        runtime_env=runtime_env,
                                        concurrency=concurrency,
                                        namespace=namespace,
                                        lifetime=lifetime)

    def shutdown(self) -> None:
        # an adopted (caller-owned) cluster stays up across shutdown, the
        # reference's detach semantics; the caller stops it via
        # cluster.stop().  This JOB still ends: its ephemeral actors die
        # with it (detached ones keep running on the adopted cluster)
        if self._owns_cluster:
            self.cluster.stop()
        elif self.actor_manager is not None:
            self.actor_manager.on_job_exit(self.job_id.binary())


# ---------------------------------------------------------------------------
# RemoteFunction
# ---------------------------------------------------------------------------

class RemoteFunction:
    """What ``@ray_tpu.remote`` returns; call ``.remote(*args)``.

    Serializable: shipping one to a worker (e.g. captured in a closure)
    reconstructs a stub that routes submissions back through that worker's
    runtime — nested tasks work (reference: workers submit tasks too).
    """

    def __init__(self, fn: Callable | None, fn_bytes: bytes | None = None,
                 name: str | None = None, num_returns: int = 1,
                 resources: dict[str, float] | None = None,
                 max_retries: int | None = None, fn_id: str | None = None,
                 strategy=None, runtime_env: dict | None = None,
                 max_calls: int = 0):
        if fn is None and fn_bytes is None and fn_id is None:
            raise ValueError("need a function, its bytes, or its id")
        self._fn = fn
        self._fn_bytes = fn_bytes
        self._name = name or getattr(fn, "__qualname__", "anonymous")
        self._num_returns = num_returns
        self._resources = dict(resources) if resources else {"CPU": 1}
        self._max_retries = max_retries
        self._strategy = strategy or DEFAULT_STRATEGY
        self._runtime_env = runtime_env
        self._max_calls = int(max_calls or 0)
        # The id is decoration-time random, NOT a content hash: a recursive
        # remote function's bytes contain its own wrapper, whose pickle
        # embeds the id — a content hash would be circular (reference keys
        # its GCS function table the same way: descriptor, not digest).
        self._fn_id = fn_id or os.urandom(16).hex()
        self._submit_cache = None   # (ResourceRequest, wire num_returns)

    # -- options ------------------------------------------------------------
    def options(self, *, num_returns: int | None = None,
                resources: dict[str, float] | None = None,
                num_cpus: float | None = None,
                max_retries: int | None = None,
                scheduling_strategy=None,
                placement_group=None,
                placement_group_bundle_index: int = -1,
                runtime_env: dict | None = None,
                max_calls: int | None = None) -> "RemoteFunction":
        res = dict(resources) if resources is not None \
            else dict(self._resources)
        if num_cpus is not None:
            res["CPU"] = num_cpus
        strategy = _resolve_strategy_options(
            scheduling_strategy, placement_group,
            placement_group_bundle_index, self._strategy)
        return RemoteFunction(
            self._fn, self._fn_bytes, self._name,
            num_returns if num_returns is not None else self._num_returns,
            res,
            max_retries if max_retries is not None else self._max_retries,
            fn_id=self._fn_id,     # same function => same registry entry
            strategy=strategy,
            runtime_env=(runtime_env if runtime_env is not None
                         else self._runtime_env),
            max_calls=(max_calls if max_calls is not None
                       else self._max_calls))

    # -- serialization (registry + shipping) --------------------------------
    def _materialize(self) -> tuple[str, bytes | None]:
        if self._fn_bytes is None and self._fn is not None:
            self._fn_bytes = serialize(self._fn)
        return self._fn_id, self._fn_bytes

    def __reduce__(self):
        # Ship as a descriptor stub (id + options), NOT by value: the
        # function bytes travel separately through the fn registry, and a
        # stub breaks the self-reference cycle of recursive remote fns.
        # Driver-side pickling eagerly registers the bytes so a stub that
        # reaches a worker only as a task argument still resolves; the
        # reentrancy guard skips this while serializing a recursive fn's
        # own body (that submission registers it anyway).
        registry = getattr(_runtime, "fn_registry", None)
        if not getattr(self, "_reducing", False) and self._fn is not None \
                and registry is not None:
            # capability-keyed, not is_driver: client mode exposes an
            # RPC-backed registry so stubs shipped as ARGS resolve on
            # the cluster too; workers have no registry attr and skip
            self._reducing = True
            try:
                fn_id, fn_bytes = self._materialize()
                registry.setdefault(fn_id, fn_bytes)
            finally:
                self._reducing = False
        return (RemoteFunction,
                (None, None, self._name, self._num_returns,
                 self._resources, self._max_retries, self._fn_id,
                 self._strategy, self._runtime_env, self._max_calls))

    def __call__(self, *a, **k):
        raise TypeError(
            f"remote function {self._name} cannot be called directly; "
            "use .remote()")

    # -- submission ----------------------------------------------------------
    def remote(self, *args, **kwargs):
        rt = _get_runtime()
        fn_id, fn_bytes = self._materialize()
        # submission invariants (demand vector, wire num_returns) are
        # per-FUNCTION and config-independent, so computed once — the
        # tiny-task submit path mints thousands of specs/s.  The retry
        # default is read per call: Config.reset between init cycles
        # must keep applying (it's one attribute read).
        retries = self._max_retries if self._max_retries is not None \
            else get_config().task_max_retries_default
        cached = self._submit_cache
        if cached is None:
            from .common.task_spec import SchedulingStrategyKind
            res = self._resources
            if self._strategy.kind is \
                    SchedulingStrategyKind.PLACEMENT_GROUP:
                # rewrite the demand onto the group's shaped bundle
                # resources (reference: PG tasks consume
                # ``CPU_group_{i}_{pgid}``)
                from .runtime.placement_group_manager import shape_request
                res = shape_request(
                    res, self._strategy.placement_group_id.hex(),
                    self._strategy.bundle_index)
            # "streaming" rides the wire as -1: the task is a GENERATOR
            # and its items seal incrementally (num_returns="streaming")
            num_returns = -1 if self._num_returns == "streaming" \
                else self._num_returns
            cached = (ResourceRequest(res), num_returns)
            self._submit_cache = cached
        rreq, num_returns = cached
        if rt.is_driver:
            job_id = rt.job_id
            task_id = TaskID.for_task(job_id)
        else:
            cur = rt.current_task_id
            job_id = cur.job_id() if cur else JobID.from_int(0)
            task_id = TaskID.for_task(job_id)
        from .util.tracing import context_for_new_task
        spec = TaskSpec(
            task_id=task_id, job_id=job_id, task_type=TaskType.NORMAL_TASK,
            function_descriptor=fn_id, args=args, kwargs=kwargs,
            num_returns=num_returns,
            resources=rreq,
            strategy=self._strategy, max_retries=retries,
            runtime_env=self._runtime_env,  # the job-level env merges in
            #                                 at the raylet submit intake
            trace_ctx=context_for_new_task(task_id),
            max_calls=self._max_calls)
        if num_returns == -1:
            from .runtime.object_ref import ObjectRefGenerator
            rt.submit_spec(spec, fn_id, fn_bytes)
            return ObjectRefGenerator(task_id, rt)
        # result refs are created BEFORE submission: the owner's refcount
        # must never dip to zero while the caller is still building them
        from .common.ids import ObjectID
        refs = [ObjectRef(ObjectID.for_task_return(task_id, i + 1))
                for i in range(num_returns)]
        rt.submit_spec(spec, fn_id, fn_bytes)
        return refs[0] if num_returns == 1 else refs


def remote(*args, **options):
    """``@remote`` or ``@remote(num_returns=2, resources={...})``."""
    if len(args) == 1 and callable(args[0]) and not options:
        fn = args[0]
        if isinstance(fn, type):
            from .actor_api import make_actor_class
            return make_actor_class(fn, {})
        return RemoteFunction(fn)

    def wrap(fn):
        if isinstance(fn, type):
            from .actor_api import make_actor_class
            return make_actor_class(fn, options)
        return RemoteFunction(
            fn,
            num_returns=options.get("num_returns", 1),
            resources=_normalize_resources(options),
            max_retries=options.get("max_retries"),
            strategy=_resolve_strategy_options(
                options.get("scheduling_strategy"),
                options.get("placement_group"),
                options.get("placement_group_bundle_index", -1), None),
            runtime_env=options.get("runtime_env"),
            max_calls=options.get("max_calls", 0))
    return wrap


def _resolve_strategy_options(scheduling_strategy, placement_group,
                              placement_group_bundle_index, default):
    """options() strategy resolution: explicit scheduling_strategy wins,
    then the placement_group= shorthand, then the inherited default."""
    if scheduling_strategy is not None:
        from .util.scheduling_strategies import resolve_strategy
        return resolve_strategy(scheduling_strategy)
    if placement_group is not None:
        from .common.task_spec import (SchedulingStrategy,
                                       SchedulingStrategyKind)
        _check_bundle_index(placement_group, placement_group_bundle_index)
        return SchedulingStrategy(
            kind=SchedulingStrategyKind.PLACEMENT_GROUP,
            placement_group_id=placement_group.id,
            bundle_index=placement_group_bundle_index)
    return default


def _check_bundle_index(pg, index: int) -> None:
    if index < -1:
        raise ValueError(f"invalid placement_group_bundle_index {index}")
    if index >= 0 and pg.bundle_specs and index >= len(pg.bundle_specs):
        raise ValueError(
            f"placement_group_bundle_index {index} out of range for a "
            f"{len(pg.bundle_specs)}-bundle group")


def _normalize_resources(options: dict) -> dict[str, float]:
    res = dict(options.get("resources") or {})
    if "num_cpus" in options:
        res["CPU"] = options["num_cpus"]
    if "num_gpus" in options:
        res["GPU"] = options["num_gpus"]
    if "memory" in options:
        res["memory"] = options["memory"]
    if "CPU" not in res:
        res["CPU"] = 1
    return res


# ---------------------------------------------------------------------------
# module-level API
# ---------------------------------------------------------------------------

def init(resources: dict[str, float] | None = None,
         num_workers: int | None = None,
         system_config: dict | None = None,
         runtime_env: dict | None = None,
         address: str | None = None,
         cluster=None, namespace: str | None = None) -> None:
    """Start the runtime.  ``cluster=`` adopts an existing simulated
    multi-node ``cluster_utils.Cluster`` (the reference's
    ``ray.init(address=cluster.address)`` pattern); ``runtime_env=`` is
    the job-level default environment for every task; ``address=`` (or
    ``"auto"`` with ``RAY_TPU_ADDRESS`` set) attaches to a running head
    daemon as a CLIENT instead of starting a local cluster (reference:
    ``ray.init("ray://…")``); ``namespace=`` scopes named-actor
    lookup/registration (divergence from upstream, documented: the
    default is the SHARED "" namespace rather than an anonymous
    per-job one — explicit namespaces give the isolation)."""
    global _runtime
    with _lock:
        if _runtime is not None:
            raise RuntimeError("ray_tpu already initialized")
        if address == "auto":
            address = os.environ.get("RAY_TPU_ADDRESS")
            if not address:
                raise RuntimeError(
                    "init(address='auto') but RAY_TPU_ADDRESS is unset "
                    "and no head daemon address was given")
        if address is not None:
            conflicting = {"resources": resources,
                           "num_workers": num_workers,
                           "system_config": system_config,
                           "cluster": cluster}
            bad = [k for k, v in conflicting.items() if v is not None]
            if bad:
                raise ValueError(
                    f"init(address=...) attaches to an existing cluster; "
                    f"{bad} configure a LOCAL cluster and would be "
                    "silently ignored — drop them or drop address")
            from .util.client import ClientRuntime
            _runtime = ClientRuntime(address, runtime_env=runtime_env,
                                     namespace=namespace)
            return
        if system_config is not None:
            Config.reset(system_config)
        cfg = get_config()
        ncpu = os.cpu_count() or 4
        if resources is None:
            resources = {"CPU": ncpu, "memory": 8}
        if num_workers is None:
            num_workers = cfg.num_workers_soft_limit or \
                min(int(resources.get("CPU", ncpu)), ncpu)
        _runtime = DriverRuntime(JobID.next(), resources, num_workers,
                                 cluster=cluster)
        _runtime.namespace = namespace or ""
        # workers inherit the job's namespace through the cluster; the
        # KV copy lets get_runtime_context() resolve it INSIDE workers
        _runtime.cluster.default_namespace = namespace or ""
        try:
            _runtime.cluster.kv.dispatch(
                "put", b"__job_namespace", (namespace or "").encode(),
                "sys", True)
        except Exception:   # noqa: BLE001 — identity metadata only
            pass
        # the cluster carries the job-level default env: EVERY spec
        # intake (driver submits, worker-submitted children, actor
        # creation) merges against it, so inheritance is uniform —
        # set_job_runtime_env also gates agents' env-blind fast path
        _runtime.cluster.set_job_runtime_env(runtime_env)


def is_initialized() -> bool:
    return _runtime is not None


def shutdown() -> None:
    global _runtime
    with _lock:
        if _runtime is not None:
            if getattr(_runtime, "is_driver", False):
                _runtime.shutdown()
            elif hasattr(_runtime, "close"):
                _runtime.close()        # client mode: drop the connection
        _runtime = None


def get(refs, timeout: float | None = None):
    single = isinstance(refs, ObjectRef)
    ref_list = [refs] if single else list(refs)
    for r in ref_list:
        if not isinstance(r, ObjectRef):
            raise TypeError(f"ray_tpu.get expects ObjectRefs, got {type(r)}")
    values = _get_runtime().get(ref_list, timeout)
    return values[0] if single else values


def put(value) -> ObjectRef:
    if isinstance(value, ObjectRef):
        raise TypeError("put of an ObjectRef is not allowed (reference "
                        "behavior)")
    return _get_runtime().put(value)


def wait(refs, *, num_returns: int = 1, timeout: float | None = None):
    if isinstance(refs, ObjectRef):
        refs = [refs]
    if num_returns > len(refs):
        raise ValueError("num_returns exceeds the number of refs")
    return _get_runtime().wait(list(refs), num_returns, timeout)


def cancel(ref: ObjectRef, *, force: bool = False) -> None:
    rt = _get_runtime()
    if rt.is_driver:
        # the task may be queued/running/agent-leased on ANY node
        rt.cluster.cancel_task(ref.task_id(), force=force)
    elif hasattr(rt, "cancel_task"):    # client mode
        rt.cancel_task(ref.task_id(), force=force)


def kill(actor_handle, *, no_restart: bool = True) -> None:
    """Forcefully terminate an actor (reference: ``ray.kill``)."""
    from .actor_api import ActorHandle
    if not isinstance(actor_handle, ActorHandle):
        raise TypeError("ray_tpu.kill expects an ActorHandle")
    rt = _get_runtime()
    if rt.is_driver:
        rt.actor_manager.kill(actor_handle._actor_id, no_restart=no_restart)
    else:
        rt.kill_actor(actor_handle._actor_id, no_restart=no_restart)


def get_actor(name: str, namespace: str | None = None):
    """Look up a named actor, scoped to the caller's namespace unless
    one is given (reference: ``ray.get_actor(name, namespace=...)``)."""
    from .actor_api import ActorHandle
    from .common.ids import ActorID
    rt = _get_runtime()
    ns = namespace if namespace is not None \
        else getattr(rt, "namespace", None)
    if rt.is_driver:
        aid = rt.actor_manager.get_by_name(name, ns or "")
    else:
        # workers pass None: the raylet resolves the job's default
        raw = rt.get_actor_id_by_name(name, ns)
        aid = ActorID(raw) if raw else None
    if aid is None:
        raise ValueError(f"no actor named {name!r} in namespace "
                         f"{(ns or '')!r}")
    return ActorHandle(aid)


def available_resources() -> dict[str, float]:
    rt = _get_runtime()
    if not hasattr(rt, "crm"):          # client mode: ask the head
        return rt.available_resources()
    totals, avail, mask = rt.crm.arrays()
    out: dict[str, float] = {}
    for row in range(totals.shape[0]):
        if not mask[row]:
            continue
        for col in range(avail.shape[1]):
            cu = int(avail[row, col])
            if cu:
                name = rt.crm.resource_index.name(col)
                out[name] = out.get(name, 0.0) + from_cu(cu)
    return out


def cluster_resources() -> dict[str, float]:
    rt = _get_runtime()
    if not hasattr(rt, "crm"):          # client mode: ask the head
        return rt.cluster_resources()
    totals, _, mask = rt.crm.arrays()
    out: dict[str, float] = {}
    for row in range(totals.shape[0]):
        if not mask[row]:
            continue
        for col in range(totals.shape[1]):
            cu = int(totals[row, col])
            if cu:
                name = rt.crm.resource_index.name(col)
                out[name] = out.get(name, 0.0) + from_cu(cu)
    return out


def timeline(filename: str | None = None):
    """Task/cluster lifecycle events in Chrome trace format (reference:
    ``ray.timeline``).  Returns the event list, or writes it to
    ``filename`` and returns the path."""
    rt = _get_runtime()
    if not hasattr(rt, "cluster"):      # client mode: ask the head
        events = rt.timeline()
        if filename is not None:
            import json
            with open(filename, "w") as f:
                json.dump(events, f)
            return filename
        return events
    events = rt.cluster.events
    if filename is not None:
        return events.dump_timeline(filename)
    return events.timeline()


class RuntimeContext:
    """Where am I running (reference: ``ray.get_runtime_context()`` /
    ``RuntimeContext`` — job/task/actor/node identity)."""

    def __init__(self, job_id=None, task_id=None, actor_id=None,
                 node_id=None, namespace: str = ""):
        self._job_id = job_id
        self._task_id = task_id
        self._actor_id = actor_id
        self._node_id = node_id
        self.namespace = namespace

    def get_job_id(self):
        return self._job_id

    def get_task_id(self):
        return self._task_id

    def get_actor_id(self):
        return self._actor_id

    def get_node_id(self):
        return self._node_id

    def __repr__(self):
        return (f"RuntimeContext(job={self._job_id}, "
                f"task={self._task_id}, actor={self._actor_id}, "
                f"node={self._node_id})")


def get_runtime_context() -> RuntimeContext:
    rt = _get_runtime()
    from .runtime.worker import WorkerApiContext
    if isinstance(rt, WorkerApiContext):    # inside a worker
        tid = rt.current_task_id
        aid_bin = rt.actor_id_bin
        from .common.ids import ActorID
        return RuntimeContext(
            job_id=tid.job_id().hex() if tid is not None else None,
            task_id=tid.hex() if tid is not None else None,
            actor_id=(ActorID(aid_bin).hex() if aid_bin else None),
            node_id=rt.node_id_hex,
            namespace=_worker_namespace(rt))
    if rt.is_driver:
        head = rt.cluster.head()
        return RuntimeContext(
            job_id=rt.job_id.hex(), node_id=head.node_id.hex(),
            namespace=rt.cluster.default_namespace)
    # client mode: a connected driver — no task identity
    return RuntimeContext(job_id=rt.job_id.hex(),
                          namespace=getattr(rt, "namespace", "") or "")


def _worker_namespace(rt) -> str:
    """The job's default namespace, resolved from the GCS KV (workers
    carry none of their own — api.init publishes it); cached after the
    first lookup."""
    ns = getattr(rt, "_cached_namespace", None)
    if ns is None:
        try:
            raw = rt.kv_op("get", b"__job_namespace", namespace="sys")
        except Exception:   # noqa: BLE001 — degraded KV: identity
            return ""       # lookups must not raise, and a TRANSIENT
            #                 failure must not cache a wrong ''
            #                 forever — retry next call
        ns = raw.decode() if raw else ""
        rt._cached_namespace = ns
    return ns


def list_named_actors(all_namespaces: bool = False) -> list[dict]:
    """Live named actors (reference: ``ray.util.list_named_actors``):
    ``[{"name", "namespace", "actor_id"}, ...]`` — the current
    namespace's by default.  Works from drivers, workers, and
    clients."""
    rt = _get_runtime()
    from .runtime.worker import WorkerApiContext
    if isinstance(rt, WorkerApiContext):
        # inside a worker: the listing rides a raylet frame
        # (named_list), like the named_actor lookup does
        ns = None if all_namespaces else _worker_namespace(rt)
        return rt.list_named_actors_via_head(ns)
    if not hasattr(rt, "cluster"):          # client mode: ask the head
        return rt.list_named_actors(
            all_namespaces, getattr(rt, "namespace", "") or "")
    ns = None if all_namespaces else rt.cluster.default_namespace
    return rt.actor_manager.list_named(ns)


def worker_stacks(node_row: int | None = None,
                  timeout: float = 5.0) -> dict:
    """What is every worker doing RIGHT NOW: {'row:index': all-thread
    stack text}.  Workers reply from their reader thread, so one
    wedged in user code still reports (the dashboard's py-spy
    integration upstream — SURVEY §5.1(c); mount empty)."""
    rt = _get_runtime()
    if not hasattr(rt, "cluster"):      # client mode: ask the head
        return rt.worker_stacks(node_row, timeout)
    got = rt.cluster.dump_worker_stacks(row=node_row, timeout=timeout)
    return {f"{r}:{i}": text for (r, i), text in got.items()}


def nodes() -> list[dict]:
    rt = _get_runtime()
    if not hasattr(rt, "crm"):          # client mode: ask the head
        return rt.nodes()
    out = []
    totals, _, mask = rt.crm.arrays()
    for row in range(totals.shape[0]):
        if mask[row]:
            nid = rt.crm.id_of(row)
            draining = rt.crm.is_draining(row)
            out.append({"NodeID": nid.hex() if nid else None,
                        "Alive": True, "Row": row,
                        "Status": "DRAINING" if draining else "ALIVE",
                        "Labels": rt.crm.labels_of(row)})
    return out


def drain_node(node_id, reason: str = "",
               deadline_s: float | None = None) -> dict:
    """Gracefully retire a node: ALIVE -> DRAINING -> removed.  The
    node stops accepting new leases/bundles immediately, running tasks
    finish, queued work and PG bundles re-place elsewhere, sole-copy
    objects migrate off, and the node is removed once empty or at
    ``deadline_s`` (default ``drain_deadline_s``), whichever is first.
    ``node_id`` is a NodeID or its hex string.  Returns the drain
    status dict ({"state": "DRAINING", ...})."""
    from .common.ids import NodeID
    if isinstance(node_id, str):
        node_id = NodeID.from_hex(node_id)
    rt = _get_runtime()
    if not hasattr(rt, "cluster"):      # client mode: ask the head
        return rt.drain_node(node_id.hex(), reason, deadline_s)
    return rt.cluster.drain_node(node_id, reason=reason,
                                 deadline_s=deadline_s)
