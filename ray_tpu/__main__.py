"""``python -m ray_tpu <command>`` — see ``ray_tpu/scripts/cli.py``."""

from .scripts.cli import main

raise SystemExit(main())
