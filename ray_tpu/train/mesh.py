"""MeshTrainer: data-parallel SPMD training as one XLA program.

The TPU-first counterpart of the actor-gang trainer: instead of N
Python worker processes exchanging gradients through a host-side
collective (the reference's torch-DDP shape), the step is compiled once
with ``shard_map`` over a ``jax.sharding.Mesh`` — the global batch is
sharded on the ``data`` axis, every device computes grads on its shard,
``lax.pmean`` averages them over ICI, and the optimizer update runs
replicated.  Scaling to a pod slice is the SAME program over a larger
mesh (SURVEY.md §2.3/§2.4 TPU-native equivalents; mount empty).
"""

from __future__ import annotations

from typing import Callable

import numpy as np

from .checkpoint import Checkpoint
from .trainer import Result


class MeshTrainer:
    def __init__(self, loss_fn: Callable, init_params,
                 *, optimizer=None, devices=None):
        """``loss_fn(params, batch) -> scalar``; ``optimizer`` is an
        optax GradientTransformation (default: sgd(1e-2))."""
        import jax
        import optax
        from jax.sharding import Mesh
        self._loss_fn = loss_fn
        self._params = init_params
        self._opt = optimizer if optimizer is not None \
            else optax.sgd(1e-2)
        self._opt_state = self._opt.init(init_params)
        devs = list(devices) if devices is not None else jax.devices()
        self._mesh = Mesh(np.array(devs), ("data",))
        self.n_devices = len(devs)
        self._step = self._build_step()

    def _build_step(self):
        import jax
        import optax
        from jax.sharding import PartitionSpec as P

        from ..util.jax_compat import shard_map_compat
        smap = shard_map_compat()

        loss_fn, opt = self._loss_fn, self._opt

        def per_device(params, opt_state, batch):
            loss, grads = jax.value_and_grad(loss_fn)(params, batch)
            # the collective IS the gradient sync: pmean over the data
            # axis rides ICI on hardware
            grads = jax.lax.pmean(grads, "data")
            loss = jax.lax.pmean(loss, "data")
            updates, opt_state = opt.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss

        return jax.jit(smap(
            per_device, mesh=self._mesh,
            in_specs=(P(), P(), P("data")),
            out_specs=(P(), P(), P())))

    def step(self, batch):
        """One global-batch step; returns the (replicated) loss."""
        batch = self._shardable(batch)
        self._params, self._opt_state, loss = self._step(
            self._params, self._opt_state, batch)
        return float(loss)

    def _shardable(self, batch):
        """Trim the leading axis to a multiple of the mesh size (static
        shapes: XLA compiles one program per distinct batch shape)."""
        import jax
        n = self.n_devices

        def trim(x):
            x = np.asarray(x)
            keep = (x.shape[0] // n) * n
            if keep == 0:
                raise ValueError(
                    f"batch of {x.shape[0]} rows cannot shard over "
                    f"{n} devices")
            return x[:keep]
        return jax.tree_util.tree_map(trim, batch)

    @property
    def params(self):
        return self._params

    def fit(self, dataset, *, epochs: int = 1,
            global_batch_size: int = 256) -> Result:
        """Train over a ``ray_tpu.data.Dataset`` (or ndarray batch
        source): batches stream from the object store, every step is
        one compiled SPMD program."""
        history: list[dict] = []
        loss = float("nan")
        for epoch in range(epochs):
            losses = []
            for batch in self._batches(dataset, global_batch_size):
                losses.append(self.step(batch))
            loss = float(np.mean(losses)) if losses else float("nan")
            history.append({"epoch": epoch, "loss": loss})
        return Result(
            metrics=history[-1] if history else {},
            checkpoint=Checkpoint({"params": self._params,
                                   "opt_state": self._opt_state}),
            history=history)

    def _batches(self, dataset, batch_size: int):
        if hasattr(dataset, "iter_batches"):
            # drop the ragged tail: static shapes keep XLA at one
            # compiled program per epoch
            for batch in dataset.iter_batches(batch_size=batch_size):
                if len(batch) == batch_size:
                    yield batch
        else:
            arr = np.asarray(dataset)
            for i in range(0, len(arr) - batch_size + 1, batch_size):
                yield arr[i:i + batch_size]

    def restore(self, checkpoint: Checkpoint) -> None:
        state = checkpoint.to_dict()
        self._params = state["params"]
        self._opt_state = state["opt_state"]
