"""JaxTrainer: gang-scheduled worker actors + collective gradient sync.

Reference parity: ``ray.train``'s ``DataParallelTrainer`` — worker
actors are gang-placed (PACK placement group), each runs the user's
``train_loop_per_worker`` with a ``TrainContext`` (rank, world size,
dataset shard, ``report``), gradients sync through the collective
backend, and rank 0's reports drive the returned ``Result``
(SURVEY.md §1 layer 14, §2.4; mount empty).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any, Callable

import numpy as np

from .checkpoint import Checkpoint

_ctx = threading.local()        # the worker-side TrainContext


@dataclass
class ScalingConfig:
    """``min_workers`` makes the gang ELASTIC: a restart after failure
    may shrink the world to whatever capacity remains (never below
    ``min_workers``) instead of deadlocking on a full-size placement
    that lost nodes can no longer satisfy, and later restarts grow
    back toward ``num_workers`` as capacity returns (reference:
    Train's elastic integration — SURVEY.md §2.4 elastic row; mount
    empty)."""

    num_workers: int = 2
    resources_per_worker: dict[str, float] = field(
        default_factory=lambda: {"CPU": 1})
    min_workers: int | None = None      # None = fixed-size gang


@dataclass
class FailureConfig:
    """Gang fault tolerance (reference ``train.FailureConfig``): on a
    worker failure the whole gang restarts — up to ``max_failures``
    times — from the latest checkpoint rank 0 persisted through
    ``train.report(..., checkpoint=...)`` (the loop resumes it via
    ``train.get_checkpoint()``)."""

    max_failures: int = 0


@dataclass
class Result:
    metrics: dict[str, Any]
    checkpoint: Checkpoint | None
    history: list[dict[str, Any]]


class TrainContext:
    def __init__(self, rank: int, world_size: int, group: str,
                 shard, config: dict,
                 checkpoint_in: Checkpoint | None = None,
                 persist_key: str | None = None,
                 collective_timeout_s: float | None = None):
        self._rank = rank
        self._world = world_size
        self._group = group
        self._shard = shard
        self._config = config
        self._persist_key = persist_key
        # None = the global collective_timeout_s knob; the elastic
        # trainer passes the tighter train_collective_timeout_s so a
        # SIGKILLed peer surfaces as GangMemberLost within the gang's
        # own budget instead of the cluster-wide default
        self._coll_timeout = collective_timeout_s
        self.checkpoint_in = checkpoint_in
        self.reports: list[dict] = []
        self.checkpoint: Checkpoint | None = None

    def get_checkpoint(self) -> Checkpoint | None:
        """The checkpoint to resume from (a prior attempt's persisted
        state, or None on a fresh start)."""
        return self.checkpoint_in

    def get_world_rank(self) -> int:
        return self._rank

    def get_world_size(self) -> int:
        return self._world

    def get_dataset_shard(self):
        return self._shard

    def get_config(self) -> dict:
        return self._config

    # -- collective helpers --------------------------------------------------
    def allreduce(self, tree, op: str = "mean"):
        """Allreduce a pytree of arrays across the worker gang in ONE
        collective round (leaves flattened into a single vector — one
        KV rendezvous instead of one per leaf)."""
        from ..util import collective as col
        leaves, treedef = _flatten(tree)
        flat = np.concatenate([np.asarray(x, dtype=np.float64).ravel()
                               for x in leaves]) if leaves else \
            np.zeros(0)
        red = col.allreduce(flat, op="sum", group_name=self._group,
                            timeout=self._coll_timeout)
        if op == "mean":
            red = red / self._world
        out, pos = [], 0
        for leaf in leaves:
            a = np.asarray(leaf)
            out.append(red[pos:pos + a.size].reshape(a.shape)
                       .astype(a.dtype))
            pos += a.size
        return _unflatten(treedef, out)

    def barrier(self) -> None:
        from ..util import collective as col
        col.barrier(group_name=self._group, timeout=self._coll_timeout)

    def report(self, metrics: dict,
               checkpoint: Checkpoint | None = None) -> None:
        self.reports.append(dict(metrics))
        if checkpoint is not None:
            self.checkpoint = checkpoint
            if self._rank == 0 and self._persist_key is not None:
                # durable checkpoint (reference: report() uploads to
                # storage) — a gang restart resumes from HERE, not from
                # scratch; rank 0 only, like the reference's convention
                from ..experimental.internal_kv import _internal_kv_put
                from ..runtime.serialization import serialize
                _internal_kv_put(self._persist_key,
                                 serialize(checkpoint.to_dict()),
                                 namespace="train")


def get_context() -> TrainContext:
    ctx = getattr(_ctx, "value", None)
    if ctx is None:
        raise RuntimeError("not inside a train loop")
    return ctx


def report(metrics: dict, checkpoint: Checkpoint | None = None) -> None:
    """``ray_tpu.train.report`` — callable from inside the loop."""
    get_context().report(metrics, checkpoint)


def get_checkpoint() -> Checkpoint | None:
    """``ray_tpu.train.get_checkpoint`` — the resume point after a gang
    restart (reference: ``train.get_checkpoint()``)."""
    return get_context().get_checkpoint()


# -- tiny pytree (dict/list/tuple/leaf) --------------------------------------

def _flatten(tree):
    leaves: list = []

    def rec(node):
        if isinstance(node, dict):
            return ("d", [(k, rec(node[k])) for k in sorted(node)])
        if isinstance(node, (list, tuple)):
            return ("l" if isinstance(node, list) else "t",
                    [rec(x) for x in node])
        leaves.append(node)
        return ("x", len(leaves) - 1)

    return leaves, rec(tree)


def _unflatten(treedef, leaves):
    kind, payload = treedef
    if kind == "d":
        return {k: _unflatten(v, leaves) for k, v in payload}
    if kind in ("l", "t"):
        seq = [_unflatten(v, leaves) for v in payload]
        return seq if kind == "l" else tuple(seq)
    return leaves[payload]


# -- the worker actor --------------------------------------------------------

class _TrainWorker:
    """One gang member: joins the collective group, runs the loop."""

    def run(self, fn_bytes: bytes, config: dict, rank: int,
            world: int, group: str, shard_rows,
            ckpt_state: dict | None = None,
            persist_key: str | None = None) -> tuple:
        from ..runtime.serialization import deserialize
        from ..util import collective as col
        col.init_collective_group(world, rank, group)
        try:
            ctx = TrainContext(
                rank, world, group, shard_rows, config,
                checkpoint_in=(Checkpoint(ckpt_state)
                               if ckpt_state is not None else None),
                persist_key=persist_key)
            _ctx.value = ctx
            try:
                deserialize(fn_bytes)(config)
            finally:
                _ctx.value = None
            ckpt_state = ctx.checkpoint.to_dict() \
                if ctx.checkpoint is not None else None
            return ctx.reports, ckpt_state
        finally:
            col.destroy_collective_group(group)


class JaxTrainer:
    def __init__(self, train_loop_per_worker: Callable[[dict], None],
                 *, train_loop_config: dict | None = None,
                 scaling_config: ScalingConfig | None = None,
                 failure_config: FailureConfig | None = None,
                 datasets: dict | None = None):
        self._fn = train_loop_per_worker
        self._config = dict(train_loop_config or {})
        self._scaling = scaling_config or ScalingConfig()
        self._failure = failure_config or FailureConfig()
        self._datasets = dict(datasets or {})

    def fit(self, timeout: float = 300.0) -> Result:
        """Run the gang to completion.  ``timeout`` is PER ATTEMPT: with
        ``FailureConfig(max_failures=k)`` the worst-case wall time is
        ``(k+1) * timeout`` plus placement; ``max_failures=-1`` retries
        forever (the reference's infinite-retry value)."""
        import logging
        import os

        import ray_tpu
        from ..experimental.internal_kv import (_internal_kv_del,
                                                _internal_kv_get)
        from ..runtime.serialization import deserialize, serialize
        from ..util.placement_group import (placement_group,
                                            remove_placement_group)
        n_target = self._scaling.num_workers
        n_min = self._scaling.min_workers
        res = self._scaling.resources_per_worker
        # serialize BEFORE reserving anything: an unpicklable train
        # loop must fail without leaking a placement group
        fn_bytes = serialize(self._fn)
        train_ds = self._datasets.get("train")
        run_id = os.urandom(4).hex()
        persist_key = f"ckpt-{run_id}"
        max_failures = self._failure.max_failures
        attempt = 0         # total restarts (drives elastic resize)
        failures = 0        # REAL failures only (drives max_failures)
        pg = None
        pg_size = 0         # bundle count of the LIVE pg (pg is None ok)
        shards: list = []
        shard_world = -1    # world size the shards were cut for
        log = logging.getLogger("ray_tpu.train")
        # proactive drain handling (driver mode): a drain notice for a
        # node hosting our bundles kills the gang NOW, so the blocked
        # gang get raises and the next attempt checkpoints-and-resizes
        # away from the draining node — a planned handoff, so it does
        # NOT burn the failure budget.  The resume point is the latest
        # checkpoint rank 0 persisted through report().
        from ray_tpu.api import _get_runtime
        cluster = getattr(_get_runtime(), "cluster", None)
        drain_hit = threading.Event()
        self._live_actors: list = []
        live_pg: dict = {"pg": None}
        sub = None
        if cluster is not None:
            def _on_node_event(msg, _c=cluster):
                if not isinstance(msg, dict) or \
                        msg.get("event") != "draining":
                    return
                pg_now = live_pg["pg"]
                if pg_now is None:
                    return
                rec = _c.pg_manager.get(pg_now.id)
                if rec is None or msg.get("row") not in rec.rows:
                    return
                drain_hit.set()
                for a in list(self._live_actors):
                    try:
                        ray_tpu.kill(a)
                    except Exception:   # noqa: BLE001 — already dead
                        pass
            sub = cluster.pubsub.subscribe("node", _on_node_event)
        try:
            while True:
                world = n_target
                if attempt > 0 and n_min is not None \
                        and n_min < n_target:
                    # ELASTIC restart: drop OUR OWN reservation first
                    # (it shadows exactly the capacity being measured),
                    # then size to what single nodes can actually host
                    # — never below min_workers; capacity that came
                    # back grows the gang toward the target again
                    if pg is not None:
                        remove_placement_group(pg)
                        pg = None
                    # resource release from the dead attempt's actors
                    # and bundles is ASYNC: the first sample comes
                    # AFTER a sleep (a t=0 reading predates the
                    # release), then poll until the fit covers the
                    # target or two consecutive post-sleep readings
                    # agree
                    import time as _time
                    deadline = _time.monotonic() + 5.0
                    fits = -1
                    while _time.monotonic() < deadline:
                        _time.sleep(0.2)
                        again = self._placeable_workers(res)
                        if again >= n_target or \
                                (again == fits and again > 0):
                            # a transient 0 is never "stable": the
                            # release may still be landing — keep
                            # polling to the deadline
                            fits = again
                            break
                        fits = again
                    world = max(min(n_target, max(fits, 0)), n_min)
                    if world != pg_size:
                        log.warning(
                            "elastic gang resize: %d -> %d workers",
                            pg_size, world)
                raw = _internal_kv_get(persist_key, namespace="train")
                ckpt_state = deserialize(raw) if raw is not None \
                    else None
                try:
                    if pg is None or world != pg_size:
                        if pg is not None:
                            remove_placement_group(pg)
                            pg = None
                        # gang placement: all-or-none (reference:
                        # Train reserves a PACK group before starting).
                        # pg_size updates BEFORE ready(): a timed-out
                        # group still matches its recorded size, so a
                        # later attempt never runs N workers against a
                        # smaller group
                        pg = placement_group([dict(res)] * world,
                                             strategy="PACK")
                        pg_size = world
                        live_pg["pg"] = pg
                        ray_tpu.get(pg.ready(), timeout=timeout)
                    live_pg["pg"] = pg
                    if shard_world != world:
                        shards = [None] * world
                        if train_ds is not None:
                            shards = [s.take_all()
                                      for s in train_ds.split(world)]
                        shard_world = world
                    outs = self._run_gang(
                        pg, fn_bytes, shards, world,
                        f"train-{run_id}-a{attempt}", ckpt_state,
                        persist_key, timeout)
                    break
                except Exception as e:  # noqa: BLE001 — worker/gang death
                    if drain_hit.is_set():
                        # planned node handoff, not a failure: resume
                        # from the checkpoint and resize off the
                        # draining node (its row is already masked).
                        # Drop the pg — the drain notice arrives BEFORE
                        # the group is displaced, so reusing it could
                        # land the new gang back on the doomed node; a
                        # fresh group places against the masked row
                        drain_hit.clear()
                        live_pg["pg"] = None
                        if pg is not None:
                            remove_placement_group(pg)
                            pg = None
                            pg_size = 0
                        attempt += 1
                        log.warning(
                            "train gang interrupted by node drain "
                            "(restart %d); checkpointing and resizing "
                            "away from the draining node", attempt)
                        continue
                    if 0 <= max_failures <= failures:
                        raise
                    attempt += 1
                    failures += 1
                    # gang restart (reference FailureConfig): the next
                    # attempt resumes from the persisted checkpoint
                    log.warning(
                        "train gang attempt %d failed (%s: %s); "
                        "restarting from the persisted checkpoint",
                        attempt, type(e).__name__, e)
        finally:
            if sub is not None:
                sub.unsubscribe()
            self._live_actors = []
            try:
                _internal_kv_del(persist_key, namespace="train")
            except Exception:   # noqa: BLE001 — a degraded KV must not
                pass            # leak the PG or mask the gang error
            if pg is not None:
                remove_placement_group(pg)
        rank0_reports, ckpt_state = outs[0]
        return Result(
            metrics=rank0_reports[-1] if rank0_reports else {},
            checkpoint=Checkpoint(ckpt_state)
            if ckpt_state is not None else None,
            history=rank0_reports)

    @staticmethod
    def _placeable_workers(res: dict) -> int:
        """How many worker BUNDLES current availability fits.  Each
        bundle must land whole on ONE node, so count per-node fits and
        sum (an aggregate view would report fragmented capacity no
        single node can host); client mode falls back to the aggregate
        (its only view), which over-estimates at worst into a ready()
        timeout that the retry loop absorbs."""
        import ray_tpu
        from ray_tpu.api import _get_runtime
        rt = _get_runtime()
        crm = getattr(rt, "crm", None)
        if crm is not None:
            from ray_tpu.common.resources import ResourceRequest
            snap = crm.snapshot()
            vec = ResourceRequest(res).dense(crm.resource_index,
                                             snap.avail.shape[1])
            total = 0
            for row in range(snap.avail.shape[0]):
                if not snap.node_mask[row]:
                    continue
                fits = [int(snap.avail[row, i]) // int(v)
                        for i, v in enumerate(vec) if v > 0]
                total += max(min(fits) if fits else 0, 0)
            return total
        avail = ray_tpu.available_resources()
        counts = [int(avail.get(k, 0.0) // v)
                  for k, v in res.items() if v > 0]
        return max(min(counts) if counts else 0, 0)

    def _run_gang(self, pg, fn_bytes, shards, n, group,
                  ckpt_state, persist_key, timeout) -> list:
        import ray_tpu
        res = self._scaling.resources_per_worker
        worker_cls = ray_tpu.remote(_TrainWorker)
        actors: list = []
        try:
            actors = [worker_cls.options(
                num_cpus=res.get("CPU", 1),
                placement_group=pg,
                placement_group_bundle_index=i).remote()
                for i in range(n)]
            # visible to the drain-notice subscriber: a draining node
            # hosting this gang kills the actors so the get below
            # raises instead of blocking out the whole drain deadline
            self._live_actors = actors
            return ray_tpu.get(
                [a.run.remote(fn_bytes, self._config, i, n, group,
                              shards[i], ckpt_state, persist_key)
                 for i, a in enumerate(actors)],
                timeout=timeout)
        finally:
            self._live_actors = []
            # kill in the FINALLY: a failed/timed-out gang must not
            # leak N actors (and their half-joined collective group)
            for a in actors:
                try:
                    ray_tpu.kill(a)
                except Exception:   # noqa: BLE001 — already dead
                    pass
