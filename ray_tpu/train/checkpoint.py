"""Checkpoint: the portable training-state container.

Reference parity: ``ray.train.Checkpoint`` — created from a dict or
directory, shipped through the object store, restored at the consumer
(``python/ray/train/_checkpoint.py`` — SURVEY.md §5.4; mount empty).
"""

from __future__ import annotations

import os
import pickle


class Checkpoint:
    def __init__(self, state: dict):
        self._state = dict(state)

    @classmethod
    def from_dict(cls, state: dict) -> "Checkpoint":
        return cls(state)

    def to_dict(self) -> dict:
        return dict(self._state)

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        with open(os.path.join(path, "checkpoint.pkl"), "rb") as f:
            return cls(pickle.load(f))

    def to_directory(self, path: str) -> str:
        os.makedirs(path, exist_ok=True)
        with open(os.path.join(path, "checkpoint.pkl"), "wb") as f:
            pickle.dump(self._state, f)
        return path

    def __repr__(self) -> str:
        return f"Checkpoint(keys={sorted(self._state)})"
