"""ElasticTrainer: a training run that survives the cluster around it.

``JaxTrainer`` restarts a gang; ``ElasticTrainer`` keeps a *run* alive
across everything the pool throws at it, by moving the run's identity
out of the driver and into cluster-durable planes:

- **Journaled progress** — epoch/step/attempt land in the
  GCS-snapshotted KV (namespace ``train``) via read-modify-write, with
  epoch/step clamped monotonic so acked progress never regresses.  A
  promoted standby (or a re-run with the same ``run_name``) inherits
  the run mid-flight instead of starting over.
- **Broadcast-fed weight sync** — the resume checkpoint is put ONCE
  and fanned out over the object plane's relay tree
  (``BroadcastManager.broadcast``) to every gang row before workers
  start, so N (re)joining workers cost one tree, not N point-to-point
  pulls of the same bytes.
- **Checkpoint replication** — the staged checkpoint object is pulled
  to ``train_ckpt_replicas`` rows off the writing node
  (``PullManager.request_pull``), so the resume point survives that
  node's death — the same primitive the drain monitor uses for sole
  copies.
- **Planned vs real failures** — node drain notices AND capacity-loan
  reclaims (both published on the ``node`` pubsub channel before work
  is displaced) kill the gang proactively and restart it as a planned
  resize: no ``max_failures`` burn.  A peer SIGKILLed mid-allreduce
  surfaces as typed :class:`~ray_tpu.util.collective.GangMemberLost`
  (bounded by ``train_collective_timeout_s``) and triggers a gang
  re-form from the last journaled step — budgeted separately
  (``max_gang_reforms``) from unexplained failures.

The simulator mirror is ``ray_tpu.sim.train.SimTrainPlane`` (the
``train_diurnal`` campaign); invariants ``goodput-accounting``,
``ckpt-durable`` and ``gang-terminal`` pin the semantics.
"""

from __future__ import annotations

import json
import threading
import weakref
from typing import Callable

from ..common import clock as _clk
from ..common.config import get_config
from .checkpoint import Checkpoint
from .trainer import (FailureConfig, JaxTrainer, Result, ScalingConfig,
                      TrainContext, _ctx)

__all__ = ["ElasticTrainer", "active_train_stats"]

# live trainers, for /metrics and `ray_tpu status` train gauges
_ACTIVE: "weakref.WeakSet[ElasticTrainer]" = weakref.WeakSet()


def active_train_stats() -> list[dict]:
    """Stats dicts of every ElasticTrainer this driver has run."""
    return [t.stats() for t in list(_ACTIVE)]


# -- the epoch journal (KV, namespace "train") --------------------------------

def _journal_read(key: str) -> dict:
    from ..experimental.internal_kv import _internal_kv_get
    try:
        raw = _internal_kv_get(key, namespace="train")
    except Exception:   # noqa: BLE001 — KV down mid-failover
        return {}
    if raw is None:
        return {}
    try:
        return json.loads(raw.decode())
    except Exception:   # noqa: BLE001 — torn write never poisons a run
        return {}


def _journal_update(key: str, **fields) -> dict:
    """Read-modify-write the run journal.  ``epoch``/``step`` only move
    FORWARD: a gang restart, a stale worker, or a promoted standby can
    never regress acked progress (the ``goodput-accounting`` invariant
    live-side)."""
    from ..experimental.internal_kv import _internal_kv_put
    cur = _journal_read(key)
    for name, value in fields.items():
        if value is None:
            continue
        if name in ("epoch", "step") and \
                isinstance(cur.get(name), (int, float)):
            value = max(cur[name], value)
        cur[name] = value
    try:
        _internal_kv_put(key, json.dumps(cur, sort_keys=True).encode(),
                         namespace="train")
    except Exception:   # noqa: BLE001 — KV down: next report retries
        pass
    return cur


def _gang_member_lost(err: BaseException) -> bool:
    """Is this gang failure a MEMBERSHIP event (recoverable re-form)
    rather than a user-code bug?  Two signatures, depending on which
    rank's error wins the race to the driver: the SIGKILLed member's
    process death (``ActorDiedError``) or a surviving rank's bounded
    collective timeout (``GangMemberLost``) — both ride through the
    RayTaskError wrapping as ``.cause`` when they pickle, and always as
    text in the re-raised traceback."""
    from ..runtime.serialization import ActorDiedError
    from ..util.collective import GangMemberLost
    seen: set[int] = set()
    e: BaseException | None = err
    while e is not None and id(e) not in seen:
        seen.add(id(e))
        if isinstance(e, (GangMemberLost, ActorDiedError)):
            return True
        e = getattr(e, "cause", None) or e.__cause__
    return "GangMemberLost" in str(err) or "ActorDiedError" in str(err)


# -- worker side --------------------------------------------------------------

class _ElasticContext(TrainContext):
    """Rank 0's reports also journal epoch/step, so the driver (or its
    promoted successor) can resume from the last *acked* step even when
    the gang dies before ``fit`` sees any output."""

    def __init__(self, *args, journal_key: str | None = None, **kwargs):
        super().__init__(*args, **kwargs)
        self._journal_key = journal_key

    def report(self, metrics: dict,
               checkpoint: Checkpoint | None = None) -> None:
        super().report(metrics, checkpoint)
        if self._rank == 0 and self._journal_key is not None \
                and checkpoint is not None:
            _journal_update(self._journal_key,
                            epoch=metrics.get("epoch"),
                            step=metrics.get("step", len(self.reports)))


class _ElasticWorker:
    """One gang member, fed by the broadcast plane: the resume
    checkpoint arrives as an ObjectRef whose bytes the controller
    already broadcast to this node, so joining is a local get."""

    def run(self, fn_bytes: bytes, config: dict, rank: int,
            world: int, group: str, shard_rows,
            ckpt_ref=None, ckpt_state: dict | None = None,
            persist_key: str | None = None,
            journal_key: str | None = None) -> tuple:
        import ray_tpu
        from ..runtime.serialization import deserialize
        from ..util import collective as col
        if ckpt_ref is not None:
            # arg resolution may already have materialised the value
            ckpt_state = ray_tpu.get(ckpt_ref) \
                if hasattr(ckpt_ref, "id") else ckpt_ref
        col.init_collective_group(world, rank, group)
        try:
            ctx = _ElasticContext(
                rank, world, group, shard_rows, config,
                checkpoint_in=(Checkpoint(ckpt_state)
                               if ckpt_state is not None else None),
                persist_key=persist_key,
                collective_timeout_s=float(
                    get_config().train_collective_timeout_s),
                journal_key=journal_key)
            _ctx.value = ctx
            try:
                deserialize(fn_bytes)(config)
            finally:
                _ctx.value = None
            state = ctx.checkpoint.to_dict() \
                if ctx.checkpoint is not None else None
            return ctx.reports, state
        finally:
            col.destroy_collective_group(group)


# -- the controller -----------------------------------------------------------

class ElasticTrainer(JaxTrainer):
    """``JaxTrainer`` with a cluster-durable run identity (see module
    docstring).  ``run_name`` pins that identity: a second driver —
    typically a promoted standby's — calling ``fit`` with the same name
    resumes the journaled run instead of starting a new one."""

    def __init__(self, train_loop_per_worker: Callable[[dict], None],
                 *, train_loop_config: dict | None = None,
                 scaling_config: ScalingConfig | None = None,
                 failure_config: FailureConfig | None = None,
                 datasets: dict | None = None,
                 run_name: str | None = None,
                 max_gang_reforms: int = 16):
        super().__init__(train_loop_per_worker,
                         train_loop_config=train_loop_config,
                         scaling_config=scaling_config,
                         failure_config=failure_config,
                         datasets=datasets)
        self._run_name = run_name
        self._max_reforms = max(int(max_gang_reforms), 1)
        self._ckpt_refs: list = []      # newest staged ckpt ref (pinned)
        self._stats: dict = {
            "run": run_name or "", "state": "idle", "journal_key": "",
            "attempts": 0, "failures": 0, "gang_losses": 0,
            "planned_resizes": 0, "sync_broadcasts": 0,
            "ckpt_replications": 0, "world": 0,
        }

    def stats(self) -> dict:
        out = dict(self._stats)
        if out.get("journal_key"):
            journal = _journal_read(out["journal_key"])
            out["epoch"] = journal.get("epoch")
            out["step"] = journal.get("step")
            # live goodput: acked epochs per wall second of fit() —
            # recovery time (gang re-forms, head failover stalls)
            # drags it down, which is the point of the metric
            t0 = getattr(self, "_t_fit", None)
            if t0 is not None and out["epoch"] is not None:
                dt = max(_clk.monotonic() - t0, 1e-9)
                out["goodput_eps"] = round(
                    (out["epoch"] + 1) / dt, 4)
        return out

    # -- checkpoint staging (broadcast + replication) ------------------------
    def _stage_checkpoint(self, cluster, ckpt_state, pg):
        """Put the resume state ONCE, fan it out over the broadcast
        relay tree to every gang row, and replicate the sole copy off
        the writing node.  Returns the ObjectRef to hand the workers
        (None = small/in-band state, shipped with the task specs)."""
        if cluster is None or ckpt_state is None:
            return None
        import ray_tpu
        ref = ray_tpu.put(ckpt_state)
        oid = ref.id
        if not cluster.directory.is_tracked(oid):
            return None
        rec = cluster.pg_manager.get(pg.id)
        rows = sorted(set(rec.rows)) if rec is not None else []
        try:
            summary = cluster.broadcasts.broadcast(oid, node_rows=rows)
            if summary.get("ok"):
                self._stats["sync_broadcasts"] += 1
        except Exception:   # noqa: BLE001 — workers fall back to pulls
            pass
        self._replicate_off_writer(cluster, oid)
        # pin the newest staged checkpoint only: older refs decref on
        # replacement, so superseded resume points can be reclaimed
        self._ckpt_refs = [ref]
        return ref

    def _replicate_off_writer(self, cluster, oid) -> None:
        """``ckpt-durable`` live-side: ask the pull manager for copies
        on other rows until ``train_ckpt_replicas`` nodes hold the
        resume point (same primitive the drain monitor uses for sole
        copies)."""
        from ..runtime.pull_manager import PullPriority
        want = max(int(get_config().train_ckpt_replicas), 1)
        have = set(cluster.directory.locations(oid))
        if len(have) >= want:
            return
        _kind, size = cluster.store.plasma_info(oid)
        snap = cluster.crm.snapshot()
        for row in range(snap.node_mask.shape[0]):
            if len(have) >= want:
                break
            if not snap.node_mask[row] or row in have:
                continue
            cluster.pull_manager.request_pull(oid, size, row,
                                              PullPriority.TASK_ARG)
            have.add(row)
        self._stats["ckpt_replications"] += 1

    # -- the run loop --------------------------------------------------------
    def fit(self, timeout: float = 300.0) -> Result:
        import logging
        import os

        import ray_tpu
        from ray_tpu.api import _get_runtime

        from ..experimental.internal_kv import (_internal_kv_del,
                                                _internal_kv_get)
        from ..runtime.serialization import deserialize, serialize
        from ..util.placement_group import (placement_group,
                                            remove_placement_group)
        n_target = self._scaling.num_workers
        n_min = self._scaling.min_workers
        res = self._scaling.resources_per_worker
        fn_bytes = serialize(self._fn)
        train_ds = self._datasets.get("train")
        run = self._run_name or os.urandom(4).hex()
        persist_key = f"ckpt-{run}"
        journal_key = f"journal-{run}"
        max_failures = self._failure.max_failures
        log = logging.getLogger("ray_tpu.train")
        cluster = getattr(_get_runtime(), "cluster", None)
        st = self._stats
        st.update(run=run, state="running", journal_key=journal_key)
        self._t_fit = _clk.monotonic()
        _ACTIVE.add(self)
        inherited = _journal_read(journal_key)
        if inherited.get("epoch") is not None:
            # the run outlived its previous driver (head failover /
            # standby promotion, or a deliberate re-run): pick it up
            # at the journaled step instead of epoch 0
            log.warning(
                "elastic run %s: inheriting journal at epoch %s "
                "step %s", run, inherited.get("epoch"),
                inherited.get("step"))
        attempt = int(inherited.get("attempt", 0))
        failures = 0
        reforms = 0
        pg = None
        pg_size = 0
        shards: list = []
        shard_world = -1
        planned_hit = threading.Event()
        self._live_actors: list = []
        live_pg: dict = {"pg": None}
        sub = None
        if cluster is not None:
            # drain notices AND loan reclaims arrive on the same
            # channel, both published BEFORE the node's work is
            # displaced — either one hitting a gang row is a PLANNED
            # resize, not a failure
            def _on_node_event(msg, _c=cluster):
                if not isinstance(msg, dict) or msg.get("event") not in \
                        ("draining", "loan_reclaim"):
                    return
                pg_now = live_pg["pg"]
                if pg_now is None:
                    return
                rec = _c.pg_manager.get(pg_now.id)
                if rec is None or msg.get("row") not in rec.rows:
                    return
                planned_hit.set()
                for a in list(self._live_actors):
                    try:
                        ray_tpu.kill(a)
                    except Exception:   # noqa: BLE001 — already dead
                        pass
            sub = cluster.pubsub.subscribe("node", _on_node_event)
        outs = None
        try:
            while True:
                world = n_target
                if attempt > 0 and n_min is not None \
                        and n_min < n_target:
                    if pg is not None:
                        remove_placement_group(pg)
                        pg = None
                    # capacity release from the dead attempt is async:
                    # poll to a stable reading (JaxTrainer's rule)
                    deadline = _clk.monotonic() + 5.0
                    fits = -1
                    while _clk.monotonic() < deadline:
                        _clk.sleep(0.2)
                        again = self._placeable_workers(res)
                        if again >= n_target or \
                                (again == fits and again > 0):
                            fits = again
                            break
                        fits = again
                    world = max(min(n_target, max(fits, 0)), n_min)
                    if world != pg_size:
                        log.warning(
                            "elastic gang resize: %d -> %d workers",
                            pg_size, world)
                raw = _internal_kv_get(persist_key, namespace="train")
                ckpt_state = deserialize(raw) if raw is not None \
                    else None
                try:
                    if pg is None or world != pg_size:
                        if pg is not None:
                            remove_placement_group(pg)
                            pg = None
                        pg = placement_group([dict(res)] * world,
                                             strategy="PACK")
                        pg_size = world
                        live_pg["pg"] = pg
                        ray_tpu.get(pg.ready(), timeout=timeout)
                    live_pg["pg"] = pg
                    if shard_world != world:
                        shards = [None] * world
                        if train_ds is not None:
                            shards = [s.take_all()
                                      for s in train_ds.split(world)]
                        shard_world = world
                    ckpt_ref = self._stage_checkpoint(cluster,
                                                      ckpt_state, pg)
                    st["attempts"] = attempt + 1
                    st["world"] = world
                    _journal_update(journal_key, attempt=attempt,
                                    world=world)
                    outs = self._run_elastic_gang(
                        pg, fn_bytes, shards, world,
                        f"etrain-{run}-a{attempt}", ckpt_ref,
                        ckpt_state, persist_key, journal_key, timeout)
                    break
                except Exception as e:  # noqa: BLE001 — gang death
                    if planned_hit.is_set():
                        planned_hit.clear()
                        live_pg["pg"] = None
                        if pg is not None:
                            remove_placement_group(pg)
                            pg = None
                            pg_size = 0
                        attempt += 1
                        st["planned_resizes"] += 1
                        log.warning(
                            "elastic gang displaced by a planned event "
                            "(drain/loan reclaim); resuming from "
                            "journaled epoch %s",
                            _journal_read(journal_key).get("epoch"))
                        continue
                    if _gang_member_lost(e) and \
                            reforms < self._max_reforms:
                        attempt += 1
                        reforms += 1
                        st["gang_losses"] += 1
                        log.warning(
                            "gang member lost mid-collective "
                            "(re-form %d/%d); resuming from journaled "
                            "epoch %s", reforms, self._max_reforms,
                            _journal_read(journal_key).get("epoch"))
                        continue
                    if 0 <= max_failures <= failures:
                        st["state"] = "failed"
                        raise
                    attempt += 1
                    failures += 1
                    st["failures"] = failures
                    log.warning(
                        "elastic gang attempt %d failed (%s: %s); "
                        "restarting from the persisted checkpoint",
                        attempt, type(e).__name__, e)
        finally:
            if sub is not None:
                sub.unsubscribe()
            self._live_actors = []
            if pg is not None:
                remove_placement_group(pg)
        # freeze the run's goodput before the journal goes: acked
        # epochs over total fit wall time, recovery stalls included
        final_epoch = _journal_read(journal_key).get("epoch")
        if final_epoch is not None:
            dt = max(_clk.monotonic() - self._t_fit, 1e-9)
            st["goodput_eps"] = round((final_epoch + 1) / dt, 4)
        # the run COMPLETED: only now retire its durable identity — a
        # failed/interrupted run keeps journal + checkpoint in the KV
        # so a successor driver can inherit it
        try:
            _internal_kv_del(persist_key, namespace="train")
            _internal_kv_del(journal_key, namespace="train")
        except Exception:   # noqa: BLE001 — degraded KV must not mask
            pass            # the result
        self._ckpt_refs = []
        st["state"] = "complete"
        rank0_reports, ckpt_state = outs[0]
        return Result(
            metrics=rank0_reports[-1] if rank0_reports else {},
            checkpoint=Checkpoint(ckpt_state)
            if ckpt_state is not None else None,
            history=rank0_reports)

    def _run_elastic_gang(self, pg, fn_bytes, shards, n, group,
                          ckpt_ref, ckpt_state, persist_key,
                          journal_key, timeout) -> list:
        import ray_tpu
        res = self._scaling.resources_per_worker
        worker_cls = ray_tpu.remote(_ElasticWorker)
        actors: list = []
        try:
            actors = [worker_cls.options(
                num_cpus=res.get("CPU", 1),
                placement_group=pg,
                placement_group_bundle_index=i).remote()
                for i in range(n)]
            self._live_actors = actors
            inband = None if ckpt_ref is not None else ckpt_state
            return ray_tpu.get(
                [a.run.remote(fn_bytes, self._config, i, n, group,
                              shards[i], ckpt_ref, inband,
                              persist_key, journal_key)
                 for i, a in enumerate(actors)],
                timeout=timeout)
        finally:
            self._live_actors = []
            for a in actors:
                try:
                    ray_tpu.kill(a)
                except Exception:   # noqa: BLE001 — already dead
                    pass
