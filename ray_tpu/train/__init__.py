"""ray_tpu.train — distributed training orchestration, TPU-first.

Reference parity: ``ray.train`` (``python/ray/train/``) — a ``Trainer``
runs ``train_loop_per_worker`` on a gang of worker actors sized by
``ScalingConfig``, workers sync gradients through a collective backend,
report metrics/checkpoints via ``train.report``, and ``fit()`` returns a
``Result`` (SURVEY.md §1 layer 14, §2.4 DP row; mount empty).

Three trainers, all real:

- **JaxTrainer** — the reference shape: N worker actors placed as a
  PACK gang, per-worker dataset shards, gradient allreduce over the
  ``ray_tpu.util.collective`` process group, and gang fault tolerance
  (``FailureConfig``): on a worker death the gang restarts and resumes
  from the checkpoint rank 0 persisted via ``train.report``.
- **ElasticTrainer** — ``JaxTrainer`` with a cluster-durable run
  identity: epoch/step journaled into the GCS-snapshotted KV (a
  promoted standby inherits the run), resume weights broadcast-fed to
  (re)joining workers, checkpoints replicated off the writing node,
  drains/loan-reclaims handled as planned resizes, and SIGKILL
  mid-allreduce recovered via typed ``GangMemberLost`` gang re-form.
- **MeshTrainer** — the TPU-first shape: ONE process, N devices;
  the training step is compiled with ``shard_map`` over a
  ``jax.sharding.Mesh`` (batch sharded on the data axis, grads
  ``pmean``-ed over ICI, params replicated) so data parallelism is an
  XLA collective, not N Python processes.
"""

from .checkpoint import Checkpoint
from .elastic import ElasticTrainer
from .mesh import MeshTrainer
from .trainer import (FailureConfig, JaxTrainer, Result, ScalingConfig,
                      get_checkpoint, get_context, report)

__all__ = ["Checkpoint", "ElasticTrainer", "FailureConfig", "JaxTrainer",
           "MeshTrainer", "Result", "ScalingConfig", "get_checkpoint",
           "get_context", "report"]
