"""ray_tpu.train — distributed training orchestration, TPU-first.

Reference parity: ``ray.train`` (``python/ray/train/``) — a ``Trainer``
runs ``train_loop_per_worker`` on a gang of worker actors sized by
``ScalingConfig``, workers sync gradients through a collective backend,
report metrics/checkpoints via ``train.report``, and ``fit()`` returns a
``Result`` (SURVEY.md §1 layer 14, §2.4 DP row; mount empty).

Two trainers, both real:

- **JaxTrainer** — the reference shape: N worker actors placed as a
  PACK gang, per-worker dataset shards, gradient allreduce over the
  ``ray_tpu.util.collective`` process group, and gang fault tolerance
  (``FailureConfig``): on a worker death the gang restarts and resumes
  from the checkpoint rank 0 persisted via ``train.report``.
- **MeshTrainer** — the TPU-first shape: ONE process, N devices;
  the training step is compiled with ``shard_map`` over a
  ``jax.sharding.Mesh`` (batch sharded on the data axis, grads
  ``pmean``-ed over ICI, params replicated) so data parallelism is an
  XLA collective, not N Python processes.
"""

from .checkpoint import Checkpoint
from .mesh import MeshTrainer
from .trainer import (FailureConfig, JaxTrainer, Result, ScalingConfig,
                      get_checkpoint, get_context, report)

__all__ = ["Checkpoint", "FailureConfig", "JaxTrainer", "MeshTrainer",
           "Result", "ScalingConfig", "get_checkpoint", "get_context",
           "report"]
