"""Rollout workers + REINFORCE-with-baseline on a JAX softmax policy.

The rollout plane mirrors the reference (worker actors step envs with
policy weights broadcast each iteration, samples return through the
object store).  The learner plane scales: ``num_learners`` > 1 runs a
gang of gradient-synchronized learner actors — each computes SUM
gradients on its shard of the batch, allreduces them through the
collective process group (``ray_tpu.util.collective``), and applies the
identical averaged update, so every learner holds the same params
(upstream's multi-learner + NCCL allreduce shape — SURVEY.md §1 layer
14; mount empty).  The multi-learner update is numerically equivalent
to the single-learner one (global baseline computed driver-side, SUM
gradients divided by the global count), not bitwise: float reduction
order differs.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable

import numpy as np


def _softmax_logits(params, obs):
    import jax.numpy as jnp
    return obs @ params["w"] + params["b"]


def _init_params(obs_dim: int, num_actions: int, seed: int) -> dict:
    """THE policy init — driver and every learner call this, so the
    gang and the rollout broadcast can never diverge by a drifted copy
    of the init (scale/dtype/rng order)."""
    rng = np.random.default_rng(seed)
    return {
        "w": (0.01 * rng.normal(size=(obs_dim, num_actions))
              ).astype(np.float32),
        "b": np.zeros(num_actions, dtype=np.float32)}


def _chosen_logp(params, obs, actions):
    """log pi(a|s) for the taken actions — shared by the single-learner
    objective and the learner gang's gradient."""
    import jax
    import jax.numpy as jnp
    logits = _softmax_logits(params, obs)
    logp = jax.nn.log_softmax(logits)
    return jnp.take_along_axis(logp, actions[:, None], axis=1)[:, 0]


def _sample_action(params, obs, rng: np.random.Generator) -> int:
    logits = np.asarray(_softmax_logits(
        {k: np.asarray(v) for k, v in params.items()}, obs))
    z = logits - logits.max()
    p = np.exp(z) / np.exp(z).sum()
    return int(rng.choice(len(p), p=p))


class RolloutWorker:
    """Actor: steps its own env copies with the broadcast policy."""

    def __init__(self, env_creator_bytes: bytes, seed: int):
        from ..runtime.serialization import deserialize
        self._env = deserialize(env_creator_bytes)()
        self._rng = np.random.default_rng(seed)

    def sample(self, params: dict, num_episodes: int,
               horizon: int) -> list[dict]:
        """Roll ``num_episodes`` episodes; returns per-episode
        {obs, actions, rewards} arrays."""
        episodes = []
        for _ in range(num_episodes):
            obs_list, act_list, rew_list = [], [], []
            obs = self._env.reset()
            for _ in range(horizon):
                a = _sample_action(params, np.asarray(obs), self._rng)
                nxt, r, done = self._env.step(a)
                obs_list.append(np.asarray(obs))
                act_list.append(a)
                rew_list.append(r)
                obs = nxt
                if done:
                    break
            episodes.append({
                "obs": np.asarray(obs_list, dtype=np.float32),
                "actions": np.asarray(act_list, dtype=np.int32),
                "rewards": np.asarray(rew_list, dtype=np.float32)})
        return episodes


@dataclass
class PGConfig:
    env_creator: Callable = None
    obs_dim: int = 0
    num_actions: int = 0
    num_workers: int = 2
    episodes_per_worker: int = 8
    horizon: int = 64
    gamma: float = 0.99
    lr: float = 0.05
    seed: int = 0
    # > 1: gradient-synchronized learner gang (collective allreduce)
    num_learners: int = 1
    extra: dict = field(default_factory=dict)


class LearnerWorker:
    """One of N gradient-synchronized learners: SUM gradients on its
    shard, allreduce across the gang, identical averaged update."""

    def __init__(self, obs_dim: int, num_actions: int, lr: float,
                 seed: int, rank: int, world: int, group: str):
        import jax
        self._params = _init_params(obs_dim, num_actions, seed)
        self._lr = lr
        self._world = world
        self._group = group
        if world > 1:
            from ..util.collective import init_collective_group
            init_collective_group(world, rank, group_name=group)

        def grad_sum(params, obs, actions, adv):
            def neg_objective(p):
                return -(_chosen_logp(p, obs, actions) * adv).sum()
            return jax.grad(neg_objective)(params)

        self._grad_sum = jax.jit(grad_sum)

    def update_shard(self, obs, actions, adv, global_count: int) -> int:
        """Gradient on THIS shard, allreduced, applied; returns the
        global count for sanity.  Empty shards contribute zeros (every
        rank must join the allreduce)."""
        if len(adv):
            grads = self._grad_sum(self._params,
                                   np.asarray(obs, np.float32),
                                   np.asarray(actions, np.int32),
                                   np.asarray(adv, np.float32))
            gw = np.asarray(grads["w"])
            gb = np.asarray(grads["b"])
        else:
            gw = np.zeros_like(self._params["w"])
            gb = np.zeros_like(self._params["b"])
        flat = np.concatenate([gw.ravel(), gb.ravel()])
        if self._world > 1:
            from ..util.collective import allreduce
            flat = np.asarray(allreduce(flat, group_name=self._group))
        flat /= max(global_count, 1)
        k = self._params["w"].size
        self._params = {
            "w": self._params["w"] - self._lr
            * flat[:k].reshape(self._params["w"].shape),
            "b": self._params["b"] - self._lr * flat[k:]}
        return int(global_count)

    def params(self) -> dict:
        return {k: np.asarray(v) for k, v in self._params.items()}


class Algorithm:
    def __init__(self, config: PGConfig):
        import jax
        import ray_tpu
        from ..runtime.serialization import serialize
        if config.env_creator is None or config.obs_dim <= 0 \
                or config.num_actions <= 0:
            raise ValueError(
                "PGConfig needs env_creator, obs_dim, num_actions")
        self.config = config
        self._params = _init_params(config.obs_dim, config.num_actions,
                                    config.seed)
        worker_cls = ray_tpu.remote(RolloutWorker)
        env_bytes = serialize(config.env_creator)
        self._workers = [worker_cls.remote(env_bytes, config.seed + i)
                         for i in range(config.num_workers)]
        self._update = jax.jit(self._make_update())
        self._learners: list = []
        if getattr(config, "num_learners", 1) > 1:
            import os
            learner_cls = ray_tpu.remote(LearnerWorker)
            group = f"rllib-learners-{os.urandom(4).hex()}"
            world = config.num_learners
            self._learners = [
                learner_cls.remote(config.obs_dim, config.num_actions,
                                   config.lr, config.seed, rank, world,
                                   group)
                for rank in range(world)]
        self.iteration = 0

    def _make_update(self):
        import jax
        import jax.numpy as jnp
        lr = self.config.lr

        def update(params, obs, actions, returns, mask):
            def neg_objective(p):
                chosen = _chosen_logp(p, obs, actions)
                # advantage = return - batch baseline (variance cut)
                denom = jnp.maximum(mask.sum(), 1.0)
                baseline = (returns * mask).sum() / denom
                adv = (returns - baseline) * mask
                return -(chosen * adv).sum() / denom
            grads = jax.grad(neg_objective)(params)
            return jax.tree_util.tree_map(
                lambda p, g: p - lr * g, params, grads)

        return update

    def _collect_episodes(self, policy_params: dict) -> tuple:
        """Parallel rollouts: broadcast the policy, gather episodes.
        Returns ``(episodes, ep_rewards)``."""
        import ray_tpu
        cfg = self.config
        batches = ray_tpu.get(
            [w.sample.remote(policy_params, cfg.episodes_per_worker,
                             cfg.horizon) for w in self._workers],
            timeout=300)
        episodes = [ep for b in batches for ep in b]
        return episodes, [float(ep["rewards"].sum()) for ep in episodes]

    def _iter_metrics(self, episodes, ep_rewards, n_steps: int,
                      **extra) -> dict:
        self.iteration += 1
        return {"training_iteration": self.iteration,
                "episodes_this_iter": len(episodes),
                "timesteps_this_iter": int(n_steps),
                "episode_reward_mean": float(np.mean(ep_rewards)),
                "episode_reward_max": float(np.max(ep_rewards)),
                "episode_reward_min": float(np.min(ep_rewards)),
                **extra}

    def train(self) -> dict:
        """One iteration: parallel rollouts -> batched PG update."""
        cfg = self.config
        params = {k: np.asarray(v) for k, v in self._params.items()}
        episodes, ep_rewards = self._collect_episodes(params)
        # flatten all timesteps; per-step discounted return-to-go
        obs, acts, rets = [], [], []
        for ep in episodes:
            r = ep["rewards"]
            g = np.zeros_like(r)
            acc = 0.0
            for t in range(len(r) - 1, -1, -1):
                acc = r[t] + cfg.gamma * acc
                g[t] = acc
            obs.append(ep["obs"])
            acts.append(ep["actions"])
            rets.append(g)
        obs = np.concatenate(obs)
        acts = np.concatenate(acts)
        rets = np.concatenate(rets).astype(np.float32)
        if self._learners:
            self._train_multi_learner(obs, acts, rets)
        else:
            mask = np.ones(len(rets), dtype=np.float32)
            self._params = self._update(self._params, obs, acts, rets,
                                        mask)
        return self._iter_metrics(episodes, ep_rewards, len(rets))

    def _train_multi_learner(self, obs, acts, rets) -> None:
        """Shard the batch across the learner gang; each computes SUM
        gradients, allreduces, applies the identical update.  The
        baseline is GLOBAL (computed here) so the summed shard
        gradients equal the single-learner batch gradient."""
        import ray_tpu
        adv = (rets - rets.mean()).astype(np.float32)
        n = len(adv)
        world = len(self._learners)
        bounds = [round(i * n / world) for i in range(world + 1)]
        refs = [
            learner.update_shard.remote(
                obs[bounds[r]:bounds[r + 1]],
                acts[bounds[r]:bounds[r + 1]],
                adv[bounds[r]:bounds[r + 1]], n)
            for r, learner in enumerate(self._learners)]
        ray_tpu.get(refs, timeout=300)
        # every learner holds identical params; mirror rank 0's for the
        # rollout broadcast
        self._params = ray_tpu.get(self._learners[0].params.remote(),
                                   timeout=60)

    def get_policy_params(self) -> dict:
        return {k: np.asarray(v) for k, v in self._params.items()}

    def compute_single_action(self, obs,
                              rng: np.random.Generator | None = None) \
            -> int:
        rng = rng or np.random.default_rng(0)
        return _sample_action(self.get_policy_params(),
                              np.asarray(obs), rng)

    def stop(self) -> None:
        import ray_tpu
        for w in self._workers:
            ray_tpu.kill(w)
        self._workers = []
        for ln in getattr(self, "_learners", []):
            ray_tpu.kill(ln)
        self._learners = []


# ---------------------------------------------------------------------------
# PPO
# ---------------------------------------------------------------------------

@dataclass
class PPOConfig(PGConfig):
    """Reference ``PPOConfig`` essentials: clipped surrogate objective,
    GAE advantages, a linear value head, multi-epoch minibatch SGD over
    each iteration's batch."""

    clip_param: float = 0.2
    num_epochs: int = 4
    minibatch_size: int = 128
    vf_coef: float = 0.5
    entropy_coef: float = 0.0
    gae_lambda: float = 0.95


def _value(params, obs):
    return obs @ params["vw"] + params["vb"]


class PPO(Algorithm):
    """Proximal Policy Optimization on the shared rollout plane.

    Rollout workers are identical to PG's (they only need the softmax
    policy weights); the learner recomputes behavior log-probs from the
    unchanged sampling params, builds GAE advantages from its value
    head, then runs clipped-surrogate minibatch epochs as one jitted
    step per minibatch (reference ``rllib/algorithms/ppo``)."""

    def __init__(self, config: PPOConfig):
        if getattr(config, "num_learners", 1) > 1:
            raise ValueError(
                "num_learners > 1 is implemented for the policy-"
                "gradient Algorithm; PPO runs a single learner")
        super().__init__(config)
        self._params = dict(self._params)
        self._params["vw"] = np.zeros(config.obs_dim, dtype=np.float32)
        self._params["vb"] = np.float32(0.0)
        import jax
        self._ppo_step = jax.jit(self._make_ppo_step())

    @staticmethod
    def _logp_host(params, obs, actions):
        """Behavior log-probs on HOST numpy: the full-batch shape varies
        per iteration, so a jitted version would recompile every
        train() call."""
        logits = obs @ params["w"] + params["b"]
        z = logits - logits.max(axis=1, keepdims=True)
        lp = z - np.log(np.exp(z).sum(axis=1, keepdims=True))
        return lp[np.arange(len(actions)), actions].astype(np.float32)

    def _make_ppo_step(self):
        import jax
        import jax.numpy as jnp
        cfg = self.config

        def step(params, obs, actions, logp_old, adv, vtarg):
            def loss_fn(p):
                lp_all = jax.nn.log_softmax(_softmax_logits(p, obs))
                lp = jnp.take_along_axis(lp_all, actions[:, None],
                                         axis=1)[:, 0]
                ratio = jnp.exp(lp - logp_old)
                clipped = jnp.clip(ratio, 1 - cfg.clip_param,
                                   1 + cfg.clip_param)
                policy_loss = -jnp.mean(
                    jnp.minimum(ratio * adv, clipped * adv))
                v = _value(p, obs)
                value_loss = jnp.mean((v - vtarg) ** 2)
                entropy = -jnp.mean(
                    jnp.sum(jnp.exp(lp_all) * lp_all, axis=1))
                return (policy_loss + cfg.vf_coef * value_loss
                        - cfg.entropy_coef * entropy), \
                    (policy_loss, value_loss)

            grads, (pl, vl) = jax.grad(loss_fn, has_aux=True)(params)
            new = jax.tree_util.tree_map(
                lambda p, g: p - cfg.lr * g, params, grads)
            return new, pl, vl
        return step

    def train(self) -> dict:
        cfg = self.config
        policy = {k: np.asarray(v) for k, v in self._params.items()
                  if k in ("w", "b")}
        episodes, ep_rewards = self._collect_episodes(policy)
        host = {k: np.asarray(v) for k, v in self._params.items()}
        obs_l, act_l, adv_l, vt_l = [], [], [], []
        for ep in episodes:
            o, r = ep["obs"], ep["rewards"]
            v = np.asarray(_value(host, o), dtype=np.float32)
            # GAE(λ): delta_t = r_t + γV(s_{t+1}) - V(s_t), terminal
            # bootstrap 0 (episodes end by done or horizon truncation —
            # truncation bootstrapping is a known simplification)
            v_next = np.append(v[1:], 0.0).astype(np.float32)
            delta = r + cfg.gamma * v_next - v
            adv = np.zeros_like(r)
            acc = 0.0
            for t in range(len(r) - 1, -1, -1):
                acc = delta[t] + cfg.gamma * cfg.gae_lambda * acc
                adv[t] = acc
            obs_l.append(o)
            act_l.append(ep["actions"])
            adv_l.append(adv)
            vt_l.append(adv + v)            # value targets
        obs = np.concatenate(obs_l)
        acts = np.concatenate(act_l)
        adv = np.concatenate(adv_l).astype(np.float32)
        vtarg = np.concatenate(vt_l).astype(np.float32)
        adv = (adv - adv.mean()) / (adv.std() + 1e-8)
        logp_old = self._logp_host(policy, obs, acts)
        n = len(acts)
        mbs = cfg.minibatch_size
        rng = np.random.default_rng(cfg.seed + self.iteration)
        pls, vls = [], []
        for _ in range(cfg.num_epochs):
            # fixed minibatch shape = one XLA compilation: full batches
            # from a permutation, remainder refilled by re-sampling (or
            # the whole batch bootstrapped when it is smaller than mbs)
            if n >= mbs:
                order = rng.permutation(n)
                starts = range(0, n - n % mbs, mbs)
                batches = [order[lo:lo + mbs] for lo in starts]
                if n % mbs:
                    batches.append(rng.choice(n, size=mbs,
                                              replace=False))
            else:
                batches = [rng.choice(n, size=mbs, replace=True)]
            for idx in batches:
                self._params, pl, vl = self._ppo_step(
                    self._params, obs[idx], acts[idx], logp_old[idx],
                    adv[idx], vtarg[idx])
                pls.append(float(pl))
                vls.append(float(vl))
        return self._iter_metrics(
            episodes, ep_rewards, n,
            policy_loss=float(np.mean(pls)),
            vf_loss=float(np.mean(vls)))
