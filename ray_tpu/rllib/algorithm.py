"""Rollout workers + REINFORCE-with-baseline on a JAX softmax policy.

The rollout plane mirrors the reference (worker actors step envs with
policy weights broadcast each iteration, samples return through the
object store); the learner is a single jitted update over the batched
episodes (SURVEY.md §1 layer 14; mount empty).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable

import numpy as np


def _softmax_logits(params, obs):
    import jax.numpy as jnp
    return obs @ params["w"] + params["b"]


def _sample_action(params, obs, rng: np.random.Generator) -> int:
    logits = np.asarray(_softmax_logits(
        {k: np.asarray(v) for k, v in params.items()}, obs))
    z = logits - logits.max()
    p = np.exp(z) / np.exp(z).sum()
    return int(rng.choice(len(p), p=p))


class RolloutWorker:
    """Actor: steps its own env copies with the broadcast policy."""

    def __init__(self, env_creator_bytes: bytes, seed: int):
        from ..runtime.serialization import deserialize
        self._env = deserialize(env_creator_bytes)()
        self._rng = np.random.default_rng(seed)

    def sample(self, params: dict, num_episodes: int,
               horizon: int) -> list[dict]:
        """Roll ``num_episodes`` episodes; returns per-episode
        {obs, actions, rewards} arrays."""
        episodes = []
        for _ in range(num_episodes):
            obs_list, act_list, rew_list = [], [], []
            obs = self._env.reset()
            for _ in range(horizon):
                a = _sample_action(params, np.asarray(obs), self._rng)
                nxt, r, done = self._env.step(a)
                obs_list.append(np.asarray(obs))
                act_list.append(a)
                rew_list.append(r)
                obs = nxt
                if done:
                    break
            episodes.append({
                "obs": np.asarray(obs_list, dtype=np.float32),
                "actions": np.asarray(act_list, dtype=np.int32),
                "rewards": np.asarray(rew_list, dtype=np.float32)})
        return episodes


@dataclass
class PGConfig:
    env_creator: Callable = None
    obs_dim: int = 0
    num_actions: int = 0
    num_workers: int = 2
    episodes_per_worker: int = 8
    horizon: int = 64
    gamma: float = 0.99
    lr: float = 0.05
    seed: int = 0
    extra: dict = field(default_factory=dict)


class Algorithm:
    def __init__(self, config: PGConfig):
        import jax
        import ray_tpu
        from ..runtime.serialization import serialize
        if config.env_creator is None or config.obs_dim <= 0 \
                or config.num_actions <= 0:
            raise ValueError(
                "PGConfig needs env_creator, obs_dim, num_actions")
        self.config = config
        rng = np.random.default_rng(config.seed)
        self._params = {
            "w": (0.01 * rng.normal(size=(config.obs_dim,
                                          config.num_actions))
                  ).astype(np.float32),
            "b": np.zeros(config.num_actions, dtype=np.float32)}
        worker_cls = ray_tpu.remote(RolloutWorker)
        env_bytes = serialize(config.env_creator)
        self._workers = [worker_cls.remote(env_bytes, config.seed + i)
                         for i in range(config.num_workers)]
        self._update = jax.jit(self._make_update())
        self.iteration = 0

    def _make_update(self):
        import jax
        import jax.numpy as jnp
        lr = self.config.lr

        def update(params, obs, actions, returns, mask):
            def neg_objective(p):
                logits = _softmax_logits(p, obs)       # (T, A)
                logp = jax.nn.log_softmax(logits)
                chosen = jnp.take_along_axis(
                    logp, actions[:, None], axis=1)[:, 0]
                # advantage = return - batch baseline (variance cut)
                denom = jnp.maximum(mask.sum(), 1.0)
                baseline = (returns * mask).sum() / denom
                adv = (returns - baseline) * mask
                return -(chosen * adv).sum() / denom
            grads = jax.grad(neg_objective)(params)
            return jax.tree_util.tree_map(
                lambda p, g: p - lr * g, params, grads)

        return update

    def train(self) -> dict:
        """One iteration: parallel rollouts -> batched PG update."""
        import ray_tpu
        cfg = self.config
        params = {k: np.asarray(v) for k, v in self._params.items()}
        batches = ray_tpu.get(
            [w.sample.remote(params, cfg.episodes_per_worker,
                             cfg.horizon) for w in self._workers],
            timeout=300)
        episodes = [ep for b in batches for ep in b]
        # flatten all timesteps; per-step discounted return-to-go
        obs, acts, rets = [], [], []
        ep_rewards = []
        for ep in episodes:
            r = ep["rewards"]
            ep_rewards.append(float(r.sum()))
            g = np.zeros_like(r)
            acc = 0.0
            for t in range(len(r) - 1, -1, -1):
                acc = r[t] + cfg.gamma * acc
                g[t] = acc
            obs.append(ep["obs"])
            acts.append(ep["actions"])
            rets.append(g)
        obs = np.concatenate(obs)
        acts = np.concatenate(acts)
        rets = np.concatenate(rets).astype(np.float32)
        mask = np.ones(len(rets), dtype=np.float32)
        self._params = self._update(self._params, obs, acts, rets, mask)
        self.iteration += 1
        return {"training_iteration": self.iteration,
                "episodes_this_iter": len(episodes),
                "timesteps_this_iter": int(len(rets)),
                "episode_reward_mean": float(np.mean(ep_rewards)),
                "episode_reward_max": float(np.max(ep_rewards)),
                "episode_reward_min": float(np.min(ep_rewards))}

    def get_policy_params(self) -> dict:
        return {k: np.asarray(v) for k, v in self._params.items()}

    def compute_single_action(self, obs,
                              rng: np.random.Generator | None = None) \
            -> int:
        rng = rng or np.random.default_rng(0)
        return _sample_action(self.get_policy_params(),
                              np.asarray(obs), rng)

    def stop(self) -> None:
        import ray_tpu
        for w in self._workers:
            ray_tpu.kill(w)
        self._workers = []
