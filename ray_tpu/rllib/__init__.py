"""ray_tpu.rllib — RL training: parallel rollouts + policy optimization.

Reference parity: ``ray.rllib`` — an ``Algorithm`` owns a set of rollout
worker ACTORS that step gym-style environments with the current policy,
gathers their sample batches each iteration, and applies a policy
update; ``train()`` returns iteration metrics like
``episode_reward_mean`` (``python/ray/rllib/`` — SURVEY.md §1 layer 14;
mount empty).

TPU-first: rollouts are Python-on-actors (environment stepping is
host-bound everywhere), but the POLICY and its update are one jitted
JAX program — softmax policy gradient with baseline, or PPO's clipped
surrogate with GAE and a value head, batched over all collected
episodes — so the math rides the compiler, and the same update shards
over a mesh the way ``train.MeshTrainer`` does.
"""

from .algorithm import PPO, Algorithm, PGConfig, PPOConfig, RolloutWorker

__all__ = ["Algorithm", "PGConfig", "PPO", "PPOConfig", "RolloutWorker"]
