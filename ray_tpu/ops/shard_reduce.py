"""Mesh-sharded delta-heartbeat kernels: the scheduling plane partitioned
by node shard with an explicit two-level ICI/DCN argmin reduce.

The single-device ``DeltaScheduler`` (scheduling/policy.py) keeps the whole
(classes x nodes) packed-key tensor and the CRM mirror on ONE chip — that
chip's HBM bounds the schedulable problem.  This module shards every
node-indexed resident by rows over a two-level device mesh
(``("dcn", "ici")`` — slices x chips-per-slice, the MULTICHIP_r05 dry-run
layout, degenerate shapes ``(1, S)`` on one slice and ``(1, 1)`` on one
chip), under explicit ``shard_map`` bodies rather than GSPMD so each device

- holds only its N/S node rows of totals/avail/mask,
- holds only its N/S key COLUMNS of the carried (C, N) key tensor,
- re-scores only its own shard's dirty rows, staged host->HBM as
  per-shard buckets (each device's upload carries ONLY its rows),

and the beat's global decisions lower to two collectives:

- water-fill sums: ``psum`` over "ici" (intra-slice) then "dcn";
- the placement argmin: each shard's local min PACKED key already carries
  the global traversal index in its low ``NODE_BITS`` bits (ties are
  impossible across nodes), so a plain ``pmin`` over "ici" then "dcn" IS
  the exact (argmin-value, global-node-index) pair reduce — no index
  bookkeeping, bit-identical to ``jnp.argmin`` on the gathered tensor.

Everything stays int32 with the contract.py width audit, so counts are
bit-identical to ``schedule_grouped_oracle`` at any shard count — the
randomized 2/4/8-way parity suite in tests/test_oracle.py holds
sharded == single-device == CPU oracle.

W6 discipline: no host<->device syncs in this module — the one sanctioned
counts readback per beat lives with the caller
(scheduling/sharded_delta.py).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..scheduling.contract import (AVAIL_SHIFT, BUDGET_CAP, MAX_NODES,
                                   SCALE, SCORE_SHIFT)
from ..util.jax_compat import shard_map_compat

# Python ints folded as literals — NOT jnp scalars (a closure-captured
# device buffer drops the axon TPU backend into ~70ms/call sync mode).
_BIG = 1 << 30
_INF_KEY = 2**31 - 1
_IDX_MASK = MAX_NODES - 1


def resolve_shards(requested: int, n_devices: int) -> int:
    """Effective shard count: 0 => one shard per local device, clamped
    to the device count and rounded DOWN to a power of two so the
    bucketed node axis (always a power of two >= 64) divides evenly and
    global traversal indices stay inside the packed key's NODE_BITS."""
    s = n_devices if requested <= 0 else min(requested, n_devices)
    s = max(s, 1)
    return 1 << (s.bit_length() - 1)


def build_mesh(n_shards: int, reduce_mode: str = "auto"):
    """Two-level ``("dcn", "ici")`` mesh over the first ``n_shards``
    local devices.

    ``reduce_mode``:
      - "flat": one slice — shape (1, S); the DCN axis is degenerate and
        the cross-shard reduce is a single ICI pmin/psum.
      - "two_level": force the MULTICHIP_r05 dry-run shape (2, S//2)
        (falls back to flat when S is odd or 1).
      - "auto": derive slices from the devices' ``slice_index`` when the
        platform exposes one and it tiles evenly; flat otherwise (CPU
        virtual devices and single-slice TPUs have nothing to split).
    """
    from jax.sharding import Mesh
    # local_devices, NOT devices(): in multi-process JAX the global list
    # includes non-addressable chips and device_put onto those raises
    devs = jax.local_devices()[:n_shards]
    s = len(devs)
    n_slices = 1
    if reduce_mode == "two_level":
        if s >= 2 and s % 2 == 0:
            n_slices = 2
    elif reduce_mode == "auto":
        slices = {getattr(d, "slice_index", None) for d in devs}
        if None not in slices and len(slices) > 1 \
                and s % len(slices) == 0:
            n_slices = len(slices)
    # host-side device-handle array, not data
    devgrid = np.array(devs)           # rtlint: disable=W6
    return Mesh(devgrid.reshape(n_slices, s // n_slices),
                ("dcn", "ici"))


def _psum2(x):
    """Two-level sum: fold within the slice over ICI, then across
    slices over DCN — the hierarchical reduce of the dry-run's
    ``hier_load``, here feeding the water-fill's global capacities."""
    return jax.lax.psum(jax.lax.psum(x, "ici"), "dcn")


def _pmin2(x):
    """Two-level min: ICI within a slice, DCN across slices.  On packed
    int32 keys this IS the global (argmin-value, node-index) pair
    reduce: the low NODE_BITS bits carry the global traversal index, so
    the minimum key is unique and decodes to the argmin node."""
    return jax.lax.pmin(jax.lax.pmin(x, "ici"), "dcn")


def _shard_linear_index(mesh_shape):
    """This device's position in the flattened ("dcn", "ici") row
    order — row blocks are laid out dcn-major, matching
    ``P(("dcn", "ici"))`` sharding semantics."""
    return (jax.lax.axis_index("dcn") * mesh_shape[1]
            + jax.lax.axis_index("ici"))


def _keys_block(totals_l, avail_l, mask_l, req, thr_fp, offset):
    """Packed keys of one request vs THIS shard's node rows, with the
    GLOBAL traversal index in the low bits (shard-local twin of
    hybrid_kernel._keys_one_req)."""
    n_l = totals_l.shape[0]
    req_pos = req > 0
    feas = jnp.all(jnp.where(req_pos[None, :], totals_l >= req[None, :],
                             True), axis=1) & mask_l
    availb = jnp.all(jnp.where(req_pos[None, :], avail_l >= req[None, :],
                               True), axis=1)
    denom = jnp.maximum(totals_l, 1)
    q = totals_l - avail_l + req[None, :]
    s = jnp.where(req_pos[None, :], (q * SCALE) // denom, 0).max(
        axis=1, initial=0)
    eff = jnp.where(availb & (s < thr_fp), 0, s)
    key = ((~availb).astype(jnp.int32) << AVAIL_SHIFT) \
        | (eff << SCORE_SHIFT) \
        | (offset + jnp.arange(n_l, dtype=jnp.int32))
    return jnp.where(feas, key, _INF_KEY)


def _keys_cols_block(totals_l, avail_l, mask_l, reqs, idx_l, thr_fp,
                     offset):
    """Key columns for the B LOCAL node rows in ``idx_l`` against all C
    classes — the shard's delta rescore costs (C, B) instead of
    (C, N/S).  Padding lanes (idx_l == n_local) clamp on gather and are
    dropped by the caller's scatter."""
    t = totals_l[idx_l]                     # (B, R)
    a = avail_l[idx_l]
    m = mask_l[idx_l]
    req_pos = reqs > 0                      # (C, R)
    feas = jnp.all(jnp.where(req_pos[:, None, :],
                             t[None] >= reqs[:, None, :], True),
                   axis=2) & m[None]        # (C, B)
    availb = jnp.all(jnp.where(req_pos[:, None, :],
                               a[None] >= reqs[:, None, :], True), axis=2)
    denom = jnp.maximum(t, 1)[None]
    q = t[None] - a[None] + reqs[:, None, :]
    s = jnp.where(req_pos[:, None, :], (q * SCALE) // denom, 0).max(
        axis=2, initial=0)
    eff = jnp.where(availb & (s < thr_fp), 0, s)
    key = ((~availb).astype(jnp.int32) << AVAIL_SHIFT) \
        | (eff << SCORE_SHIFT) \
        | (offset + idx_l.astype(jnp.int32))[None, :]
    return jnp.where(feas, key, _INF_KEY)


def _slots_at_or_below_l(L, totals_l, used_l, req, req_pos, m_max_l,
                         thr_fp):
    """Shard-local m_n(L) — identical closed form to
    hybrid_kernel._slots_at_or_below on this shard's rows."""
    Lp = jnp.where(L < thr_fp, thr_fp - 1, L)
    num = (Lp + 1) * totals_l - used_l * SCALE - 1
    denom = jnp.maximum(req * SCALE, 1)[None, :]
    jc = jnp.clip(num // denom, 0, _BIG)
    jcount = jnp.where(req_pos[None, :], jc, _BIG).min(axis=1)
    return jnp.minimum(m_max_l, jcount)


def _schedule_group_sharded(avail_l, totals_l, mask_l, req, count,
                            thr_fp, offset, my_lin, n_lin,
                            require_available):
    """Shard-local water-fill for one class: every global reduction of
    hybrid_kernel._schedule_group lowers to the two-level collectives.
    Returns (alloc_l (n_local,), inf_count scalar, new_avail_l)."""
    n_l = totals_l.shape[0]
    req_pos = req > 0
    any_req = req_pos.any()
    used_l = totals_l - avail_l

    feas = jnp.all(jnp.where(req_pos[None, :], totals_l >= req[None, :],
                             True), axis=1) & mask_l
    caps = jnp.where(req_pos[None, :],
                     avail_l // jnp.maximum(req, 1)[None, :], _BIG)
    m_max_l = jnp.where(feas & any_req,
                        jnp.clip(caps.min(axis=1), 0, _BIG), 0)

    total_cap = _psum2(m_max_l.sum())
    n_avail = jnp.minimum(count, total_cap)
    overflow = count - n_avail

    m_of = partial(_slots_at_or_below_l, totals_l=totals_l, used_l=used_l,
                   req=req, req_pos=req_pos, m_max_l=m_max_l,
                   thr_fp=thr_fp)

    def bisect(carry, _):
        lo, hi = carry
        mid = (lo + hi) // 2
        ok = _psum2(m_of(mid).sum()) >= n_avail
        return (jnp.where(ok, lo, mid + 1), jnp.where(ok, mid, hi)), None

    (l_star, _), _ = jax.lax.scan(
        bisect, (jnp.int32(0), jnp.int32(2 * SCALE)), None,
        length=SCALE.bit_length() + 2)

    base_l = jnp.where(l_star > 0, m_of(jnp.maximum(l_star - 1, 0)), 0)
    at_level = m_of(l_star)
    extra_l = at_level - base_l
    rem = n_avail - _psum2(base_l.sum())
    # global exclusive prefix over traversal order: local cumsum plus the
    # level-set mass of every PRECEDING shard (row blocks are contiguous
    # in shard-linear order, so "preceding shard" == "lower rows")
    g_ici = jax.lax.all_gather(extra_l.sum(), "ici")      # (ici,)
    g_all = jax.lax.all_gather(g_ici, "dcn").reshape(-1)  # (S,)
    before = jnp.where(jnp.arange(n_lin) < my_lin, g_all, 0).sum()
    prefix_l = jnp.cumsum(extra_l) - extra_l + before
    give = jnp.clip(rem - prefix_l, 0, extra_l)
    alloc_l = base_l + give

    new_avail_l = avail_l - alloc_l[:, None] * req[None, :]

    # overflow: the two-level argmin reduce.  Local packed min carries
    # the global node index; pmin over ICI then DCN is exact.
    okeys_l = _keys_block(totals_l, new_avail_l, mask_l, req, thr_fp,
                          offset)
    gmin = _pmin2(okeys_l.min(initial=_INF_KEY))
    infeasible = gmin == _INF_KEY
    onode = gmin & _IDX_MASK                     # global traversal index
    queue_ok = ~infeasible
    if require_available:
        o_avail = (gmin >> AVAIL_SHIFT) & 1 == 0
        queue_ok = queue_ok & o_avail
    # scatter the overflow into the owning shard's local column; every
    # other shard drops it (explicit bound check: a negative local
    # position must not wrap around like a numpy index)
    local_pos = onode - offset
    mine = queue_ok & (local_pos >= 0) & (local_pos < n_l)
    oadd = jnp.where(mine, overflow, 0)
    alloc_row = alloc_l.at[jnp.where(mine, local_pos, n_l)].add(
        oadd, mode="drop")
    inf_count = jnp.where(queue_ok, 0, overflow)
    return alloc_row, inf_count, new_avail_l


class ShardPlane:
    """The jitted shard_map kernel bundle for one mesh.

    Holds the mesh plus the four sharded entry points the
    ``ShardedDeltaScheduler`` drives.  Residents' layouts:

      totals/avail  (N, R)  P(("dcn","ici"), None)   rows by shard
      mask          (N,)    P(("dcn","ici"))
      keys          (C, N)  P(None, ("dcn","ici"))   key COLUMNS by shard
      reqs          (C, R)  P()                      replicated

    Per-shard host->HBM buckets (dirty rows, overrides) arrive as
    (S*B, ...) arrays sharded on the leading axis: each device's
    transfer carries exactly its own shard's B-row bucket, indexed by
    LOCAL row (padding == n_local, dropped by the scatter).
    """

    def __init__(self, mesh):
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P
        self.mesh = mesh
        self.n_shards = int(np.prod(mesh.devices.shape))
        self._P = P
        self.sh_rows = NamedSharding(mesh, P(("dcn", "ici"), None))
        self.sh_vec = NamedSharding(mesh, P(("dcn", "ici")))
        self.sh_cols = NamedSharding(mesh, P(None, ("dcn", "ici")))
        self.sh_repl = NamedSharding(mesh, P())
        self._smap = shard_map_compat()
        self._shape = tuple(mesh.devices.shape)
        self._full_rescore = None
        self._apply_rows = None
        self._apply_classes = None
        self._fused = {}

    # -- kernel builders (lazy: first call jits, later calls reuse) --------
    def full_rescore(self, totals, avail, mask, reqs, thr_fp):
        if self._full_rescore is None:
            P = self._P
            shape = self._shape

            def body(t_l, a_l, m_l, reqs, thr):
                n_l = t_l.shape[0]
                offset = (_shard_linear_index(shape) * n_l).astype(
                    jnp.int32)
                return jax.vmap(lambda r: _keys_block(
                    t_l, a_l, m_l, r, thr, offset))(reqs)

            self._full_rescore = jax.jit(self._smap(
                body, mesh=self.mesh,
                in_specs=(P(("dcn", "ici"), None),
                          P(("dcn", "ici"), None),
                          P(("dcn", "ici")), P(), P()),
                out_specs=P(None, ("dcn", "ici"))))
        return self._full_rescore(totals, avail, mask, reqs,
                                  jnp.int32(thr_fp))

    def apply_dirty_rows(self, totals, avail, mask, keys, reqs, idx,
                         row_totals, row_avail, row_mask, thr_fp):
        """Scatter each shard's dirty-row bucket into ITS rows and
        re-score only its touched key columns."""
        if self._apply_rows is None:
            P = self._P
            shape = self._shape

            def body(t_l, a_l, m_l, k_l, reqs, idx_l, rt_l, ra_l, rm_l,
                     thr):
                n_l = t_l.shape[0]
                offset = (_shard_linear_index(shape) * n_l).astype(
                    jnp.int32)
                t_l = t_l.at[idx_l].set(rt_l, mode="drop")
                a_l = a_l.at[idx_l].set(ra_l, mode="drop")
                m_l = m_l.at[idx_l].set(rm_l, mode="drop")
                cols = _keys_cols_block(t_l, a_l, m_l, reqs, idx_l, thr,
                                        offset)
                k_l = k_l.at[:, idx_l].set(cols, mode="drop")
                return t_l, a_l, m_l, k_l

            self._apply_rows = jax.jit(self._smap(
                body, mesh=self.mesh,
                in_specs=(P(("dcn", "ici"), None),
                          P(("dcn", "ici"), None),
                          P(("dcn", "ici")),
                          P(None, ("dcn", "ici")), P(),
                          P(("dcn", "ici")),
                          P(("dcn", "ici"), None),
                          P(("dcn", "ici"), None),
                          P(("dcn", "ici")), P()),
                out_specs=(P(("dcn", "ici"), None),
                           P(("dcn", "ici"), None),
                           P(("dcn", "ici")),
                           P(None, ("dcn", "ici")))))
        return self._apply_rows(totals, avail, mask, keys, reqs, idx,
                                row_totals, row_avail, row_mask,
                                jnp.int32(thr_fp))

    def apply_dirty_classes(self, totals, avail, mask, keys, reqs, idx,
                            class_reqs, thr_fp):
        """Install B new classes (replicated reqs scatter) and re-score
        their key rows shard-locally.  Padding idx == C."""
        if self._apply_classes is None:
            P = self._P
            shape = self._shape

            def body(t_l, a_l, m_l, k_l, reqs, idx, class_reqs, thr):
                n_l = t_l.shape[0]
                offset = (_shard_linear_index(shape) * n_l).astype(
                    jnp.int32)
                reqs = reqs.at[idx].set(class_reqs, mode="drop")
                rows_l = jax.vmap(lambda r: _keys_block(
                    t_l, a_l, m_l, r, thr, offset))(class_reqs)
                k_l = k_l.at[idx].set(rows_l, mode="drop")
                return reqs, k_l

            self._apply_classes = jax.jit(self._smap(
                body, mesh=self.mesh,
                in_specs=(P(("dcn", "ici"), None),
                          P(("dcn", "ici"), None),
                          P(("dcn", "ici")),
                          P(None, ("dcn", "ici")), P(), P(), P(), P()),
                out_specs=(P(), P(None, ("dcn", "ici")))))
        return self._apply_classes(totals, avail, mask, keys, reqs, idx,
                                   class_reqs, jnp.int32(thr_fp))

    def fused_beat(self, totals, avail, mask, keys, reqs, class_slots,
                   group_counts, extra_mask, ov_idx, ov_avail, thr_fp,
                   require_available=False):
        """One sharded heartbeat: per-shard ephemeral overrides + soft
        mask, the G-class water-fill scan with two-level collectives,
        and the carried-key argmin via the ICI->DCN pmin reduce.  Each
        shard also prices its own rows' per-(class, node) lease budgets
        from the scan's final avail carry (contract.compute_budgets
        twin) — a purely node-local map, so sharding it is exact.

        Returns (packed (G + C, N+1) int32 REPLICATED — rows [:G] the
        water-fill counts + overflow column, rows [G:] the lease
        budgets — and amin (C,) int32 replicated); the host's single
        fetch reads one buffer, the cross-device gather happened on the
        interconnect."""
        key = bool(require_available)
        if key not in self._fused:
            P = self._P
            shape = self._shape
            n_lin = self.n_shards
            req_av = key

            def body(t_l, a_l, m_l, k_l, reqs, slots, counts, em_l,
                     ovi_l, ova_l, thr):
                n_l = t_l.shape[0]
                my_lin = _shard_linear_index(shape)
                offset = (my_lin * n_l).astype(jnp.int32)
                a_eff = a_l.at[ovi_l].set(ova_l, mode="drop")
                m_eff = m_l & em_l
                group_reqs = reqs[jnp.clip(slots, 0,
                                           reqs.shape[0] - 1)]

                def step(av_l, xs):
                    req, count = xs
                    row_l, inf_c, new_av_l = _schedule_group_sharded(
                        av_l, t_l, m_eff, req, count, thr, offset,
                        my_lin, n_lin, req_av)
                    return new_av_l, (row_l, inf_c)

                av_fin, (alloc, inf) = jax.lax.scan(
                    step, a_eff, (group_reqs, counts))

                # shard-local lease budgets off the post-beat avail
                # (clamped >= 0 before ``//`` — contract.compute_budgets)
                av_nn = jnp.maximum(av_fin, 0)

                def budget_row(req):
                    pos = req > 0
                    feas = jnp.all(
                        jnp.where(pos[None, :], t_l >= req[None, :],
                                  True), axis=1) & m_eff
                    fill = jnp.where(
                        pos[None, :],
                        av_nn // jnp.maximum(req, 1)[None, :],
                        BUDGET_CAP).min(axis=1, initial=BUDGET_CAP)
                    return jnp.where(feas,
                                     jnp.clip(fill, 0, BUDGET_CAP), 0)

                budgets_l = jax.vmap(budget_row)(reqs).astype(
                    jnp.int32)                           # (C, n_local)
                lmin = k_l.min(axis=1, initial=_INF_KEY)     # (C,)
                gmin = _pmin2(lmin)
                amin = jnp.where(gmin == _INF_KEY, 0,
                                 gmin & _IDX_MASK).astype(jnp.int32)
                return alloc, inf, budgets_l, amin

            smapped = self._smap(
                body, mesh=self.mesh,
                in_specs=(P(("dcn", "ici"), None),
                          P(("dcn", "ici"), None),
                          P(("dcn", "ici")),
                          P(None, ("dcn", "ici")), P(), P(), P(),
                          P(("dcn", "ici")),
                          P(("dcn", "ici")),
                          P(("dcn", "ici"), None), P()),
                out_specs=(P(None, ("dcn", "ici")), P(),
                           P(None, ("dcn", "ici")), P()))

            def wrapper(t, a, m, k, reqs, slots, counts, em, ovi, ova,
                        thr):
                alloc, inf, budgets, amin = smapped(
                    t, a, m, k, reqs, slots, counts, em, ovi, ova, thr)
                return (jnp.concatenate(
                    [jnp.concatenate([alloc, inf[:, None]], axis=1),
                     jnp.pad(budgets, ((0, 0), (0, 1)))], axis=0), amin)

            self._fused[key] = jax.jit(
                wrapper,
                out_shardings=(self.sh_repl, self.sh_repl))
        return self._fused[key](totals, avail, mask, keys, reqs,
                                class_slots, group_counts, extra_mask,
                                ov_idx, ov_avail, jnp.int32(thr_fp))


def plane_for(n_shards: int, reduce_mode: str = "auto",
              _cache: dict = {}) -> ShardPlane:      # noqa: B006
    """Process-wide ShardPlane cache: one kernel bundle per
    (shard count, reduce topology) — engines come and go per raylet,
    the compiled XLA programs should not."""
    key = (n_shards, reduce_mode, jax.default_backend())
    plane = _cache.get(key)
    if plane is None:
        plane = _cache[key] = ShardPlane(build_mesh(n_shards,
                                                    reduce_mode))
    return plane


def gspmd_plane(n_shards: int = 0, reduce_mode: str = "auto"):
    """Resolve + cache the ShardPlane for the GSPMD ``*_sharded_np``
    kernel wrappers (hybrid/locality/topk/binpack): node rows shard over
    the two-level mesh via input NamedShardings and XLA GSPMD lowers the
    kernels' global reductions to collectives — no shard_map rewrite per
    kernel.  Returns the plane; callers pad the node axis to a multiple
    of ``plane.n_shards`` with mask-False rows (kernel no-ops)."""
    return plane_for(resolve_shards(n_shards, len(jax.local_devices())),
                     reduce_mode)


def pad_node_rows(n: int, n_shards: int) -> int:
    """Rows of padding needed so the node axis divides the shard count."""
    return (-n) % max(n_shards, 1)
