"""TPU gang-placement kernel: placement-group bundles as device math.

Device twin of ``ray_tpu/scheduling/bundles.py`` (the CPU oracle — see its
docstring for the contract and the reference citations:
``src/ray/raylet/scheduling/policy/bundle_scheduling_policy.cc`` invoked from
``GcsPlacementGroupScheduler::ScheduleUnplacedBundles``, SURVEY.md §3.5;
mount empty, semantics re-derived).

Shape discipline: a batch of P placement groups, each padded to B bundle
slots over R resources — ``(P, B, R)`` requests + ``(P, B)`` validity +
``(P,)`` strategy codes.  The outer ``lax.scan`` carries ``avail`` so group
p+1 sees group p's reservations (sequential semantics); each group is
atomic — its bundle placements apply to the carry only if every valid bundle
found an available node.  The inner bundle loop is a second ``lax.scan``
(B is small: gang sizes are tens, not thousands).

Width note: a STRICT_PACK group sums its bundle demands; the sum is clamped
to ``MAX_TOTAL_CU + 1`` — any value above every node's per-resource total
(the int32 contract caps totals at MAX_TOTAL_CU) is equivalently infeasible,
and the clamp keeps ``(t - a + req) * SCALE`` inside int32.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..common.resources import MAX_TOTAL_CU
from ..scheduling.bundles import PlacementStrategy
from ..scheduling.contract import AVAIL_SHIFT
from .hybrid_kernel import _INF_KEY, _keys_one_req

_PACK = PlacementStrategy.PACK.value
_STRICT_PACK = PlacementStrategy.STRICT_PACK.value
_STRICT_SPREAD = PlacementStrategy.STRICT_SPREAD.value


def _avail_keys(totals, avail, req, thr_fp, mask):
    """Hybrid keys with feasible-but-unavailable nodes forced to INF
    (bundle reservation consumes resources — availability is a hard
    requirement, unlike task scheduling's queue-on-feasible)."""
    keys = _keys_one_req(totals, avail, req, thr_fp, mask)
    return jnp.where((keys >> AVAIL_SHIFT) & 1 == 0, keys, _INF_KEY)


def _place_soft(avail, totals, node_mask, reqs, valid, strategy, thr_fp):
    """PACK / SPREAD / STRICT_SPREAD: bundle-at-a-time scan."""

    def step(carry, xs):
        avail, used, ok = carry
        req, v = xs
        primary = jnp.where(strategy == _PACK, used, ~used) & node_mask
        k1 = _avail_keys(totals, avail, req, thr_fp, primary)
        n1 = jnp.argmin(k1).astype(jnp.int32)
        ok1 = k1[n1] != _INF_KEY
        k2 = _avail_keys(totals, avail, req, thr_fp, node_mask)
        n2 = jnp.argmin(k2).astype(jnp.int32)
        ok2 = k2[n2] != _INF_KEY
        use_fb = (strategy != _STRICT_SPREAD) & ~ok1
        node = jnp.where(ok1, n1, n2)
        found = ok1 | (use_fb & ok2)
        place = found & v
        avail = avail.at[node].add(
            jnp.where(place, -req, 0), mode="drop")
        used = used.at[node].set(used[node] | place, mode="drop")
        ok = ok & (found | ~v)
        row = jnp.where(place, node, -1)
        return (avail, used, ok), row

    used0 = jnp.zeros(totals.shape[0], dtype=bool)
    (new_avail, _, ok), rows = jax.lax.scan(
        step, (avail, used0, jnp.bool_(True)), (reqs, valid))
    return rows, ok, new_avail


def _place_strict_pack(avail, totals, node_mask, reqs, valid, thr_fp):
    total = jnp.where(valid[:, None], reqs, 0).sum(axis=0)
    total = jnp.minimum(total, MAX_TOTAL_CU + 1)   # width clamp, see module doc
    keys = _avail_keys(totals, avail, total, thr_fp, node_mask)
    node = jnp.argmin(keys).astype(jnp.int32)
    ok = keys[node] != _INF_KEY
    rows = jnp.where(valid & ok, node, -1)
    new_avail = avail.at[node].add(jnp.where(ok, -total, 0), mode="drop")
    return rows, ok, new_avail


@jax.jit
def schedule_bundle_groups(totals, avail, node_mask, bundle_reqs,
                           bundle_valid, strategies, thr_fp):
    """Atomically place P padded placement groups on device.

    totals/avail: (N, R) int32 cu.  node_mask: (N,) bool.
    bundle_reqs: (P, B, R) int32.  bundle_valid: (P, B) bool.
    strategies: (P,) int32 PlacementStrategy codes.  thr_fp: int32 scalar.

    Returns (rows (P, B) int32 node rows, -1 for padded/failed bundles;
             ok (P,) bool per-group success; new_avail (N, R)).
    Groups run in index order; a failed group leaves ``avail`` untouched.
    Bit-identical to bundles.schedule_bundles applied sequentially.
    """

    def group_step(avail, xs):
        reqs, valid, strategy = xs
        rows_s, ok_s, avail_s = _place_soft(
            avail, totals, node_mask, reqs, valid, strategy, thr_fp)
        rows_p, ok_p, avail_p = _place_strict_pack(
            avail, totals, node_mask, reqs, valid, thr_fp)
        is_sp = strategy == _STRICT_PACK
        rows = jnp.where(is_sp, rows_p, rows_s)
        ok = jnp.where(is_sp, ok_p, ok_s)
        new_avail = jnp.where(is_sp, avail_p, avail_s)
        new_avail = jnp.where(ok, new_avail, avail)    # atomicity
        rows = jnp.where(ok, rows, -1)
        return new_avail, (rows, ok)

    new_avail, (rows, ok) = jax.lax.scan(
        group_step, avail, (bundle_reqs, bundle_valid, strategies))
    return rows, ok, new_avail


def schedule_bundle_groups_np(totals, avail, node_mask, bundle_reqs,
                              bundle_valid, strategies, thr_fp=None,
                              spread_threshold=None):
    """Host wrapper: numpy in/out, device compute."""
    from ..scheduling.contract import threshold_fp
    if thr_fp is None:
        thr_fp = threshold_fp(spread_threshold)
    strat = np.asarray(
        [s.value if isinstance(s, PlacementStrategy) else int(s)
         for s in strategies], dtype=np.int32)
    rows, ok, new_avail = schedule_bundle_groups(
        jnp.asarray(totals, jnp.int32), jnp.asarray(avail, jnp.int32),
        jnp.asarray(node_mask, bool), jnp.asarray(bundle_reqs, jnp.int32),
        jnp.asarray(bundle_valid, bool), jnp.asarray(strat),
        jnp.int32(thr_fp))
    return np.asarray(rows), np.asarray(ok), np.asarray(new_avail)
