"""Sequence/context parallelism: ring attention + Ulysses all-to-all.

The reference orchestrates frameworks that do long-context training but
contains no sequence parallelism itself (SURVEY.md §5.7); this rebuild
makes it first-class because the TPU mesh makes it natural:

- **Ring attention** (blockwise attention with K/V rotation): the
  sequence axis is sharded over a mesh axis; each device computes
  attention of its query block against one K/V block at a time while
  K/V blocks rotate around the ring via ``lax.ppermute`` (neighbor
  exchanges ride ICI), accumulating with an online softmax — exact
  attention over sequences ``world_size``× longer than one device's
  memory, compute/communication overlapped by XLA pipelining.

- **Ulysses all-to-all**: ``lax.all_to_all`` reshards
  sequence-parallel activations to HEAD-parallel, runs ordinary full
  attention on each device's head slice, and reshards back — the
  all-to-all alternative for models with enough heads.

Both are exact (tested bit-close against single-device full attention)
and compile to one XLA program under ``shard_map``.
"""

from __future__ import annotations

from functools import partial

import numpy as np


def _smap():
    from ..util.jax_compat import shard_map_compat
    return shard_map_compat()


def full_attention(q, k, v, causal: bool = False, precision=None):
    """Single-device reference: softmax(QK^T / sqrt(d)) V.

    Shapes ``(batch, seq, heads, dim)``.  ``precision``: a
    ``jax.lax.Precision`` for the matmuls — on TPU the default runs
    bf16 MXU passes, which makes BLOCKWISE accumulation (ring) differ
    from the one-shot softmax at ~1e-3; pass ``HIGHEST`` when exact
    agreement matters more than throughput.
    """
    import jax
    import jax.numpy as jnp
    scale = 1.0 / np.sqrt(q.shape[-1])
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        precision=precision) * scale
    if causal:
        tq, tk = scores.shape[-2], scores.shape[-1]
        mask = jnp.arange(tk)[None, :] > jnp.arange(tq)[:, None]
        scores = jnp.where(mask, -jnp.inf, scores)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v, precision=precision)


def _ring_attention_shard(q, k, v, *, axis_name: str, axis_size: int,
                          causal: bool, precision=None):
    """Per-device body: q/k/v are this device's sequence block
    ``(batch, block, heads, dim)``."""
    import jax
    import jax.numpy as jnp

    block = q.shape[1]
    scale = 1.0 / np.sqrt(q.shape[-1])
    my = jax.lax.axis_index(axis_name)
    q_pos = my * block + jnp.arange(block)              # global q rows

    def fold(s, k_blk, v_blk, acc, denom, m):
        """Online-softmax accumulation of one K/V block (the block
        held after ``s`` rotations = rank ``my - s``'s)."""
        kv_rank = (my - s) % axis_size
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k_blk,
                            precision=precision) * scale
        if causal:
            k_pos = kv_rank * block + jnp.arange(block)
            bad = k_pos[None, :] > q_pos[:, None]       # future keys
            scores = jnp.where(bad[None, None], -jnp.inf, scores)
        blk_max = scores.max(axis=-1)
        new_m = jnp.maximum(m, blk_max)
        # fully-masked rows keep -inf max; exp(-inf - -inf) guards
        safe_m = jnp.where(jnp.isfinite(new_m), new_m, 0.0)
        corr = jnp.exp(jnp.where(jnp.isfinite(m), m - safe_m, -jnp.inf))
        p = jnp.exp(scores - safe_m[..., None])
        p = jnp.where(jnp.isfinite(scores), p, 0.0)
        acc = acc * corr[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p, v_blk, precision=precision)
        denom = denom * corr + p.sum(axis=-1)
        return acc, denom, new_m

    def step(s, carry):
        k_blk, v_blk, acc, denom, m = carry
        acc, denom, m = fold(s, k_blk, v_blk, acc, denom, m)
        # rotate K/V to the next device (neighbor exchange over ICI)
        perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        return k_blk, v_blk, acc, denom, m

    b, t, h, d = q.shape
    init = (k, v,
            jnp.zeros((b, h, t, d), q.dtype),
            jnp.zeros((b, h, t), q.dtype),
            jnp.full((b, h, t), -jnp.inf, q.dtype))
    # loop runs axis_size-1 [fold + rotate] rounds; the LAST block
    # folds outside the loop so no wasted final exchange rides the ring
    k_blk, v_blk, acc, denom, m = jax.lax.fori_loop(
        0, axis_size - 1, step, init)
    acc, denom, _ = fold(axis_size - 1, k_blk, v_blk, acc, denom, m)
    out = acc / jnp.maximum(denom[..., None], 1e-30)
    return out.transpose(0, 2, 1, 3)                    # -> (b, t, h, d)


# jitted program cache: jax.jit keys on the wrapped FUNCTION OBJECT, so
# rebuilding partial+shard_map+jit per call would retrace and recompile
# every invocation (same pattern as DeviceCollectiveGroup._sharded)
_compiled: dict = {}


def ring_attention(q, k, v, *, mesh, axis_name: str = "sp",
                   causal: bool = False, precision=None):
    """Exact attention with the SEQUENCE axis sharded over
    ``mesh[axis_name]``; inputs/outputs ``(batch, seq, heads, dim)``
    with seq = world * block."""
    import jax
    from jax.sharding import PartitionSpec as P
    axis_size = mesh.shape[axis_name]
    key = ("ring", mesh, axis_name, causal, precision)
    fn = _compiled.get(key)
    if fn is None:
        body = partial(_ring_attention_shard, axis_name=axis_name,
                       axis_size=axis_size, causal=causal,
                       precision=precision)
        spec = P(None, axis_name, None, None)
        fn = jax.jit(_smap()(body, mesh=mesh, in_specs=(spec,) * 3,
                             out_specs=spec))
        _compiled[key] = fn
    return fn(q, k, v)


def _ulysses_shard(q, k, v, *, axis_name: str, causal: bool,
                   precision=None):
    """Per-device body: reshard seq-parallel -> head-parallel with
    all-to-all, attend fully, reshard back."""
    import jax

    def a2a(x, forward: bool):
        # (b, block, H, d) <-> (b, seq, H/w, d): split one axis across
        # the mesh, gather the other — one fused ICI all-to-all
        if forward:
            return jax.lax.all_to_all(x, axis_name, split_axis=2,
                                      concat_axis=1, tiled=True)
        return jax.lax.all_to_all(x, axis_name, split_axis=1,
                                  concat_axis=2, tiled=True)

    qh, kh, vh = a2a(q, True), a2a(k, True), a2a(v, True)
    out = full_attention(qh, kh, vh, causal=causal, precision=precision)
    return a2a(out, False)


def ulysses_attention(q, k, v, *, mesh, axis_name: str = "sp",
                      causal: bool = False, precision=None):
    """Exact attention via all-to-all head resharding; requires
    ``heads % world == 0``.  Same layout contract as
    ``ring_attention``."""
    import jax
    from jax.sharding import PartitionSpec as P
    world = mesh.shape[axis_name]
    if q.shape[2] % world != 0:
        raise ValueError(
            f"ulysses needs heads ({q.shape[2]}) divisible by the mesh "
            f"axis ({world})")
    key = ("ulysses", mesh, axis_name, causal, precision)
    fn = _compiled.get(key)
    if fn is None:
        body = partial(_ulysses_shard, axis_name=axis_name,
                       causal=causal, precision=precision)
        spec = P(None, axis_name, None, None)
        fn = jax.jit(_smap()(body, mesh=mesh, in_specs=(spec,) * 3,
                             out_specs=spec))
        _compiled[key] = fn
    return fn(q, k, v)
