"""Flash attention as a Pallas TPU kernel — the single-device hot op.

Blocked attention with the online-softmax recurrence computed entirely
in VMEM: for each query block the kernel streams key/value blocks,
keeps the running (max, normalizer, accumulator) as loop carries, and
writes one normalized output block — O(T) memory instead of the O(T^2)
score matrix, with both matmuls on the MXU
(``preferred_element_type=float32`` accumulation).

Pairs with the mesh-level strategies in ``ops/ring_attention.py``: ring
/ Ulysses shard the sequence ACROSS chips; this kernel is the
within-chip block engine.  On non-TPU backends it runs in Pallas
interpreter mode (tests on the CPU mesh), so one code path serves both.

Shapes ``(batch, seq, heads, dim)``; ``seq`` must divide by the block
size and ``dim`` should be a multiple of 128 (MXU lane width) for the
compiled path.
"""

from __future__ import annotations

import numpy as np


def flash_attention(q, k, v, *, causal: bool = False,
                    block_q: int = 128, block_k: int = 128,
                    interpret: bool | None = None):
    """softmax(QK^T / sqrt(d)) V, blockwise in VMEM.

    ``interpret=None`` auto-selects interpreter mode off-TPU.
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, t, h, d = q.shape
    if k.shape != q.shape or v.shape != q.shape:
        raise ValueError("q/k/v must share shape (batch, seq, heads, "
                         f"dim); got {q.shape}/{k.shape}/{v.shape}")
    block_q = min(block_q, t)
    block_k = min(block_k, t)
    if t % block_q or t % block_k:
        raise ValueError(
            f"seq {t} must divide by block_q={block_q} and "
            f"block_k={block_k} (pad the sequence)")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    scale = 1.0 / np.sqrt(d)
    nq, nk = t // block_q, t // block_k

    # the key-block index is a GRID dimension (innermost = sequential
    # on TPU), with the online-softmax state in VMEM scratch persisting
    # across its steps: VMEM holds O(block) of K/V at a time, so the
    # sequence length is bounded by HBM, not by the ~16 MB VMEM (the
    # regime flash attention exists for).  m/l ride (block_q, 128)
    # scratch — lane-width tiles; column 0 is the value.
    def kernel(q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref):
        qi, j = pl.program_id(1), pl.program_id(2)

        @pl.when(j == 0)
        def _init():
            acc_ref[...] = jnp.zeros_like(acc_ref)
            m_ref[...] = jnp.full_like(m_ref, -jnp.inf)
            l_ref[...] = jnp.zeros_like(l_ref)

        # causal: key blocks entirely past this query block are dead
        # work — skip the matmuls, not just the probabilities
        live = (j * block_k <= qi * block_q + block_q - 1) \
            if causal else (j >= 0)

        @pl.when(live)
        def _accumulate():
            qb = q_ref[0].astype(jnp.float32) * scale   # (BQ, D)
            kb = k_ref[0].astype(jnp.float32)           # (BK, D)
            s = jax.lax.dot_general(
                qb, kb, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)     # (BQ, BK)
            if causal:
                q_pos = qi * block_q + jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 0)
                k_pos = j * block_k + jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 1)
                s = jnp.where(k_pos > q_pos, -jnp.inf, s)
            m_prev = m_ref[:, :1]                       # (BQ, 1)
            m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
            corr = jnp.exp(m_prev - m_new)
            p = jnp.exp(s - m_new)
            p = jnp.where(jnp.isfinite(s), p, 0.0)
            vb = v_ref[0].astype(jnp.float32)
            acc_ref[...] = acc_ref[...] * corr + jax.lax.dot(
                p, vb, preferred_element_type=jnp.float32)
            l_new = l_ref[:, :1] * corr + p.sum(axis=-1, keepdims=True)
            m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
            l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

        @pl.when(j == nk - 1)
        def _finalize():
            o_ref[0] = (acc_ref[...] /
                        jnp.maximum(l_ref[:, :1], 1e-30)) \
                .astype(o_ref.dtype)

    # heads fold into the grid's leading axis: (B*H, T, D) layout
    def fold(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, t, d)

    out = pl.pallas_call(
        kernel,
        grid=(b * h, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, d),
                         lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, block_k, d),
                         lambda bh, i, j: (bh, j, 0)),
            pl.BlockSpec((1, block_k, d),
                         lambda bh, i, j: (bh, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d),
                               lambda bh, i, j: (bh, i, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, t, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
        interpret=interpret,
    )(fold(q), fold(k), fold(v))
    return out.reshape(b, h, t, d).transpose(0, 2, 1, 3)
