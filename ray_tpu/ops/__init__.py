from .bundle_kernel import schedule_bundle_groups, schedule_bundle_groups_np
from .hybrid_kernel import schedule_grouped, schedule_grouped_np
from .pull_kernel import (choose_sources, choose_sources_np,
                          choose_sources_oracle)

__all__ = ["schedule_bundle_groups", "schedule_bundle_groups_np",
           "schedule_grouped", "schedule_grouped_np",
           "choose_sources", "choose_sources_np", "choose_sources_oracle"]
