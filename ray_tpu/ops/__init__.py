from .hybrid_kernel import schedule_grouped, schedule_grouped_np

__all__ = ["schedule_grouped", "schedule_grouped_np"]
