from .broadcast_kernel import plan_fanout, plan_fanout_np, plan_fanout_oracle
from .bundle_kernel import schedule_bundle_groups, schedule_bundle_groups_np
from .flash_attention import flash_attention
from .hybrid_kernel import (schedule_grouped, schedule_grouped_np,
                            schedule_grouped_sharded_np)
from .pull_kernel import (choose_sources, choose_sources_np,
                          choose_sources_oracle)
from .ring_attention import (full_attention, ring_attention,
                             ulysses_attention)
from .shard_reduce import build_mesh, plane_for, resolve_shards

__all__ = ["schedule_bundle_groups", "schedule_bundle_groups_np",
           "schedule_grouped", "schedule_grouped_np",
           "schedule_grouped_sharded_np",
           "choose_sources", "choose_sources_np", "choose_sources_oracle",
           "plan_fanout", "plan_fanout_np", "plan_fanout_oracle",
           "flash_attention", "full_attention", "ring_attention",
           "ulysses_attention",
           "build_mesh", "plane_for", "resolve_shards"]
