"""The TPU scheduling kernel: batched hybrid placement as dense device math.

This is BASELINE.json's north star: the per-heartbeat batch of pending tasks
evaluated as one dense (tasks x nodes x resources) computation — feasibility
mask + critical-resource-utilization score + pack/spread tie-break — instead
of the reference's per-task ``HybridSchedulingPolicy::Schedule`` calls inside
the raylet event loop (``src/ray/raylet/scheduling/policy/
hybrid_scheduling_policy.cc``, invoked from
``ClusterTaskManager::ScheduleAndDispatchTasks`` — SURVEY.md §3.2 hot loop;
reference mount empty, semantics re-derived in scheduling/contract.py).

Why not lax.scan over tasks?  Sequential semantics (task t+1 sees resources
consumed by task t) would serialize 1M tiny steps — SURVEY §7 hard part 1.
The resolution implemented here:

1.  Tasks are grouped by scheduling class (identical demand vector).  The
    reference itself drains its scheduling queue class-by-class, so this is
    semantics-preserving, not an approximation.
2.  Within one class, sequential greedy placement onto min-key nodes is a
    *merge of per-node non-decreasing key sequences*: placing on the argmin
    node only raises that node's key.  The final per-node placement counts
    are therefore a water-fill: find the smallest key level L* such that the
    total number of placement slots with key <= L* covers the group, take
    every slot strictly below L*, and hand out the remaining slots at level
    L* in traversal order (the contract's tie-break).  The per-node slot
    count at level L has a closed integer form because the score is an
    integer-linear function of the placement index j:

        s(j)   = max_i ((used_i + (j+1) r_i) * S) // T_i
        s(j)<=L  ⟺  ∀i: used_i + (j+1) r_i) * S < (L+1) T_i
                 ⟺  j+1 <= ((L+1) T_i - used_i S - 1) // (r_i S)

    so "slots with key <= L" is a vectorized O(N*R) expression and L* is a
    14-step integer binary search — no data-dependent iteration counts, no
    dynamic shapes, everything jit-compiles to one XLA program.
3.  Groups run under one lax.scan carrying ``avail`` — G steps (number of
    distinct scheduling classes, typically tens), not T steps (tasks).

All arithmetic is int32 with the width audit in scheduling/contract.py, so
results are bit-identical to the numpy oracle on any backend.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..scheduling.contract import AVAIL_SHIFT, BUDGET_CAP, SCALE, SCORE_SHIFT

# Python ints (folded into the program as literals), NOT jnp scalars: a
# closure-captured device buffer — even a scalar — drops the axon TPU
# backend into a ~70ms/call synchronous slow mode for the whole process.
_BIG = 1 << 30
_INF_KEY = 2**31 - 1


def _keys_one_req(totals, avail, req, thr_fp, mask):
    """Packed int32 keys of one request vs all nodes (device twin of
    contract.compute_keys)."""
    n = totals.shape[0]
    req_pos = req > 0
    t = totals
    a = avail
    feas = jnp.all(jnp.where(req_pos[None, :], t >= req[None, :], True),
                   axis=1) & mask
    availb = jnp.all(jnp.where(req_pos[None, :], a >= req[None, :], True),
                     axis=1)
    denom = jnp.maximum(t, 1)
    q = t - a + req[None, :]
    s = jnp.where(req_pos[None, :], (q * SCALE) // denom, 0).max(
        axis=1, initial=0)
    eff = jnp.where(availb & (s < thr_fp), 0, s)
    key = ((~availb).astype(jnp.int32) << AVAIL_SHIFT) \
        | (eff << SCORE_SHIFT) | jnp.arange(n, dtype=jnp.int32)
    return jnp.where(feas, key, _INF_KEY)


def _slots_at_or_below(L, totals, used, req, req_pos, m_max, thr_fp):
    """m_n(L): per-node count of placement slots with eff-score key <= L.

    Threshold collapse: levels below thr_fp all equal the level-0 count
    (eff score of a sub-threshold available slot is 0).
    """
    Lp = jnp.where(L < thr_fp, thr_fp - 1, L)
    num = (Lp + 1) * totals - used * SCALE - 1          # (N, R)
    denom = jnp.maximum(req * SCALE, 1)[None, :]
    jc = jnp.clip(num // denom, 0, _BIG)
    jcount = jnp.where(req_pos[None, :], jc, _BIG).min(axis=1)
    return jnp.minimum(m_max, jcount)


def _schedule_group(avail, totals, node_mask, req, count, gmask, thr_fp,
                    require_available=False):
    """Place ``count`` identical requests; returns (counts_row (N+1,),
    new_avail)."""
    n = totals.shape[0]
    req_pos = req > 0
    any_req = req_pos.any()
    used = totals - avail

    feas = jnp.all(jnp.where(req_pos[None, :], totals >= req[None, :], True),
                   axis=1) & node_mask & gmask
    caps = jnp.where(req_pos[None, :],
                     avail // jnp.maximum(req, 1)[None, :], _BIG)
    m_max = jnp.where(feas & any_req, jnp.clip(caps.min(axis=1), 0, _BIG), 0)

    total_cap = m_max.sum()
    n_avail = jnp.minimum(count, total_cap)     # placements that consume
    overflow = count - n_avail                  # queue on best feasible

    m_of = partial(_slots_at_or_below, totals=totals, used=used, req=req,
                   req_pos=req_pos, m_max=m_max, thr_fp=thr_fp)

    # binary search smallest L in [0, 2*SCALE] with sum(m(L)) >= n_avail
    def bisect(carry, _):
        lo, hi = carry
        mid = (lo + hi) // 2
        ok = m_of(mid).sum() >= n_avail
        return (jnp.where(ok, lo, mid + 1), jnp.where(ok, mid, hi)), None

    (l_star, _), _ = jax.lax.scan(
        bisect, (jnp.int32(0), jnp.int32(2 * SCALE)), None,
        length=SCALE.bit_length() + 2)

    base = jnp.where(l_star > 0, m_of(jnp.maximum(l_star - 1, 0)), 0)
    at_level = m_of(l_star)
    extra = at_level - base
    rem = n_avail - base.sum()
    prefix = jnp.cumsum(extra) - extra          # exclusive, traversal order
    give = jnp.clip(rem - prefix, 0, extra)
    alloc = base + give                         # (N,) placements that consume

    new_avail = avail - alloc[:, None] * req[None, :]

    # overflow: all remaining tasks queue on the single best feasible node
    # computed on the post-allocation state (sequential semantics: once no
    # node is available, keys stop changing, so the argmin repeats).
    okeys = _keys_one_req(totals, new_avail, req, thr_fp, node_mask & gmask)
    onode = jnp.argmin(okeys).astype(jnp.int32)
    infeasible = okeys[onode] == _INF_KEY
    ocol = jnp.where(infeasible, n, onode)
    if require_available:
        # autoscaler fit semantics: feasible-but-unavailable overflow counts
        # as leftover (column n), never queued (oracle require_available
        # flag).  Overflow on an AVAILABLE node still places: that only
        # happens for empty requests, which consume nothing and are always
        # available (capacity never exhausts them into the overflow branch).
        o_avail = (okeys[onode] >> AVAIL_SHIFT) & 1 == 0
        ocol = jnp.where(infeasible | ~o_avail, n, onode)

    counts_row = jnp.zeros(n + 1, jnp.int32).at[:n].set(alloc)
    counts_row = counts_row.at[ocol].add(overflow)
    return counts_row, new_avail


@partial(jax.jit, static_argnames=("unroll", "require_available"))
def schedule_grouped(totals, avail, node_mask, group_reqs, group_counts,
                     group_masks, thr_fp, unroll: int = 1,
                     require_available: bool = False):
    """Batch-schedule G scheduling classes over N nodes on device.

    totals/avail: (N, R) int32 cu.  node_mask: (N,) bool.
    group_reqs: (G, R) int32.  group_counts: (G,) int32 (0 = padding row).
    group_masks: (G, N) bool (per-class affinity/label restriction).
    thr_fp: int32 scalar spread threshold in score fixed point.

    Returns (counts (G, N+1) int32, new_avail (N, R) int32).  Column N
    counts infeasible tasks.  Bit-identical to
    scheduling.oracle.schedule_grouped_oracle by construction.
    """
    def step(avail, xs):
        req, count, gmask = xs
        row, new_avail = _schedule_group(avail, totals, node_mask, req,
                                         count, gmask, thr_fp,
                                         require_available)
        return new_avail, row

    new_avail, counts = jax.lax.scan(
        step, avail, (group_reqs, group_counts, group_masks), unroll=unroll)
    return counts, new_avail


def _keys_one_req_host(totals, avail, req, thr_fp, mask):
    """Pure-numpy twin of ``_keys_one_req`` (int64 host arithmetic;
    values are int32-bounded by the contract audit, so results are
    bit-identical)."""
    n = totals.shape[0]
    req_pos = req > 0
    feas = np.where(req_pos[None, :], totals >= req[None, :],
                    True).all(axis=1) & mask
    availb = np.where(req_pos[None, :], avail >= req[None, :],
                      True).all(axis=1)
    denom = np.maximum(totals, 1)
    q = totals - avail + req[None, :]
    s = np.where(req_pos[None, :], (q * SCALE) // denom, 0).max(
        axis=1, initial=0)
    eff = np.where(availb & (s < thr_fp), 0, s)
    key = ((~availb).astype(np.int64) << AVAIL_SHIFT) \
        | (eff << SCORE_SHIFT) | np.arange(n, dtype=np.int64)
    return np.where(feas, key, np.int64(_INF_KEY))


def schedule_group_host(avail, totals, node_mask, req, count,
                        gmask=None, thr_fp=None, pref_row=-1,
                        require_available=False):
    """Pure-NUMPY water-fill for ONE scheduling class — no jit, no
    device transfer: the raylet's small-round dispatch path, where a
    per-round device round-trip would cost more than the math.  Same
    closed-form water-fill as ``_schedule_group`` (bit-identical; the
    parity suite compares all three of oracle/device/host).

    ``pref_row`` >= 0 applies the soft-locality semantics of
    ``schedule_grouped_localized``: a FEASIBLE preferred node takes the
    whole class (availability only gates consumption); fallback to the
    water-fill fires only when the preferred node is infeasible.

    Returns ``(counts_row (N+1,) int32, new_avail (N, R) int64)``;
    column N counts infeasible/queued-nowhere tasks.
    """
    from ..scheduling.contract import threshold_fp
    if thr_fp is None:
        thr_fp = threshold_fp(None)
    thr_fp = int(thr_fp)
    totals = np.asarray(totals, np.int64)
    avail = np.asarray(avail, np.int64)
    node_mask = np.asarray(node_mask, bool)
    req = np.asarray(req, np.int64)
    n = totals.shape[0]
    if gmask is None:
        gmask = np.ones(n, dtype=bool)
    req_pos = req > 0
    count = int(count)

    if pref_row is not None and pref_row >= 0:
        p = min(max(int(pref_row), 0), n - 1)
        feas_p = bool(np.where(req_pos, totals[p] >= req, True).all()
                      and node_mask[p] and gmask[p])
        m = count if feas_p else 0
        cap_p = int(np.where(req_pos, avail[p] // np.maximum(req, 1),
                             _BIG).min(initial=_BIG))
        consumed = min(m, max(cap_p, 0))
        avail2 = avail.copy()
        avail2[p] -= req * consumed
        rest, avail3 = schedule_group_host(
            avail2, totals, node_mask, req, count - m, gmask, thr_fp,
            pref_row=-1, require_available=require_available)
        rest[p] += m
        return rest, avail3

    any_req = bool(req_pos.any())
    used = totals - avail
    feas = np.where(req_pos[None, :], totals >= req[None, :],
                    True).all(axis=1) & node_mask & gmask
    caps = np.where(req_pos[None, :],
                    avail // np.maximum(req, 1)[None, :], _BIG)
    m_max = np.where(feas & any_req,
                     caps.min(axis=1).clip(0, _BIG), 0)
    total_cap = int(m_max.sum())
    n_avail = min(count, total_cap)
    overflow = count - n_avail

    denom_req = np.maximum(req * SCALE, 1)[None, :]
    used_scaled = used * SCALE

    def m_of(L):
        Lp = thr_fp - 1 if L < thr_fp else L
        num = (Lp + 1) * totals - used_scaled - 1
        jc = (num // denom_req).clip(0, _BIG)
        jcount = np.where(req_pos[None, :], jc, _BIG).min(axis=1)
        return np.minimum(m_max, jcount)

    lo, hi = 0, 2 * SCALE
    while lo < hi:
        mid = (lo + hi) // 2
        if int(m_of(mid).sum()) >= n_avail:
            hi = mid
        else:
            lo = mid + 1
    l_star = lo
    base = m_of(l_star - 1) if l_star > 0 else np.zeros(n, np.int64)
    extra = m_of(l_star) - base
    rem = n_avail - int(base.sum())
    prefix = np.cumsum(extra) - extra
    give = (rem - prefix).clip(0, extra)
    alloc = base + give
    new_avail = avail - alloc[:, None] * req[None, :]

    okeys = _keys_one_req_host(totals, new_avail, req, thr_fp,
                               node_mask & gmask)
    onode = int(np.argmin(okeys))
    infeasible = okeys[onode] == _INF_KEY
    ocol = n if infeasible else onode
    if require_available:
        o_avail = (int(okeys[onode]) >> AVAIL_SHIFT) & 1 == 0
        if infeasible or not o_avail:
            ocol = n
    counts_row = np.zeros(n + 1, np.int32)
    counts_row[:n] = alloc
    counts_row[ocol] += overflow
    return counts_row, new_avail


# -- delta-heartbeat kernels --------------------------------------------------
#
# The heartbeat keeps three residents in HBM between beats: the CRM mirror
# (totals/avail/mask), the interned class request matrix ``reqs`` (C, R),
# and the carried key tensor ``keys`` (C, N) — each class's packed placement
# keys against every node, bit-identical to contract.compute_keys on the
# mirror.  Per beat only the dirty slices move host->HBM and only the
# touched key columns/rows re-score; a beat's placement decisions come back
# in one fused counts readback (see scheduling.policy.DeltaScheduler).


@jax.jit
def full_rescore(totals, avail, mask, reqs, thr_fp):
    """(C, N) carried key tensor: every resident scheduling class scored
    against every node (vmapped device twin of contract.compute_keys)."""
    return jax.vmap(
        lambda r: _keys_one_req(totals, avail, r, thr_fp, mask))(reqs)


def _keys_cols(totals, avail, mask, reqs, idx, thr_fp):
    """Key columns for the B nodes in ``idx`` against all C classes —
    the delta rescore costs (C, B) instead of (C, N)."""
    t = totals[idx]                         # (B, R); padding idx clamps
    a = avail[idx]
    m = mask[idx]
    req_pos = reqs > 0                      # (C, R)
    feas = jnp.all(jnp.where(req_pos[:, None, :],
                             t[None] >= reqs[:, None, :], True),
                   axis=2) & m[None]        # (C, B)
    availb = jnp.all(jnp.where(req_pos[:, None, :],
                               a[None] >= reqs[:, None, :], True), axis=2)
    denom = jnp.maximum(t, 1)[None]
    q = t[None] - a[None] + reqs[:, None, :]
    s = jnp.where(req_pos[:, None, :], (q * SCALE) // denom, 0).max(
        axis=2, initial=0)
    eff = jnp.where(availb & (s < thr_fp), 0, s)
    key = ((~availb).astype(jnp.int32) << AVAIL_SHIFT) \
        | (eff << SCORE_SHIFT) | idx.astype(jnp.int32)[None, :]
    return jnp.where(feas, key, _INF_KEY)


@jax.jit
def apply_dirty_rows(totals, avail, mask, keys, reqs, idx,
                     row_totals, row_avail, row_mask, thr_fp):
    """Scatter B dirty node rows into the device mirror and re-score ONLY
    the touched key columns.  ``idx`` entries == N are padding lanes
    (the scatter drops them; their rescored columns are dropped too).
    Returns (totals, avail, mask, keys)."""
    totals = totals.at[idx].set(row_totals, mode="drop")
    avail = avail.at[idx].set(row_avail, mode="drop")
    mask = mask.at[idx].set(row_mask, mode="drop")
    cols = _keys_cols(totals, avail, mask, reqs, idx, thr_fp)
    keys = keys.at[:, idx].set(cols, mode="drop")
    return totals, avail, mask, keys


@jax.jit
def apply_dirty_classes(totals, avail, mask, keys, reqs, idx, class_reqs,
                        thr_fp):
    """Install B new/changed scheduling classes (slots ``idx``; padding
    == C) and re-score their full key rows.  Returns (reqs, keys)."""
    reqs = reqs.at[idx].set(class_reqs, mode="drop")
    rows = jax.vmap(
        lambda r: _keys_one_req(totals, avail, r, thr_fp, mask))(class_reqs)
    keys = keys.at[idx].set(rows, mode="drop")
    return reqs, keys


@partial(jax.jit, static_argnames=("require_available",))
def fused_beat(totals, avail, mask, keys, reqs, class_slots, group_counts,
               extra_mask, ov_idx, ov_avail, thr_fp,
               require_available=False):
    """One heartbeat against the resident mirror: per-beat ephemeral row
    overrides (the raylet's planned-load debits), an extra soft mask
    (suspect avoidance), the grouped water-fill, and the per-class argmin
    of the carried key tensor — everything the host needs comes back in
    ONE counts readback per beat, not one per class.  The water-fill's
    final carry (post-beat avail) is NOT discarded: it prices the
    per-(class, node) lease budgets (contract.compute_budgets device
    twin) that ride the same readback, so the lease plane's admission
    quotas are the device's own leftover headroom, for free.

    class_slots: (G,) int32 slots into ``reqs``.  ov_idx/ov_avail:
    (B,) int32 rows + (B, R) int32 replacement avail rows applied for
    this beat only (padding idx == N; the resident mirror is untouched).
    Returns (packed (G + C, N+1) int32 — rows [:G] are the water-fill
    counts with the overflow column, rows [G:] the per-class lease
    budgets (zero overflow column) — and argmin_rows (C,) int32)."""
    avail_eff = avail.at[ov_idx].set(ov_avail, mode="drop")
    mask_eff = mask & extra_mask
    group_reqs = reqs[jnp.clip(class_slots, 0, reqs.shape[0] - 1)]
    n = totals.shape[0]
    ones = jnp.ones((n,), bool)

    def step(av, xs):
        req, count = xs
        row, new_av = _schedule_group(av, totals, mask_eff, req, count,
                                      ones, thr_fp, require_available)
        return new_av, row

    av_fin, counts = jax.lax.scan(step, avail_eff, (group_reqs, group_counts))

    # Lease budgets off the post-beat avail.  Clamp >= 0 before the floor
    # division (contract: numpy and XLA disagree on negative ``//``), and
    # price EVERY resident class, not just this beat's active groups —
    # idle repeat classes are exactly the ones the lease plane admits
    # raylet-side without asking the head.
    av_nn = jnp.maximum(av_fin, 0)

    def budget_row(req):
        pos = req > 0
        feas = jnp.all(jnp.where(pos[None, :], totals >= req[None, :], True),
                       axis=1) & mask_eff
        fill = jnp.where(pos[None, :],
                         av_nn // jnp.maximum(req, 1)[None, :],
                         BUDGET_CAP).min(axis=1, initial=BUDGET_CAP)
        return jnp.where(feas, jnp.clip(fill, 0, BUDGET_CAP), 0)

    budgets = jax.vmap(budget_row)(reqs).astype(jnp.int32)          # (C, N)
    packed = jnp.concatenate(
        [counts, jnp.pad(budgets, ((0, 0), (0, 1)))], axis=0)       # +1 col
    amin = jnp.argmin(keys, axis=1).astype(jnp.int32)
    return packed, amin


def schedule_grouped_np(totals, avail, node_mask, group_reqs, group_counts,
                        group_masks=None, thr_fp=None, spread_threshold=None):
    """Convenience host wrapper: numpy in/out, device compute."""
    from ..scheduling.contract import threshold_fp
    if thr_fp is None:
        thr_fp = threshold_fp(spread_threshold)
    g, n = group_reqs.shape[0], totals.shape[0]
    if group_masks is None:
        group_masks = np.ones((g, n), dtype=bool)
    counts, new_avail = schedule_grouped(
        jnp.asarray(totals, jnp.int32), jnp.asarray(avail, jnp.int32),
        jnp.asarray(node_mask, bool), jnp.asarray(group_reqs, jnp.int32),
        jnp.asarray(group_counts, jnp.int32), jnp.asarray(group_masks, bool),
        jnp.int32(thr_fp))
    return np.asarray(counts), np.asarray(new_avail)


_SHARDED_JIT: dict = {}


def schedule_grouped_sharded_np(totals, avail, node_mask, group_reqs,
                                group_counts, group_masks=None,
                                thr_fp=None, spread_threshold=None,
                                n_shards: int = 0,
                                reduce_mode: str = "auto"):
    """GSPMD row-sharded twin of ``schedule_grouped_np``: node rows
    partition over the two-level ("dcn", "ici") mesh
    (ops.shard_reduce) and the water-fill's global sums lower to XLA
    collectives.  Bit-identical to the single-device call; node rows
    pad to a shard multiple with mask-False rows (kernel no-ops)."""
    from ..scheduling.contract import threshold_fp
    from .shard_reduce import gspmd_plane, pad_node_rows
    if thr_fp is None:
        thr_fp = threshold_fp(spread_threshold)
    g, n = group_reqs.shape[0], totals.shape[0]
    if group_masks is None:
        group_masks = np.ones((g, n), dtype=bool)
    pl = gspmd_plane(n_shards, reduce_mode)
    pad = pad_node_rows(n, pl.n_shards)
    if pad:
        totals = np.pad(totals, ((0, pad), (0, 0)))
        avail = np.pad(avail, ((0, pad), (0, 0)))
        node_mask = np.pad(node_mask, (0, pad))
        group_masks = np.pad(group_masks, ((0, 0), (0, pad)))
    key = ("hybrid", pl.n_shards, reduce_mode, jax.default_backend())
    step = _SHARDED_JIT.get(key)
    if step is None:
        step = _SHARDED_JIT[key] = jax.jit(
            schedule_grouped, out_shardings=(pl.sh_repl, pl.sh_rows))
    counts, new_avail = step(
        jax.device_put(np.ascontiguousarray(totals, np.int32), pl.sh_rows),
        jax.device_put(np.ascontiguousarray(avail, np.int32), pl.sh_rows),
        jax.device_put(np.ascontiguousarray(node_mask, bool), pl.sh_vec),
        jax.device_put(np.ascontiguousarray(group_reqs, np.int32),
                       pl.sh_repl),
        jax.device_put(np.ascontiguousarray(group_counts, np.int32),
                       pl.sh_repl),
        jax.device_put(np.ascontiguousarray(group_masks, bool),
                       pl.sh_cols),
        jnp.int32(thr_fp))
    counts = np.asarray(counts)             # rtlint: disable=W6
    new_avail = np.asarray(new_avail)       # rtlint: disable=W6
    if pad:
        counts = np.concatenate([counts[:, :n], counts[:, -1:]], axis=1)
        new_avail = new_avail[:n]
    return counts, new_avail
