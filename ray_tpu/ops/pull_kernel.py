"""Device pull-source selection: the PullManager's bandwidth cost model.

Reference parity: upstream's ``PullManager`` (``src/ray/object_manager/
pull_manager.cc``) prioritizes pull requests and picks transfer sources
against per-link cost/bandwidth accounting — the component BASELINE.json's
north star singles out: "the Plasma object store's pull-manager cost model
... reuse[s] the same device-resident node-bandwidth matrix" (SURVEY.md §1
layer 6, §3.3; mount empty).

TPU-first formulation: one batch of R pending pull requests is a dense
computation over the (N x N) node-bandwidth matrix resident in HBM.  For
request r the candidate score is the source's bandwidth to the
destination derated by the bytes already in flight FROM that source —

    eff[r, n] = loc[r, n] & bw[n, dest[r]] > 0
                  ? max(bw[n, dest[r]] // (1 + infl[n] // UNIT), 1) : 0
    src[r]    = argmax_n eff[r, n]          (first max -> deterministic)
    cost[r]   = size_kb[r] // eff[src[r]]   (~ transfer ms)
    infl[src[r]] += size_kb[r]              (sequential greedy)

The in-flight update runs SEQUENTIALLY over the batch (a fori_loop on
device, a plain loop in the oracle): two concurrent pulls in one
activation round therefore spread across replicas instead of both
piling onto the same "cheapest" source — the bug the derating exists to
fix.  With a zero in-flight vector the selection is bit-identical to
the historical pure-argmax kernel.  All arithmetic is int32 (sizes in
KB, bandwidth in MB/s, cost in ~ms), so CPU and TPU agree bit-for-bit
with the numpy oracle below.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

_NO_SOURCE_COST = np.int32(2**31 - 1)
# in-flight derating unit: one "stream equivalent" per 32 MB already
# queued on a source's uplink (4 default-size chunks) — eff bandwidth is
# the fair share bw / (1 + streams)
_INFLIGHT_UNIT_KB = np.int32(32 * 1024)


@jax.jit
def choose_sources(loc, bw, dest, sizes_kb, inflight_kb):
    """Pick the best transfer source for each pull request, on device.

    loc: (R, N) bool — which nodes hold a copy of each object.
    bw: (N, N) int32 — bandwidth in MB/s, ``bw[src, dst]``.
    dest: (R,) int32 — requesting node row per request.
    sizes_kb: (R,) int32 — object size in KB.
    inflight_kb: (N,) int32 — KB already assigned to transfers FROM
        each node (this batch's own picks accumulate on top).

    Returns (src (R,) int32, cost (R,) int32): ``src = -1`` when no node
    holds the object; cost ~ transfer milliseconds (KB // eff-MB/s), used
    for activation ordering.  Deterministic: ties break to the lowest row.
    """
    r = loc.shape[0]
    bw_to_dest = bw[:, dest].T                      # (R, N)

    def body(i, state):
        infl, src_acc, cost_acc = state
        raw = bw_to_dest[i]
        eff = jnp.where(
            loc[i] & (raw > 0),
            jnp.maximum(raw // (1 + infl // _INFLIGHT_UNIT_KB), 1), 0)
        s = jnp.argmax(eff).astype(jnp.int32)
        best = eff[s]
        picked = best > 0
        src_i = jnp.where(picked, s, -1)
        cost_i = jnp.where(picked, sizes_kb[i] // jnp.maximum(best, 1),
                           _NO_SOURCE_COST)
        infl = infl.at[jnp.where(picked, s, 0)].add(
            jnp.where(picked, sizes_kb[i], 0))
        return (infl, src_acc.at[i].set(src_i),
                cost_acc.at[i].set(cost_i))

    _infl, src, cost = jax.lax.fori_loop(
        0, r, body,
        (inflight_kb.astype(jnp.int32),
         jnp.full((r,), -1, dtype=jnp.int32),
         jnp.full((r,), _NO_SOURCE_COST, dtype=jnp.int32)))
    return src, cost


def choose_sources_oracle(loc: np.ndarray, bw: np.ndarray, dest: np.ndarray,
                          sizes_kb: np.ndarray,
                          inflight_kb: np.ndarray | None = None
                          ) -> tuple[np.ndarray, np.ndarray]:
    """Numpy oracle — bit-identical to ``choose_sources``."""
    loc = np.asarray(loc, dtype=bool)
    bw = np.asarray(bw, dtype=np.int32)
    dest = np.asarray(dest, dtype=np.int32)
    sizes_kb = np.asarray(sizes_kb, dtype=np.int32)
    n = bw.shape[0]
    infl = np.zeros(n, dtype=np.int32)
    if inflight_kb is not None:
        infl[:] = np.asarray(inflight_kb, dtype=np.int32)
    r = loc.shape[0]
    src = np.full(r, -1, dtype=np.int32)
    cost = np.full(r, _NO_SOURCE_COST, dtype=np.int32)
    bw_to_dest = bw[:, dest].T
    for i in range(r):
        raw = bw_to_dest[i]
        eff = np.where(
            loc[i] & (raw > 0),
            np.maximum(raw // (1 + infl // _INFLIGHT_UNIT_KB),
                       np.int32(1)),
            np.int32(0)).astype(np.int32)
        s = np.int32(eff.argmax())
        best = eff[s]
        if best > 0:
            src[i] = s
            cost[i] = sizes_kb[i] // max(np.int32(1), best)
            infl[s] += sizes_kb[i]
    return src, cost


def choose_sources_np(loc, bw, dest, sizes_kb, inflight_kb=None):
    """Host wrapper for the device kernel: pads the request axis to a
    power-of-2 bucket (avoids a fresh XLA compile per batch size) and
    returns numpy arrays."""
    loc = np.asarray(loc, dtype=bool)
    r = loc.shape[0]
    rp = max(8, 1 << (r - 1).bit_length())
    n = loc.shape[1]
    loc_p = np.zeros((rp, n), dtype=bool)
    loc_p[:r] = loc
    dest_p = np.zeros(rp, dtype=np.int32)
    dest_p[:r] = dest
    sizes_p = np.zeros(rp, dtype=np.int32)
    sizes_p[:r] = sizes_kb
    infl = np.zeros(n, dtype=np.int32)
    if inflight_kb is not None:
        infl[:] = inflight_kb
    src, cost = choose_sources(
        jnp.asarray(loc_p), jnp.asarray(bw, dtype=jnp.int32),
        jnp.asarray(dest_p), jnp.asarray(sizes_p), jnp.asarray(infl))
    return np.asarray(src)[:r], np.asarray(cost)[:r]
