"""Device pull-source selection: the PullManager's bandwidth cost model.

Reference parity: upstream's ``PullManager`` (``src/ray/object_manager/
pull_manager.cc``) prioritizes pull requests and picks transfer sources
against per-link cost/bandwidth accounting — the component BASELINE.json's
north star singles out: "the Plasma object store's pull-manager cost model
... reuse[s] the same device-resident node-bandwidth matrix" (SURVEY.md §1
layer 6, §3.3; mount empty).

TPU-first formulation: one batch of R pending pull requests is a dense
computation over the (N x N) node-bandwidth matrix resident in HBM —

    eff[r, n]  = loc[r, n] ? bw[n, dest[r]] : 0
    src[r]     = argmax_n eff[r, n]        (first max -> deterministic)
    cost[r]    = size_kb[r] // bw[src[r], dest[r]]   (~ transfer ms)

instead of a per-request host loop over object locations.  All arithmetic
is int32 (sizes in KB, bandwidth in MB/s, cost in ~ms), so CPU and TPU
agree bit-for-bit with the numpy oracle below.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

_NO_SOURCE_COST = np.int32(2**31 - 1)


@jax.jit
def choose_sources(loc, bw, dest, sizes_kb):
    """Pick the best transfer source for each pull request, on device.

    loc: (R, N) bool — which nodes hold a copy of each object.
    bw: (N, N) int32 — bandwidth in MB/s, ``bw[src, dst]``.
    dest: (R,) int32 — requesting node row per request.
    sizes_kb: (R,) int32 — object size in KB.

    Returns (src (R,) int32, cost (R,) int32): ``src = -1`` when no node
    holds the object; cost ~ transfer milliseconds (KB // MB/s), used for
    activation ordering.  Deterministic: ties break to the lowest row.
    """
    bw_to_dest = bw[:, dest].T                      # (R, N)
    eff = jnp.where(loc, bw_to_dest, 0)
    src = jnp.argmax(eff, axis=1).astype(jnp.int32)
    best = jnp.take_along_axis(eff, src[:, None], axis=1)[:, 0]
    cost = jnp.where(best > 0, sizes_kb // jnp.maximum(best, 1),
                     _NO_SOURCE_COST)
    return jnp.where(best > 0, src, -1), cost


def choose_sources_oracle(loc: np.ndarray, bw: np.ndarray, dest: np.ndarray,
                          sizes_kb: np.ndarray
                          ) -> tuple[np.ndarray, np.ndarray]:
    """Numpy oracle — bit-identical to ``choose_sources``."""
    loc = np.asarray(loc, dtype=bool)
    bw = np.asarray(bw, dtype=np.int32)
    dest = np.asarray(dest, dtype=np.int32)
    sizes_kb = np.asarray(sizes_kb, dtype=np.int32)
    eff = np.where(loc, bw[:, dest].T, 0).astype(np.int32)
    src = eff.argmax(axis=1).astype(np.int32)
    best = np.take_along_axis(eff, src[:, None], axis=1)[:, 0]
    cost = np.where(best > 0, sizes_kb // np.maximum(best, 1),
                    _NO_SOURCE_COST).astype(np.int32)
    return np.where(best > 0, src, -1).astype(np.int32), cost


def choose_sources_np(loc, bw, dest, sizes_kb):
    """Host wrapper for the device kernel: pads the request axis to a
    power-of-2 bucket (avoids a fresh XLA compile per batch size) and
    returns numpy arrays."""
    loc = np.asarray(loc, dtype=bool)
    r = loc.shape[0]
    rp = max(8, 1 << (r - 1).bit_length())
    n = loc.shape[1]
    loc_p = np.zeros((rp, n), dtype=bool)
    loc_p[:r] = loc
    dest_p = np.zeros(rp, dtype=np.int32)
    dest_p[:r] = dest
    sizes_p = np.zeros(rp, dtype=np.int32)
    sizes_p[:r] = sizes_kb
    src, cost = choose_sources(
        jnp.asarray(loc_p), jnp.asarray(bw, dtype=jnp.int32),
        jnp.asarray(dest_p), jnp.asarray(sizes_p))
    return np.asarray(src)[:r], np.asarray(cost)[:r]
