"""TPU autoscaler kernel: demand bin-packing onto node types (config #5).

Device twin of ``ray_tpu/autoscaler/demand.py`` — see its docstring for the
contract and reference citation (upstream ``ResourceDemandScheduler``,
SURVEY.md layer 11; mount empty, contract re-derived).

Phase 1 (fit onto existing nodes) IS the water-fill kernel
(``schedule_grouped`` with the first-fit threshold and
``require_available=True``).  Phase 2 is the launch loop: each iteration
first-fit-packs one virtual node of EVERY type in parallel (a ``lax.scan``
over demand classes carrying per-type used vectors), picks the best type by
(utilization score, lowest index), and batch-launches the repeat factor.
The loop is a ``lax.while_loop`` bounded by G*K + G + K + 2 iterations (the
contract's progress argument), independent of demand counts — 1M pending
demands cost the same as 1k.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..common.resources import MAX_TOTAL_CU
from ..scheduling.contract import SCALE
from .hybrid_kernel import _BIG, schedule_grouped

# Smallest fixed-point threshold above max score => first-fit traversal
# while keeping (L+1)*totals within int32 (see contract.py width audit).
FIRST_FIT_THR_FP = 2 * SCALE + 1


def _pack_all_types(type_caps, demand_reqs, remaining):
    """First-fit one fresh node of every type: (packed (K, G), used (K, R))."""
    K, R = type_caps.shape

    def step(used, xs):
        req, rem = xs
        pos = req > 0
        space = type_caps - used
        fit = jnp.where(pos[None, :],
                        space // jnp.maximum(req, 1)[None, :],
                        _BIG).min(axis=1)
        fit = jnp.clip(fit, 0, jnp.maximum(rem, 0))
        fit = jnp.where(pos.any(), fit, 0)
        return used + fit[:, None] * req[None, :], fit

    used, packed = jax.lax.scan(
        step, jnp.zeros((K, R), jnp.int32), (demand_reqs, remaining))
    return packed.T, used


def _launch_loop(type_caps, type_quotas, demand_reqs, remaining, max_iters):
    K = type_caps.shape[0]

    def cond(carry):
        remaining, quota, launches, it, done = carry
        return (remaining.sum() > 0) & ~done & (it < max_iters)

    def body(carry):
        remaining, quota, launches, it, _ = carry
        packed, used = _pack_all_types(type_caps, demand_reqs, remaining)
        score = jnp.where(type_caps > 0,
                          (used * SCALE) // jnp.maximum(type_caps, 1),
                          0).max(axis=1)
        eligible = (quota > 0) & (packed.sum(axis=1) > 0)
        s_eff = jnp.where(eligible, score, -1)
        k = jnp.argmax(s_eff).astype(jnp.int32)   # first max = lowest index
        ok = s_eff[k] >= 0
        p = packed[k]
        t = jnp.where(p > 0, remaining // jnp.maximum(p, 1), _BIG).min()
        t = jnp.maximum(jnp.minimum(t, quota[k]), 1)
        remaining = jnp.where(ok, jnp.maximum(remaining - t * p, 0),
                              remaining)
        quota = jnp.where(ok, quota.at[k].add(-t), quota)
        launches = jnp.where(ok, launches.at[k].add(t), launches)
        return remaining, quota, launches, it + 1, ~ok

    init = (remaining, type_quotas, jnp.zeros(K, jnp.int32), jnp.int32(0),
            jnp.bool_(False))
    remaining, _, launches, _, _ = jax.lax.while_loop(cond, body, init)
    return launches, remaining


@jax.jit
def autoscale(totals, avail, node_mask, demand_reqs, demand_counts,
              type_caps, type_quotas, extra_mask=None):
    """Full demand-scheduler pass on device.

    totals/avail: (N, R) int32 cu existing nodes.  node_mask: (N,) bool.
    demand_reqs: (G, R) int32.  demand_counts: (G,) int32.
    type_caps: (K, R) int32.  type_quotas: (K,) int32.
    extra_mask: optional (N,) bool beat-scoped node filter (suspect
    soft-mask) ANDed into node_mask without re-uploading it.

    Returns (launches (K,), fit_counts (G, N+1), unmet (G,), new_avail).
    Bit-identical to autoscaler.demand.get_nodes_to_launch.
    """
    if extra_mask is not None:
        node_mask = node_mask & extra_mask
    G, N = demand_reqs.shape[0], totals.shape[0]
    gmasks = jnp.ones((G, N), dtype=bool)
    fit_counts, new_avail = schedule_grouped(
        totals, avail, node_mask, demand_reqs, demand_counts, gmasks,
        jnp.int32(FIRST_FIT_THR_FP), require_available=True)
    remaining = fit_counts[:, -1]
    zero_rows = ~(demand_reqs > 0).any(axis=1)
    remaining = jnp.where(zero_rows, 0, remaining)
    K = type_caps.shape[0]
    max_iters = G * K + G + K + 2
    launches, unmet = _launch_loop(type_caps, type_quotas, demand_reqs,
                                   remaining, max_iters)
    return launches, fit_counts, unmet, new_avail


def autoscale_np(totals, avail, node_mask, demand_reqs, demand_counts,
                 type_caps, type_quotas, extra_mask=None):
    """Host wrapper: numpy in/out, device compute.

    Enforces the int32 width contract on node-type capacities: the launch
    loop computes ``used * SCALE`` in int32 (the oracle uses int64), which
    is only exact for caps within MAX_TOTAL_CU — the same bound
    ``common.resources.to_cu`` applies to real node resources.
    """
    if (np.asarray(type_caps) > MAX_TOTAL_CU).any():
        raise ValueError(
            f"type_caps exceed MAX_TOTAL_CU={MAX_TOTAL_CU} cu "
            "(int32 score-arithmetic contract)")
    out = autoscale(
        jnp.asarray(totals, jnp.int32), jnp.asarray(avail, jnp.int32),
        jnp.asarray(node_mask, bool), jnp.asarray(demand_reqs, jnp.int32),
        jnp.asarray(demand_counts, jnp.int32),
        jnp.asarray(type_caps, jnp.int32), jnp.asarray(type_quotas, jnp.int32),
        None if extra_mask is None else jnp.asarray(extra_mask, bool))
    return tuple(np.asarray(o) for o in out)


_SHARDED_JIT: dict = {}


def autoscale_sharded_np(totals, avail, node_mask, demand_reqs,
                         demand_counts, type_caps, type_quotas,
                         extra_mask=None, n_shards: int = 0,
                         reduce_mode: str = "auto"):
    """GSPMD row-sharded twin of ``autoscale_np``: existing-node rows
    partition over the two-level mesh (ops.shard_reduce) for the
    phase-1 fit; the phase-2 launch loop's (K, R) state is tiny and
    stays replicated.  Bit-identical to the single-device call."""
    from .shard_reduce import gspmd_plane, pad_node_rows
    caps_h = np.asarray(type_caps)      # rtlint: disable=W6
    if (caps_h > MAX_TOTAL_CU).any():
        raise ValueError(
            f"type_caps exceed MAX_TOTAL_CU={MAX_TOTAL_CU} cu "
            "(int32 score-arithmetic contract)")
    n = totals.shape[0]
    pl = gspmd_plane(n_shards, reduce_mode)
    pad = pad_node_rows(n, pl.n_shards)
    if pad:
        totals = np.pad(totals, ((0, pad), (0, 0)))
        avail = np.pad(avail, ((0, pad), (0, 0)))
        node_mask = np.pad(node_mask, (0, pad))
        if extra_mask is not None:
            extra_mask = np.pad(extra_mask, (0, pad))
    key = (pl.n_shards, reduce_mode, jax.default_backend())
    step = _SHARDED_JIT.get(key)
    if step is None:
        step = _SHARDED_JIT[key] = jax.jit(
            autoscale, out_shardings=(pl.sh_repl, pl.sh_repl,
                                      pl.sh_repl, pl.sh_rows))
    launches, fit_counts, unmet, new_avail = step(
        jax.device_put(np.ascontiguousarray(totals, np.int32), pl.sh_rows),
        jax.device_put(np.ascontiguousarray(avail, np.int32), pl.sh_rows),
        jax.device_put(np.ascontiguousarray(node_mask, bool), pl.sh_vec),
        jax.device_put(np.ascontiguousarray(demand_reqs, np.int32),
                       pl.sh_repl),
        jax.device_put(np.ascontiguousarray(demand_counts, np.int32),
                       pl.sh_repl),
        jax.device_put(np.ascontiguousarray(type_caps, np.int32),
                       pl.sh_repl),
        jax.device_put(np.ascontiguousarray(type_quotas, np.int32),
                       pl.sh_repl),
        None if extra_mask is None else
        jax.device_put(np.ascontiguousarray(extra_mask, bool), pl.sh_vec))
    launches = np.asarray(launches)         # rtlint: disable=W6
    fit_counts = np.asarray(fit_counts)     # rtlint: disable=W6
    unmet = np.asarray(unmet)               # rtlint: disable=W6
    new_avail = np.asarray(new_avail)       # rtlint: disable=W6
    if pad:
        fit_counts = np.concatenate([fit_counts[:, :n],
                                     fit_counts[:, -1:]], axis=1)
        new_avail = new_avail[:n]
    return launches, fit_counts, unmet, new_avail
