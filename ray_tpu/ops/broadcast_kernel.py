"""Device broadcast tree shaping: the fan-out plan kernel.

1->N weight distribution wants a relay tree whose shape follows the
node-bandwidth matrix — the same dense HBM-resident input the pull
cost model (``ops/pull_kernel.py``) scores transfer sources against.
This kernel reuses that formulation for the 1->N case: given the
member set, the root, a fan-out cap and the current per-node uplink
load, it emits a parent assignment plus the attach order (the chunk
schedule follows attach order — an earlier-attached member starts
receiving, and therefore relaying, sooner).

Greedy one-attach-per-step construction, all int32:

    step k:  eff[p, c] = covered[p] & member[c] & ~covered[c]
                           & children[p] < fanout & bw[p, c] > 0
               ? max(bw[p, c] // ((1 + children[p]
                                     + inflight_kb[p] // UNIT)
                                  * (1 + depth[p])), 1) : 0
             (p*, c*) = argmax eff   (flat row-major, first max)
             parent[c*] = p*; order[c*] = k
             depth[c*] = depth[p*] + 1; children[p*] += 1

Two deratings shape the tree.  The load term (children + uplink
in-flight, same 32 MB stream unit as the pull cost model) makes a
parent that already feeds children progressively less attractive, so
the tree spreads across the topology instead of every member chaining
off the root.  The depth term charges a parent for its own distance
from the root — without it a freshly attached leaf always out-scores
a once-loaded parent and a uniform-bandwidth matrix degenerates to an
N-deep chain; with it the same matrix yields a balanced fanout-F tree
(depth ~log_F N).  Ties break to the lowest (parent, child) pair —
deterministic on both backends.  The CPU oracle below is bit-identical
(same discipline as the hybrid/pull kernels); ``plan_fanout_np`` pads
the node axis to a power-of-2 bucket for a stable XLA compile cache.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

# same stream-equivalent unit as the pull cost model: every 32 MB in
# flight on a node's uplink counts as one extra concurrent stream
_INFLIGHT_UNIT_KB = np.int32(32 * 1024)


@jax.jit
def plan_fanout(member, bw, root, fanout, inflight_kb):
    """Shape the broadcast tree, on device.

    member: (N,) bool — broadcast participants (root included).
    bw: (N, N) int32 — bandwidth in MB/s, ``bw[src, dst]``.
    root: int32 scalar — row of the origin replica.
    fanout: int32 scalar — max children per node (>= 1).
    inflight_kb: (N,) int32 — KB already in flight FROM each node.

    Returns (parent (N,) int32, order (N,) int32): ``parent[c]`` is the
    node c relays from (-1 for the root and non-members), ``order[c]``
    the attach step (0-based; -1 for the root and non-members).  A
    member left unattached (unreachable bandwidth row) keeps -1/-1.
    """
    n = member.shape[0]
    units = inflight_kb.astype(jnp.int32) // _INFLIGHT_UNIT_KB

    def body(k, state):
        covered, children, depth, parent, order = state
        # (p, c) eligibility + load- and depth-derated uplink score
        can_parent = covered & (children < fanout)          # (N,)
        want_child = member & ~covered                      # (N,)
        denom = (1 + children + units) * (1 + depth)        # (N,)
        eff = jnp.where(
            can_parent[:, None] & want_child[None, :] & (bw > 0),
            jnp.maximum(bw // denom[:, None], 1), 0)
        idx = jnp.argmax(eff.reshape(-1)).astype(jnp.int32)
        p, c = idx // n, idx % n
        hit = eff.reshape(-1)[idx] > 0
        parent = parent.at[c].set(jnp.where(hit, p, parent[c]))
        order = order.at[c].set(jnp.where(hit, k, order[c]))
        depth = depth.at[c].set(jnp.where(hit, depth[p] + 1, depth[c]))
        covered = covered.at[c].set(jnp.where(hit, True, covered[c]))
        children = children.at[p].add(jnp.where(hit, 1, 0))
        return covered, children, depth, parent, order

    covered0 = jnp.zeros((n,), dtype=bool).at[root].set(True)
    state = (covered0,
             jnp.zeros((n,), dtype=jnp.int32),
             jnp.zeros((n,), dtype=jnp.int32),
             jnp.full((n,), -1, dtype=jnp.int32),
             jnp.full((n,), -1, dtype=jnp.int32))
    _cov, _ch, _dep, parent, order = jax.lax.fori_loop(0, n, body, state)
    return parent, order


def plan_fanout_oracle(member: np.ndarray, bw: np.ndarray, root: int,
                       fanout: int,
                       inflight_kb: np.ndarray | None = None
                       ) -> tuple[np.ndarray, np.ndarray]:
    """Numpy oracle — bit-identical to ``plan_fanout``."""
    member = np.asarray(member, dtype=bool)
    bw = np.asarray(bw, dtype=np.int32)
    n = member.shape[0]
    units = np.zeros(n, dtype=np.int32)
    if inflight_kb is not None:
        units[:] = np.asarray(inflight_kb,
                              dtype=np.int32) // _INFLIGHT_UNIT_KB
    covered = np.zeros(n, dtype=bool)
    covered[root] = True
    children = np.zeros(n, dtype=np.int32)
    depth = np.zeros(n, dtype=np.int32)
    parent = np.full(n, -1, dtype=np.int32)
    order = np.full(n, -1, dtype=np.int32)
    for k in range(n):
        can_parent = covered & (children < fanout)
        want_child = member & ~covered
        denom = ((1 + children + units) * (1 + depth)).astype(np.int32)
        eff = np.where(
            can_parent[:, None] & want_child[None, :] & (bw > 0),
            np.maximum(bw // denom[:, None], np.int32(1)),
            np.int32(0)).astype(np.int32)
        idx = int(eff.reshape(-1).argmax())
        p, c = idx // n, idx % n
        if eff.reshape(-1)[idx] <= 0:
            continue        # matches the device no-op step
        parent[c] = p
        order[c] = k
        depth[c] = depth[p] + 1
        covered[c] = True
        children[p] += 1
    return parent, order


def plan_fanout_np(member, bw, root: int, fanout: int, inflight_kb=None):
    """Host wrapper for the device kernel: pads the node axis to a
    power-of-2 bucket (stable XLA compile cache) and returns numpy
    arrays.  Padded rows are non-members with zero bandwidth, so they
    can never be chosen; step count grows with the padding but every
    extra step is a no-op argmax over zeros."""
    member = np.asarray(member, dtype=bool)     # rtlint: disable=W6
    n = member.shape[0]
    npad = max(8, 1 << (n - 1).bit_length())
    mem_p = np.zeros(npad, dtype=bool)
    mem_p[:n] = member
    bw_p = np.zeros((npad, npad), dtype=np.int32)
    bw_p[:n, :n] = bw
    infl_p = np.zeros(npad, dtype=np.int32)
    if inflight_kb is not None:
        infl_p[:n] = inflight_kb
    parent, order = plan_fanout(
        jnp.asarray(mem_p), jnp.asarray(bw_p),
        jnp.int32(root), jnp.int32(fanout), jnp.asarray(infl_p))
    parent = np.asarray(parent)[:n]             # rtlint: disable=W6
    order = np.asarray(order)[:n]               # rtlint: disable=W6
    return parent, order
