"""Device scheduling extensions: soft-locality and top-k water-fill.

These put the REMAINING live scheduling surfaces on device
(VERDICT r03 item 5): rounds with locality-biased tasks, lease-spillback
avoidance, and top-k sampling no longer force the host policy.

``schedule_grouped_localized`` — per-group soft node affinity (the
raylet's locality row): up to the preferred node's availability capacity
places there first, the remainder water-fills.  Bit-identical to the
sequential host path (NodeAffinity-soft per task, then hybrid fallback)
by the same argument as the grouped contract: the host consumes the
preferred node's availability task-by-task until it runs out — exactly
the floor-div capacity — and the fallback tasks form a uniform hybrid
batch (reference: locality-aware lease targeting + HybridPolicy —
SURVEY.md §2.5; mount empty).

``schedule_grouped_topk`` — the contention-spread mode
(``scheduler_top_k_fraction``): each class's tasks spread EVENLY over
its k best-keyed feasible nodes, rotated by one pinned random draw per
(seed, round, group).  DOCUMENTED DIVERGENCE from the host sampler:
the host draws per task from a Philox stream (uniform over top-k in
expectation); the device spreads exactly evenly with a random rotation
— same spreading intent, deterministic replay via the pinned seed, but
the two backends' draws differ, so top-k rounds are not bit-compared
across backends (fraction = 0 remains the bit-exact-parity mode).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from .hybrid_kernel import (_BIG, _INF_KEY, _keys_one_req,
                            _schedule_group)


@jax.jit
def schedule_grouped_localized(totals, avail, node_mask, group_reqs,
                               group_counts, group_masks, pref_rows,
                               thr_fp, extra_mask=None):
    """Like ``schedule_grouped`` with per-group soft locality.

    pref_rows: (G,) int32 preferred node row per group, -1 = none.
    extra_mask: optional (N,) bool beat-scoped node filter (suspect
    soft-mask) ANDed into node_mask without re-uploading it.
    Returns (counts (G, N+1), new_avail)."""
    if extra_mask is not None:
        node_mask = node_mask & extra_mask
    n = totals.shape[0]

    def step(avail, xs):
        req, count, gmask, pref = xs
        has_pref = pref >= 0
        p = jnp.clip(pref, 0, n - 1)
        req_pos = req > 0
        feas_p = jnp.all(jnp.where(req_pos, totals[p] >= req, True)) \
            & node_mask[p] & gmask[p]
        # host NodeAffinity-soft semantics: a FEASIBLE preferred node
        # takes every task (they queue there); availability only gates
        # how much is consumed.  Fallback fires only when infeasible.
        m = jnp.where(has_pref & feas_p, count, 0).astype(jnp.int32)
        cap_p = jnp.where(req_pos, avail[p] // jnp.maximum(req, 1),
                          _BIG).min()
        consumed = jnp.minimum(m, jnp.clip(cap_p, 0, _BIG))
        avail2 = avail.at[p].add(-req * consumed)
        rest, avail3 = _schedule_group(avail2, totals, node_mask, req,
                                       count - m, gmask, thr_fp, False)
        return avail3, rest.at[p].add(m)

    new_avail, counts = jax.lax.scan(
        step, avail, (group_reqs, group_counts, group_masks, pref_rows))
    return counts, new_avail


@partial(jax.jit, static_argnames=())
def schedule_grouped_topk(totals, avail, node_mask, group_reqs,
                          group_counts, group_masks, thr_fp, k_abs,
                          k_frac_num, k_frac_den, rng_key,
                          extra_mask=None):
    """Top-k contention spread on device (see module docstring).

    k per group = min(feasible, max(k_abs,
    ceil(feasible * k_frac_num / k_frac_den))).  Each group's tasks
    spread evenly over its k best keys with a rotated remainder;
    consuming placements are capped by per-node availability (the host
    sampler likewise only subtracts from available nodes — tasks beyond
    capacity queue without consuming)."""
    if extra_mask is not None:
        node_mask = node_mask & extra_mask
    n = totals.shape[0]

    def step(carry, xs):
        avail, key = carry
        req, count, gmask, gi = xs
        keys = _keys_one_req(totals, avail, req, thr_fp,
                             node_mask & gmask)
        feasible = keys != _INF_KEY
        nf = feasible.sum().astype(jnp.int32)
        # ceil(nf * num / den): parenthesize — unary minus binds tighter
        # than //, so -(-x)//d would floor instead
        k = jnp.maximum(k_abs, -((-nf * k_frac_num) // k_frac_den))
        k = jnp.clip(k, 1, jnp.maximum(nf, 1))
        order = jnp.argsort(keys, stable=True)      # best first
        in_topk = jnp.arange(n, dtype=jnp.int32) < k
        # even spread with a pinned random rotation for the remainder
        gkey = jax.random.fold_in(key, gi)
        offset = jax.random.randint(gkey, (), 0, jnp.maximum(k, 1))
        base = count // jnp.maximum(k, 1)
        extra_n = count - base * k
        pos = jnp.arange(n, dtype=jnp.int32)
        gets_extra = ((pos - offset) % jnp.maximum(k, 1)) < extra_n
        per_slot = jnp.where(in_topk, base + gets_extra, 0)
        counts_sorted = jnp.where(nf > 0, per_slot, 0)
        alloc = jnp.zeros(n, jnp.int32).at[order].set(counts_sorted)
        # consume only up to availability (queued tasks don't subtract)
        req_pos = req > 0
        caps = jnp.where(req_pos[None, :],
                         avail // jnp.maximum(req, 1)[None, :], _BIG)
        cap = jnp.clip(caps.min(axis=1), 0, _BIG)
        consumed = jnp.minimum(alloc, cap)
        new_avail = avail - consumed[:, None] * req[None, :]
        # no feasible node: the whole class overflows to column n
        row = jnp.where(nf > 0,
                        jnp.zeros(n + 1, jnp.int32).at[:n].set(alloc),
                        jnp.zeros(n + 1, jnp.int32).at[n].set(count))
        return (new_avail, key), row

    (new_avail, _), counts = jax.lax.scan(
        step, (avail, rng_key),
        (group_reqs, group_counts, group_masks,
         jnp.arange(group_reqs.shape[0], dtype=jnp.int32)))
    return counts, new_avail


# -- host wrappers -----------------------------------------------------------

def schedule_grouped_localized_np(totals, avail, node_mask, group_reqs,
                                  group_counts, pref_rows,
                                  group_masks=None, thr_fp=None,
                                  spread_threshold=None,
                                  extra_mask=None):
    from ..scheduling.contract import threshold_fp
    if thr_fp is None:
        thr_fp = threshold_fp(spread_threshold)
    g, n = group_reqs.shape[0], totals.shape[0]
    if group_masks is None:
        group_masks = np.ones((g, n), dtype=bool)
    counts, new_avail = schedule_grouped_localized(
        jnp.asarray(totals, jnp.int32), jnp.asarray(avail, jnp.int32),
        jnp.asarray(node_mask, bool), jnp.asarray(group_reqs, jnp.int32),
        jnp.asarray(group_counts, jnp.int32),
        jnp.asarray(group_masks, bool),
        jnp.asarray(pref_rows, jnp.int32), jnp.int32(thr_fp),
        None if extra_mask is None else jnp.asarray(extra_mask, bool))
    return np.asarray(counts), np.asarray(new_avail)


def schedule_grouped_topk_np(totals, avail, node_mask, group_reqs,
                             group_counts, seed, round_index,
                             group_masks=None, thr_fp=None,
                             spread_threshold=None, k_abs=1,
                             k_frac=0.0, extra_mask=None):
    from fractions import Fraction

    from ..scheduling.contract import threshold_fp
    if thr_fp is None:
        thr_fp = threshold_fp(spread_threshold)
    g, n = group_reqs.shape[0], totals.shape[0]
    if group_masks is None:
        group_masks = np.ones((g, n), dtype=bool)
    frac = Fraction(k_frac).limit_denominator(1 << 16)
    rng_key = jax.random.fold_in(
        jax.random.PRNGKey(int(seed)), int(round_index))
    counts, new_avail = schedule_grouped_topk(
        jnp.asarray(totals, jnp.int32), jnp.asarray(avail, jnp.int32),
        jnp.asarray(node_mask, bool), jnp.asarray(group_reqs, jnp.int32),
        jnp.asarray(group_counts, jnp.int32),
        jnp.asarray(group_masks, bool), jnp.int32(thr_fp),
        jnp.int32(max(int(k_abs), 1)),
        jnp.int32(frac.numerator), jnp.int32(max(frac.denominator, 1)),
        rng_key,
        None if extra_mask is None else jnp.asarray(extra_mask, bool))
    return np.asarray(counts), np.asarray(new_avail)


_SHARDED_JIT: dict = {}


def _sharded_call(name, fn, pl, reduce_mode):
    key = (name, pl.n_shards, reduce_mode, jax.default_backend())
    step = _SHARDED_JIT.get(key)
    if step is None:
        step = _SHARDED_JIT[key] = jax.jit(
            fn, out_shardings=(pl.sh_repl, pl.sh_rows))
    return step


def schedule_grouped_localized_sharded_np(totals, avail, node_mask,
                                          group_reqs, group_counts,
                                          pref_rows, group_masks=None,
                                          thr_fp=None,
                                          spread_threshold=None,
                                          extra_mask=None,
                                          n_shards: int = 0,
                                          reduce_mode: str = "auto"):
    """GSPMD row-sharded twin of ``schedule_grouped_localized_np``:
    node rows partition over the two-level mesh (ops.shard_reduce),
    global reductions lower to XLA collectives.  Bit-identical."""
    from ..scheduling.contract import threshold_fp
    from .shard_reduce import gspmd_plane, pad_node_rows
    if thr_fp is None:
        thr_fp = threshold_fp(spread_threshold)
    g, n = group_reqs.shape[0], totals.shape[0]
    if group_masks is None:
        group_masks = np.ones((g, n), dtype=bool)
    pl = gspmd_plane(n_shards, reduce_mode)
    pad = pad_node_rows(n, pl.n_shards)
    if pad:
        totals = np.pad(totals, ((0, pad), (0, 0)))
        avail = np.pad(avail, ((0, pad), (0, 0)))
        node_mask = np.pad(node_mask, (0, pad))
        group_masks = np.pad(group_masks, ((0, 0), (0, pad)))
        if extra_mask is not None:
            extra_mask = np.pad(extra_mask, (0, pad))
    step = _sharded_call("localized", schedule_grouped_localized, pl,
                         reduce_mode)
    counts, new_avail = step(
        jax.device_put(np.ascontiguousarray(totals, np.int32), pl.sh_rows),
        jax.device_put(np.ascontiguousarray(avail, np.int32), pl.sh_rows),
        jax.device_put(np.ascontiguousarray(node_mask, bool), pl.sh_vec),
        jax.device_put(np.ascontiguousarray(group_reqs, np.int32),
                       pl.sh_repl),
        jax.device_put(np.ascontiguousarray(group_counts, np.int32),
                       pl.sh_repl),
        jax.device_put(np.ascontiguousarray(group_masks, bool), pl.sh_cols),
        jax.device_put(np.ascontiguousarray(pref_rows, np.int32),
                       pl.sh_repl),
        jnp.int32(thr_fp),
        None if extra_mask is None else
        jax.device_put(np.ascontiguousarray(extra_mask, bool), pl.sh_vec))
    counts = np.asarray(counts)             # rtlint: disable=W6
    new_avail = np.asarray(new_avail)       # rtlint: disable=W6
    if pad:
        counts = np.concatenate([counts[:, :n], counts[:, -1:]], axis=1)
        new_avail = new_avail[:n]
    return counts, new_avail


def schedule_grouped_topk_sharded_np(totals, avail, node_mask, group_reqs,
                                     group_counts, seed, round_index,
                                     group_masks=None, thr_fp=None,
                                     spread_threshold=None, k_abs=1,
                                     k_frac=0.0, extra_mask=None,
                                     n_shards: int = 0,
                                     reduce_mode: str = "auto"):
    """GSPMD row-sharded twin of ``schedule_grouped_topk_np`` (same
    padding + collective-lowering story as the localized variant)."""
    from fractions import Fraction

    from ..scheduling.contract import threshold_fp
    from .shard_reduce import gspmd_plane, pad_node_rows
    if thr_fp is None:
        thr_fp = threshold_fp(spread_threshold)
    g, n = group_reqs.shape[0], totals.shape[0]
    if group_masks is None:
        group_masks = np.ones((g, n), dtype=bool)
    pl = gspmd_plane(n_shards, reduce_mode)
    pad = pad_node_rows(n, pl.n_shards)
    if pad:
        totals = np.pad(totals, ((0, pad), (0, 0)))
        avail = np.pad(avail, ((0, pad), (0, 0)))
        node_mask = np.pad(node_mask, (0, pad))
        group_masks = np.pad(group_masks, ((0, 0), (0, pad)))
        if extra_mask is not None:
            extra_mask = np.pad(extra_mask, (0, pad))
    frac = Fraction(k_frac).limit_denominator(1 << 16)
    rng_key = jax.random.fold_in(
        jax.random.PRNGKey(int(seed)), int(round_index))
    step = _sharded_call("topk", schedule_grouped_topk, pl, reduce_mode)
    counts, new_avail = step(
        jax.device_put(np.ascontiguousarray(totals, np.int32), pl.sh_rows),
        jax.device_put(np.ascontiguousarray(avail, np.int32), pl.sh_rows),
        jax.device_put(np.ascontiguousarray(node_mask, bool), pl.sh_vec),
        jax.device_put(np.ascontiguousarray(group_reqs, np.int32),
                       pl.sh_repl),
        jax.device_put(np.ascontiguousarray(group_counts, np.int32),
                       pl.sh_repl),
        jax.device_put(np.ascontiguousarray(group_masks, bool), pl.sh_cols),
        jnp.int32(thr_fp), jnp.int32(max(int(k_abs), 1)),
        jnp.int32(frac.numerator), jnp.int32(max(frac.denominator, 1)),
        rng_key,
        None if extra_mask is None else
        jax.device_put(np.ascontiguousarray(extra_mask, bool), pl.sh_vec))
    counts = np.asarray(counts)             # rtlint: disable=W6
    new_avail = np.asarray(new_avail)       # rtlint: disable=W6
    if pad:
        counts = np.concatenate([counts[:, :n], counts[:, -1:]], axis=1)
        new_avail = new_avail[:n]
    return counts, new_avail
