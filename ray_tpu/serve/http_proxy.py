"""Serve HTTP ingress: routes requests to deployment replica pools.

Reference parity: Serve's HTTP proxy is its primary surface — an HTTP
server on every node routes ``/route_prefix`` requests into deployment
replica sets, JSON in/out, with per-request timeouts
(``python/ray/serve/_private/proxy.py``, SURVEY.md §1 layer 14; mount
empty).  Here one ingress runs in the driver/head process on the shared
``BackgroundHTTPServer`` scaffolding; ``serve.run(..., route_prefix=…)``
binds a prefix to the application's handle.

Replicas see a plain ``HTTPRequest`` value (method, path, query, body)
and may return ``bytes``/``str`` (sent raw) or any JSON-serializable
value (sent as ``application/json``).
"""

from __future__ import annotations

import json
import threading
from dataclasses import dataclass, field
from urllib.parse import parse_qsl, urlsplit

from ..runtime.http_server import BackgroundHTTPServer


@dataclass
class HTTPRequest:
    """What a deployment's ``__call__`` receives for an HTTP request."""

    method: str
    path: str                       # full path, route prefix included
    query: dict = field(default_factory=dict)
    body: bytes = b""

    def json(self):
        return json.loads(self.body) if self.body else None


class HttpIngress(BackgroundHTTPServer):
    allowed_methods = ("GET", "POST", "PUT", "DELETE")

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 request_timeout_s: float = 30.0,
                 max_body_bytes: int = 64 * 1024 * 1024):
        self._routes: dict[str, object] = {}    # prefix -> handle
        self._rlock = threading.Lock()
        self._timeout = request_timeout_s
        self._max_body = max_body_bytes
        super().__init__(host=host, port=port, name="serve-http")

    @property
    def address(self) -> str:
        return f"http://{self.host}:{self.port}"

    def add_route(self, prefix: str, handle,
                  stream: bool = False) -> None:
        """``stream=True``: the deployment's handler is a GENERATOR —
        responses go out with chunked transfer encoding, one chunk per
        yielded item (reference: Serve streaming HTTP responses)."""
        prefix = _norm_prefix(prefix)
        # handle variants are cheap facades over one shared per-
        # deployment RequestRouter, so building them here or per
        # request makes no routing difference; the stream one is
        # prebuilt simply because its mode is fixed per route
        stream_handle = handle.options(stream=True) if stream else None
        with self._rlock:
            self._routes[prefix] = (handle, stream_handle)

    def remove_route(self, prefix: str, handle=None) -> None:
        """Drop a route; with ``handle`` given, only if that handle
        still owns it (a later app may have claimed the prefix)."""
        prefix = _norm_prefix(prefix)
        with self._rlock:
            entry = self._routes.get(prefix)
            if handle is None or (entry is not None
                                  and entry[0] is handle):
                self._routes.pop(prefix, None)

    def routes(self) -> list[str]:
        with self._rlock:
            return sorted(self._routes)

    # -- request path --------------------------------------------------------
    def route(self, request) -> None:
        import ray_tpu
        parts = urlsplit(request.path)
        path = parts.path or "/"
        if path == "/-/routes":     # the reference's route listing
            self.reply(request, json.dumps(self.routes()).encode(),
                       "application/json")
            return
        matched = self._match(path)
        handle, stream_handle = matched if matched else (None, None)
        if handle is None:
            self.reply(request, json.dumps(
                {"error": "NotFound",
                 "message": f"no route matches {path!r}",
                 "routes": self.routes()}).encode(),
                "application/json", status=404)
            return
        try:
            n = int(request.headers.get("Content-Length") or 0)
        except ValueError:
            n = -1
        if n < 0:
            # malformed/negative Content-Length: read(-1) would buffer
            # the stream until EOF — refuse instead
            self.reply(request, json.dumps(
                {"error": "BadRequest",
                 "message": "missing or malformed Content-Length"}
                ).encode(), "application/json", status=400)
            return
        if n > self._max_body:
            # refuse before allocating: an oversized Content-Length must
            # not allocate in the ingress process before the handler runs
            self.reply(request, json.dumps(
                {"error": "PayloadTooLarge",
                 "message": f"body of {n} bytes exceeds the ingress "
                            f"limit of {self._max_body}"}).encode(),
                "application/json", status=413)
            return
        body = request.rfile.read(n) if n else b""
        req = HTTPRequest(method=request.command, path=path,
                          query=dict(parse_qsl(parts.query)), body=body)
        # deadline propagation: X-Request-Deadline carries the client's
        # remaining budget in seconds; the effective deadline (never
        # looser than the ingress timeout) rides into the router, which
        # drops the request BEFORE dispatch if it expires while queued
        timeout = self._timeout
        hdr = request.headers.get("X-Request-Deadline")
        if hdr is not None:
            try:
                timeout = min(timeout, float(hdr))
            except ValueError:
                self.reply(request, json.dumps(
                    {"error": "BadRequest",
                     "message": "malformed X-Request-Deadline header"}
                    ).encode(), "application/json", status=400)
                return
            if timeout <= 0:
                self._reply_deadline(request, "deadline already expired")
                return
        # sharded request plane: a session key (X-Session-Id header,
        # else the multiplexed model id header) consistent-hashes the
        # call onto one router shard — the in-process analogue of each
        # ingress replica owning a shard.  Sessionless requests spread
        # round-robin across shards.
        session = (request.headers.get("X-Session-Id")
                   or request.headers.get("serve_multiplexed_model_id")
                   or "")
        mux = request.headers.get("serve_multiplexed_model_id") or ""
        if session or mux:
            handle = handle.options(session_id=session,
                                    multiplexed_model_id=mux)
            if stream_handle is not None:
                stream_handle = stream_handle.options(
                    session_id=session, multiplexed_model_id=mux)
        if stream_handle is not None:
            try:
                gen = stream_handle.remote(req)
            except Exception as e:      # noqa: BLE001
                self._reply_error(request, e)
                return

            def chunks():
                for ref in gen:
                    item = ray_tpu.get(ref, timeout=timeout)
                    if isinstance(item, (bytes, bytearray)):
                        yield bytes(item)
                    elif isinstance(item, str):
                        yield item.encode()
                    else:       # JSON lines for structured items
                        yield json.dumps(item).encode() + b"\n"
            self.reply_stream(request, chunks(),
                              "application/octet-stream")
            return
        try:
            result = ray_tpu.get(
                handle.options(timeout_s=timeout).remote(req),
                timeout=timeout)
        except Exception as e:          # noqa: BLE001
            self._reply_error(request, e)
            return
        if isinstance(result, (bytes, bytearray)):
            self.reply(request, bytes(result), "application/octet-stream")
        elif isinstance(result, str):
            self.reply(request, result.encode(),
                       "text/plain; charset=utf-8")
        else:
            self.reply(request, json.dumps(result).encode(),
                       "application/json")

    # -- error mapping -------------------------------------------------------
    def _reply_error(self, request, exc: Exception) -> None:
        """Structured error responses: a shed request answers 503 with a
        Retry-After hint, a blown deadline answers 504, and a handler
        exception answers 500 — never a dropped connection."""
        from ..common.status import BackPressureError
        if isinstance(exc, BackPressureError):
            from ..common.config import get_config
            retry_after = max(get_config().serve_retry_after_s, 0.0)
            self.reply(request, json.dumps(
                {"error": "BackPressure", "message": str(exc)}).encode(),
                "application/json", status=503,
                headers={"Retry-After": f"{retry_after:g}"})
        elif isinstance(exc, TimeoutError):
            self._reply_deadline(request, str(exc))
        else:
            self.reply(request, json.dumps(
                {"error": type(exc).__name__,
                 "message": str(exc)}).encode(),
                "application/json", status=500)

    def _reply_deadline(self, request, message: str) -> None:
        self.reply(request, json.dumps(
            {"error": "DeadlineExceeded", "message": message}).encode(),
            "application/json", status=504)

    def _match(self, path: str):
        """Longest-prefix route match on path-segment boundaries;
        returns (handle, stream) or None."""
        with self._rlock:
            best = None
            for prefix, entry in self._routes.items():
                if path == prefix or prefix == "/" or \
                        path.startswith(prefix + "/"):
                    if best is None or len(prefix) > len(best[0]):
                        best = (prefix, entry)
            return best[1] if best else None


def _norm_prefix(prefix: str) -> str:
    if not prefix.startswith("/"):
        raise ValueError(f"route_prefix must start with '/': {prefix!r}")
    return prefix.rstrip("/") or "/"
