"""Deployments, replica pools, routing, autoscaling.

Reference parity: Serve's controller owns per-deployment replica sets
and reconciles them against target counts; ``DeploymentHandle`` routes
requests client-side with power-of-two-choices on observed in-flight
load (as upstream) and reports load; autoscaling moves replica counts
between
``min_replicas`` and ``max_replicas`` to hold
``target_ongoing_requests`` per replica (``python/ray/serve/`` —
SURVEY.md §1 layer 14; mount empty).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable


def _api():
    import ray_tpu
    return ray_tpu


# -- model multiplexing ------------------------------------------------------

_MUX_LOCK = threading.Lock()    # per-process: guards replica LRU caches
# created eagerly at import: a lazily-raced creation could hand two
# threads DIFFERENT vars and silently lose a request's model id
import contextvars as _contextvars  # noqa: E402

_mux_model_id = _contextvars.ContextVar("serve_mux_model", default="")


def _mux_var():
    return _mux_model_id


def get_multiplexed_model_id() -> str:
    """Inside a replica method: the model id the current request was
    routed with (reference: ``serve.get_multiplexed_model_id``)."""
    return _mux_var().get()


def multiplexed(fn=None, *, max_num_models_per_replica: int = 3):
    """Decorate a replica's model-loader method: results cache per
    replica in an LRU bounded at ``max_num_models_per_replica``
    (reference: ``@serve.multiplexed`` model multiplexing — one
    replica set serves MANY models, each loaded on demand and evicted
    least-recently-used).  Pair with
    ``handle.options(multiplexed_model_id=...)``, which routes every
    call for one model id to the same replica (rendezvous hashing) so
    its cache stays hot."""
    import functools
    cap = max(int(max_num_models_per_replica), 1)

    def deco(loader):
        # the cache lives ON the instance and the lock is a module
        # global: the deployment target class must stay picklable, so
        # the closure may capture only plain values
        cache_attr = f"_serve_mux_cache_{loader.__name__}"

        pending_attr = f"_serve_mux_pending_{loader.__name__}"

        @functools.wraps(loader)
        def wrapper(self, model_id: str):
            import threading as _threading
            from collections import OrderedDict

            # late import: a module-global referenced directly would be
            # captured BY VALUE when cloudpickle ships the enclosing
            # user class, and locks don't pickle
            from ray_tpu.serve.deployment import _MUX_LOCK
            while True:
                with _MUX_LOCK:
                    cache = getattr(self, cache_attr, None)
                    if cache is None:
                        cache = OrderedDict()
                        setattr(self, cache_attr, cache)
                    if model_id in cache:
                        cache.move_to_end(model_id)
                        return cache[model_id]
                    pending = getattr(self, pending_attr, None)
                    if pending is None:
                        pending = {}
                        setattr(self, pending_attr, pending)
                    ev = pending.get(model_id)
                    if ev is None:
                        # we lead the load; concurrent cold requests
                        # for the same model WAIT instead of each
                        # running the expensive loader
                        pending[model_id] = _threading.Event()
                        break
                ev.wait(timeout=600.0)
                # leader finished (or failed): re-check the cache; a
                # failed leader leaves it absent and a follower leads
            try:
                model = loader(self, model_id)  # load OUTSIDE the lock
                with _MUX_LOCK:
                    cache[model_id] = model
                    cache.move_to_end(model_id)
                    while len(cache) > cap:
                        cache.popitem(last=False)   # evict LRU
                return model
            finally:
                with _MUX_LOCK:
                    ev2 = pending.pop(model_id, None)
                if ev2 is not None:
                    ev2.set()
        wrapper._serve_multiplexed = True
        return wrapper
    return deco if fn is None else deco(fn)


# -- replica shell -----------------------------------------------------------

class _ReplicaShell:
    """Hosts one user replica object and settles its load accounting.

    The GCS KV inflight counter is incremented by the ``RequestRouter``
    at dispatch (so submitted-but-unfinished calls count toward
    autoscaling) and decremented HERE when execution completes.
    Replicas run as threaded actors (``max_concurrency`` = the
    deployment's ``max_ongoing_requests``), so a slow request does not
    head-of-line-block the others — the worker's reader-thread frame
    routing makes the shared pipe safe for concurrent calls.

    The shell also publishes a per-call context for ``@serve.batch``
    wrappers on the user object: the deployment's KV key base (batch
    histograms aggregate cluster-wide) and the replica's LIVE call
    count, which lets a batch leader cut its window early once every
    in-flight call has joined the batch.
    """

    def __init__(self, target_bytes: bytes, init_args: bytes,
                 kv_key: str):
        from ray_tpu.runtime.serialization import deserialize
        target = deserialize(target_bytes)
        args, kwargs = deserialize(init_args)
        self._obj = target(*args, **kwargs)
        self._kv_key = kv_key.encode()
        self._kv_base = kv_key.split("-", 1)[1] if "-" in kv_key \
            else kv_key
        self._active = 0
        self._active_lock = threading.Lock()
        self._model_version = "v1"

    def _active_count(self) -> int:
        with self._active_lock:
            return self._active

    def _reload(self, artifact, version: str) -> dict:
        """Hot-swap step on a DRAINED replica (the rollout controller
        pulls it out of routing first): hand the new weights to the
        user object's ``reload(artifact)`` if it defines one, re-tag
        the model version, and run the verification probe
        (``__check_health__`` when defined).  ``artifact`` arrives as
        the broadcast-staged value (ObjectRef args resolve before the
        call, so the bytes come off the replica-local copy the tree
        delivered); ``None`` re-tags only (rollback with no retained
        artifact)."""
        ok = True
        if artifact is not None and hasattr(self._obj, "reload"):
            try:
                self._obj.reload(artifact)
            except Exception:   # noqa: BLE001 — a throwing reload is a
                ok = False      # failed probe, not a dead replica
        self._model_version = version
        if ok and hasattr(self._obj, "__check_health__"):
            try:
                ok = bool(self._obj.__check_health__())
            except Exception:   # noqa: BLE001 — same contract
                ok = False
        return {"ok": ok, "version": version,
                "active": self._active_count()}

    def __serve_call__(self, method: str, args: tuple, kwargs: dict,
                       model_id: str = ""):
        import inspect

        from ray_tpu.experimental.internal_kv import _internal_kv_incr

        from .batching import _shell_ctx

        def settle():
            _internal_kv_incr(self._kv_key, -1, namespace="serve")
        with self._active_lock:
            self._active += 1
        shell_token = _shell_ctx.set(
            {"kv_base": self._kv_base, "active": self._active_count})
        token = _mux_var().set(model_id) if model_id else None
        try:
            out = getattr(self._obj, method)(*args, **kwargs)
        except BaseException:
            settle()
            raise
        finally:
            if token is not None:
                _mux_var().reset(token)
            _shell_ctx.reset(shell_token)
            with self._active_lock:
                self._active -= 1
        if inspect.isgenerator(out):
            # a STREAMING response stays in the inflight count until
            # the stream finishes — calling the generator function
            # returns instantly, and settling then would leave the
            # autoscaler blind to long-running streams.  The model-id
            # var re-wraps EVERY advance: the body only executes at
            # next(), long after the outer finally reset the token,
            # and a token left set across a yield would bleed into
            # interleaved calls on the same thread
            def stream():
                try:
                    while True:
                        tok = _mux_var().set(model_id) if model_id \
                            else None
                        try:
                            item = next(out)
                        except StopIteration:
                            return
                        finally:
                            if tok is not None:
                                _mux_var().reset(tok)
                        yield item
                finally:
                    settle()
            return stream()
        settle()
        return out


# -- controller actor --------------------------------------------------------

class _Controller:
    """Owns one deployment's replica set (actor handles) and scales it.

    Runs as a dedicated actor so handles living in tasks/other actors
    can fetch the current replica list; scaling decisions read the KV
    inflight counter on ``tick`` (handles fire one per request).
    """

    def __init__(self, cls_or_fn_bytes: bytes, init_args: bytes,
                 num_replicas: int, autoscaling: dict | None,
                 actor_options: dict, max_ongoing_requests: int = 4,
                 max_queued_requests: int = 200, name: str = ""):
        import os
        self._target_bytes = cls_or_fn_bytes
        self._init_args_bytes = init_args
        self._autoscaling = autoscaling
        self._actor_options = dict(actor_options)
        self._max_ongoing = max(int(max_ongoing_requests), 1)
        self._max_queued = max(int(max_queued_requests), 0)
        self._name = name
        self._kv_base = os.urandom(6).hex()
        self._kv_key = f"inflight-{self._kv_base}"
        self._replicas: list = []
        self._loaners: list = []    # replicas on LOANED batch nodes
        self._retiring: list = []   # loaners draining for reclaim
        self._releasing: list = []  # replicas draining for a reverse lend
        self._flipping: list = []   # replicas out of routing mid-flip
        self._version = 0
        self._model_version = "v1"  # the deployment's SERVING version
        self._replica_versions: dict[str, str] = {}  # actor hex -> ver
        self._rollout_active = False
        self._last_scale = time.monotonic()
        if autoscaling:
            n = autoscaling.get("min_replicas", 1)
        else:
            n = max(num_replicas, 1)
        for _ in range(n):
            self._start_replica()

    def _start_replica(self) -> None:
        import ray_tpu
        actor_cls = ray_tpu.remote(_ReplicaShell)
        opts = dict(self._actor_options)
        # replicas handle requests CONCURRENTLY (threaded actor up to
        # max_ongoing_requests — upstream replicas do the same on their
        # event loop)
        opts.setdefault("max_concurrency", self._max_ongoing)
        stub = actor_cls.options(**opts) if opts else actor_cls
        handle = stub.remote(self._target_bytes, self._init_args_bytes,
                             self._kv_key)
        self._replicas.append(handle)
        self._replica_versions[handle._actor_id.binary().hex()] = \
            self._model_version
        self._version += 1

    def _stop_replica(self) -> None:
        import ray_tpu
        handle = self._replicas.pop()
        self._replica_versions.pop(handle._actor_id.binary().hex(),
                                   None)
        self._version += 1
        ray_tpu.kill(handle)

    # -- handle-facing -------------------------------------------------------
    def get_replicas(self):
        auto = self._autoscaling
        hi = auto.get("max_replicas", 4) if auto else \
            len(self._replicas)
        return (self._version, list(self._replicas) + list(self._loaners),
                self._kv_key, {
                    "max_ongoing": self._max_ongoing,
                    "max_queued": self._max_queued,
                    "name": self._name,
                    "base": self._kv_base,
                    # the loan manager's "pool exhausted" signal: the
                    # regular pool cannot grow past its configured cap
                    "at_max": len(self._replicas) >= hi,
                    "loaners": len(self._loaners),
                    "releasing": len(self._releasing),
                    # model-version plane: per-replica version tags so
                    # routers can pin sessions to a consistent version
                    # while a rollout is mid-flight
                    "model_version": self._model_version,
                    "replica_versions": dict(self._replica_versions),
                    "rollout_active": self._rollout_active,
                })

    # -- elastic capacity loaning (driver LoanManager calls these) -----------
    def add_loaner(self, actor_options: dict):
        """Start one replica on a LOANED batch node: the options carry
        the loan-shaped resource (``serve_loaned``) that only loaned
        CRM rows expose, so placement lands there and nowhere else.
        Returns the replica handle — the loan record keeps it for the
        targeted reclaim drain."""
        import ray_tpu
        actor_cls = ray_tpu.remote(_ReplicaShell)
        opts = dict(self._actor_options)
        opts.update(actor_options)
        opts.setdefault("max_concurrency", self._max_ongoing)
        handle = actor_cls.options(**opts).remote(
            self._target_bytes, self._init_args_bytes, self._kv_key)
        self._loaners.append(handle)
        self._replica_versions[handle._actor_id.binary().hex()] = \
            self._model_version
        self._version += 1
        return handle

    def begin_retire_loaner(self, key_hex: str = ""):
        """Reclaim step 1: pull one loaner out of the routing set
        (version bump -> shards stop dispatching to it) but keep it
        alive to finish in-flight work.  ``key_hex`` targets a specific
        replica (node death); empty retires the newest loan (LIFO)."""
        pick = None
        if key_hex:
            for h in self._loaners:
                if h._actor_id.binary().hex() == key_hex:
                    pick = h
                    break
        elif self._loaners:
            pick = self._loaners[-1]
        if pick is None:
            return None
        self._loaners.remove(pick)
        self._retiring.append(pick)
        self._version += 1
        return pick

    def finish_retire_loaner(self, key_hex: str) -> bool:
        """Reclaim step 2: the drain converged (or timed out, or the
        node died) — kill the retiring replica and forget it."""
        import ray_tpu
        for h in list(self._retiring):
            if h._actor_id.binary().hex() == key_hex:
                self._retiring.remove(h)
                try:
                    ray_tpu.kill(h)
                except Exception:   # noqa: BLE001 — already dead
                    pass
                return True
        return False

    # -- reverse lending (batch/train borrows a serve node) ------------------
    def begin_release_replica(self):
        """Reverse-lend step 1: lend one regular replica's node to
        batch/train — pull the newest replica out of routing (version
        bump -> shards stop dispatching) but keep it alive to finish
        in-flight work; the loan manager kills it once idle via
        ``finish_release_replica``, freeing the node for batch
        placement.  Refuses to shrink below the autoscaling floor."""
        auto = self._autoscaling
        lo = max(auto.get("min_replicas", 1) if auto else 1, 1)
        if len(self._replicas) <= lo:
            return None
        pick = self._replicas[-1]               # LIFO, like loan reclaim
        self._replicas.remove(pick)
        self._releasing.append(pick)
        self._version += 1
        return pick

    def finish_release_replica(self, key_hex: str) -> bool:
        """Reverse-lend step 2: the drain converged (or the node died)
        — kill the released replica; its resources return to the CRM
        and batch placement can use the whole node."""
        import ray_tpu
        for h in list(self._releasing):
            if h._actor_id.binary().hex() == key_hex:
                self._releasing.remove(h)
                self._replica_versions.pop(key_hex, None)
                try:
                    ray_tpu.kill(h)
                except Exception:   # noqa: BLE001 — already dead
                    pass
                self._version += 1
                return True
        return False

    def restore_replica(self) -> None:
        """Reverse-lend epilogue: the lend ended (serve pressure came
        back, or the lent node died) — start a fresh replica to take
        the lent one's place in the pool."""
        self._start_replica()

    # -- model-version plane (versioning/rollout.py calls these) -------------
    def begin_flip(self, key_hex: str) -> bool:
        """Flip step 1: pull the replica out of the routing set
        (version bump -> shards stop dispatching to it) but keep it
        alive to drain its in-flight requests — the retire-loaner
        two-step, applied to a regular replica for a weight swap."""
        for h in self._replicas:
            if h._actor_id.binary().hex() == key_hex:
                self._replicas.remove(h)
                self._flipping.append(h)
                self._version += 1
                return True
        return False

    def commit_flip(self, key_hex: str, model_version: str) -> bool:
        """Flip step 2 (success): the drained replica reloaded and
        probed healthy — re-enter routing under the new version tag."""
        for h in list(self._flipping):
            if h._actor_id.binary().hex() == key_hex:
                self._flipping.remove(h)
                self._replicas.append(h)
                self._replica_versions[key_hex] = model_version
                self._version += 1
                return True
        return False

    def cancel_flip(self, key_hex: str, dead: bool = False) -> bool:
        """Flip step 2 (failure): probe failed (back into routing on
        the OLD version, untouched) or the replica died mid-flip
        (dropped from the set entirely)."""
        import ray_tpu
        for h in list(self._flipping):
            if h._actor_id.binary().hex() == key_hex:
                self._flipping.remove(h)
                if dead:
                    self._replica_versions.pop(key_hex, None)
                    try:
                        ray_tpu.kill(h)
                    except Exception:   # noqa: BLE001 — already dead
                        pass
                else:
                    self._replicas.append(h)
                self._version += 1
                return True
        return False

    def flipping_handles(self) -> list:
        return list(self._flipping)

    def set_model_version(self, model_version: str) -> None:
        """Seal: new replicas (scale-up, loaners) now start on this
        version."""
        self._model_version = model_version

    def model_version(self) -> str:
        return self._model_version

    def set_rollout_active(self, active: bool) -> None:
        """Routers pin sessions to per-replica version tags only while
        a rollout is actually mid-flight (the pin table costs a dict
        lookup per pick)."""
        self._rollout_active = bool(active)
        self._version += 1

    def version_counts(self) -> dict:
        out: dict[str, int] = {}
        for v in self._replica_versions.values():
            out[v] = out.get(v, 0) + 1
        return out

    def ensure_replica(self):
        """Cold start for scale-to-zero: a request arrived while no
        replica exists."""
        if not self._replicas:
            self._start_replica()
        return self._version

    def tick(self):
        """Autoscaling check (fired by handles; fire-and-forget)."""
        self._maybe_scale()
        return None

    def _signals(self) -> tuple[int, int, float]:
        """The router-maintained load signals for this deployment:
        (dispatched-but-unfinished, queued awaiting a free slot,
        request-latency EWMA in ms)."""
        from ray_tpu.experimental.internal_kv import (_internal_kv_get,
                                                      _internal_kv_incr)
        inflight = _internal_kv_incr(self._kv_key.encode(), 0,
                                     namespace="serve")
        queued = _internal_kv_incr(f"queued-{self._kv_base}".encode(),
                                   0, namespace="serve")
        raw = _internal_kv_get(f"lat-{self._kv_base}".encode(),
                               namespace="serve")
        try:
            lat_ms = float(raw) if raw else 0.0
        except ValueError:
            lat_ms = 0.0
        return inflight, queued, lat_ms

    def _maybe_scale(self) -> None:
        auto = self._autoscaling
        if not auto:
            return
        now = time.monotonic()
        if now - self._last_scale < auto.get("upscale_delay_s", 0.1):
            return
        target = max(auto.get("target_ongoing_requests", 2), 1)
        lo = auto.get("min_replicas", 1)
        hi = auto.get("max_replicas", 4)
        inflight, queued, lat_ms = self._signals()
        # demand = executing + queued: a bounded router queue means
        # raw inflight alone UNDERCOUNTS pressure (requests the router
        # is holding back never show up in the replica counter)
        demand = inflight + queued
        want = max(lo, min(hi, -(-demand // target)))
        target_lat = auto.get("target_latency_ms", 0.0)
        if target_lat and lat_ms > target_lat \
                and want <= len(self._replicas) < hi:
            # latency-EWMA escape hatch: per-replica load looks on
            # target but requests are SLOW — add capacity anyway
            want = len(self._replicas) + 1
        if want > len(self._replicas):
            while len(self._replicas) < want:
                self._start_replica()
            self._last_scale = now
        elif want < len(self._replicas) and queued == 0 and \
                now - self._last_scale > auto.get("downscale_delay_s",
                                                  1.0):
            # never downscale with a backlog: the queue would re-pack
            # the survivors and immediately re-trigger an upscale
            while len(self._replicas) > want:
                self._stop_replica()
            self._last_scale = now

    def num_replicas(self) -> int:
        return len(self._replicas) + len(self._loaners)

    def stats(self) -> dict:
        """Controller-side view of the request-plane load signals
        (``serve.status`` merges this with the driver router's
        counters)."""
        inflight, queued, lat_ms = self._signals()
        return {"deployment": self._name,
                "replicas": len(self._replicas),
                "loaners": len(self._loaners),
                "inflight": inflight, "queued": queued,
                "latency_ewma_ms": lat_ms,
                "model_version": self._model_version,
                "version_counts": self.version_counts(),
                "rollout_active": self._rollout_active}

    def shutdown(self) -> None:
        import ray_tpu
        for h in list(self._replicas) + list(self._loaners) + \
                list(self._retiring) + list(self._flipping):
            ray_tpu.kill(h)
        self._replicas.clear()
        self._loaners.clear()
        self._retiring.clear()
        self._flipping.clear()
        self._replica_versions.clear()
        # the deployment's KV counters (inflight/queued/lat/batch*) are
        # keyed by a per-controller random base: delete them, or every
        # run/delete cycle leaks namespace entries forever
        from ray_tpu.experimental.internal_kv import (_internal_kv_del,
                                                      _internal_kv_list)
        try:
            suffix = self._kv_base.encode()
            for key in _internal_kv_list(b"", namespace="serve"):
                if key.endswith(suffix):
                    _internal_kv_del(key, namespace="serve")
        except Exception:   # noqa: BLE001 — cleanup is best-effort
            pass


# -- handle ------------------------------------------------------------------

class DeploymentHandle:
    """Facade over the deployment's ``RequestRouter``: ``.remote()``
    submits through the shared per-controller router, which enforces
    the per-replica in-flight cap, the bounded queue, and deadline
    propagation (see ``serve/router.py``).  Every handle variant
    produced by ``options()`` routes through the SAME router, so the
    load view and the admission bound stay coherent across callers.

    Serializable (carries only the controller's actor handle plus the
    call options), so deployments compose: pass one deployment's handle
    to another's ``bind``.
    """

    def __init__(self, controller_handle, method: str = "__call__",
                 stream: bool = False, multiplexed_model_id: str = "",
                 timeout_s: float | None = None, session_id: str = ""):
        self._controller = controller_handle
        self._method = method
        self._stream = stream
        self._mux_id = multiplexed_model_id
        self._timeout_s = timeout_s
        self._session_id = session_id

    def options(self, *, method_name: str | None = None,
                stream: bool | None = None,
                multiplexed_model_id: str | None = None,
                timeout_s: float | None = None,
                session_id: str | None = None) -> "DeploymentHandle":
        """``stream=True``: calls return an ObjectRefGenerator — the
        replica method must be a generator; items stream back with
        backpressure (reference: handle.options(stream=True)).
        ``multiplexed_model_id``: route every call for this model to
        the same replica (rendezvous hashing) so its ``@multiplexed``
        LRU cache stays hot.  ``timeout_s``: per-request deadline —
        a request still queued in the router when it expires is
        DROPPED before dispatch and its ref raises
        ``GetTimeoutError``.  ``session_id``: consistent-hash the call
        onto one router shard (the per-ingress sharded request plane —
        a multiplexed model id implies its own session key)."""
        return DeploymentHandle(
            self._controller,
            method_name if method_name is not None else self._method,
            stream if stream is not None else self._stream,
            multiplexed_model_id if multiplexed_model_id is not None
            else self._mux_id,
            timeout_s if timeout_s is not None else self._timeout_s,
            session_id if session_id is not None else self._session_id)

    def remote(self, *args, **kwargs):
        from .router import RouterGroup
        return RouterGroup.for_controller(self._controller).submit(
            self._method, args, kwargs, self._mux_id, self._stream,
            self._timeout_s, session=self._session_id)

    def __reduce__(self):
        return (DeploymentHandle,
                (self._controller, self._method, self._stream,
                 self._mux_id, self._timeout_s, self._session_id))


# -- deployment / application ------------------------------------------------

@dataclass
class Application:
    """A bound deployment node.  ``bind`` composes DECLARATIVELY:
    passing one deployment's ``bind()`` result as an argument to
    another's makes a deployment GRAPH — ``serve.run`` materializes
    the whole DAG depth-first, replacing each nested node with its
    live ``DeploymentHandle`` (reference: Serve's ``bind`` DAG API,
    ``python/ray/serve/``, SURVEY.md §1 layer 14; mount empty).
    A node shared by several parents (diamond fan-in) materializes
    once and its replicas are shared."""

    deployment: "Deployment"
    args: tuple
    kwargs: dict


class Deployment:
    def __init__(self, target: type | Callable, name: str,
                 num_replicas: int = 1,
                 autoscaling_config: dict | None = None,
                 ray_actor_options: dict | None = None,
                 max_ongoing_requests: int = 4,
                 max_queued_requests: int | None = None):
        self._target = target
        self.name = name
        self._num_replicas = num_replicas
        self._autoscaling = autoscaling_config
        self._actor_options = dict(ray_actor_options or {})
        self._max_ongoing = max_ongoing_requests
        # None => the serve_max_queued_requests config default,
        # resolved in the DRIVER at run() time (workers may not share
        # the driver's system_config overrides)
        self._max_queued = max_queued_requests

    def options(self, *, num_replicas: int | None = None,
                autoscaling_config: dict | None = None,
                ray_actor_options: dict | None = None,
                name: str | None = None,
                max_ongoing_requests: int | None = None,
                max_queued_requests: int | None = None) -> "Deployment":
        return Deployment(
            self._target, name or self.name,
            num_replicas if num_replicas is not None
            else self._num_replicas,
            autoscaling_config if autoscaling_config is not None
            else self._autoscaling,
            ray_actor_options if ray_actor_options is not None
            else self._actor_options,
            max_ongoing_requests if max_ongoing_requests is not None
            else self._max_ongoing,
            max_queued_requests if max_queued_requests is not None
            else self._max_queued)

    def bind(self, *args, **kwargs) -> Application:
        return Application(self, args, kwargs)


def deployment(target: type | Callable | None = None, *,
               name: str | None = None, num_replicas: int = 1,
               autoscaling_config: dict | None = None,
               ray_actor_options: dict | None = None,
               max_ongoing_requests: int = 4,
               max_queued_requests: int | None = None):
    """``@serve.deployment`` (bare or parameterized)."""
    def make(t):
        tgt = t if isinstance(t, type) else _wrap_function(t)
        return Deployment(tgt, name or t.__name__, num_replicas,
                          autoscaling_config, ray_actor_options,
                          max_ongoing_requests, max_queued_requests)
    if target is not None:
        return make(target)
    return make


def _wrap_function(fn: Callable) -> type:
    import inspect

    class _FnReplica:
        # function deployments that are generators stream over HTTP too
        _serve_http_stream = inspect.isgeneratorfunction(fn)

        def __call__(self, *args, **kwargs):
            return fn(*args, **kwargs)
    _FnReplica.__name__ = getattr(fn, "__name__", "fn_replica")
    return _FnReplica


# -- run / delete / status ---------------------------------------------------

@dataclass
class _Running:
    controller: Any
    handle: DeploymentHandle
    deployment: Deployment = None
    route_prefix: str | None = None
    # child controllers of a deployment graph (teardown order: root
    # first — it is the only one the ingress/user routes into)
    child_controllers: list = field(default_factory=list)


_apps: dict[str, _Running] = {}
_apps_lock = threading.Lock()
_ingress = None
_ingress_lock = threading.Lock()


def start(http_host: str = "127.0.0.1", http_port: int = 0,
          request_timeout_s: float = 30.0,
          max_body_bytes: int = 64 * 1024 * 1024) -> str:
    """Start the HTTP ingress (idempotent); returns its address.
    ``http_port=0`` binds an ephemeral port — pass 8000 for the
    reference's fixed default."""
    return _ensure_ingress(http_host, http_port,
                           request_timeout_s, max_body_bytes).address


def _ensure_ingress(http_host: str = "127.0.0.1", http_port: int = 0,
                    request_timeout_s: float = 30.0,
                    max_body_bytes: int = 64 * 1024 * 1024):
    global _ingress
    from .http_proxy import HttpIngress
    with _ingress_lock:
        if _ingress is None:
            _ingress = HttpIngress(http_host, http_port,
                                   request_timeout_s, max_body_bytes)
        return _ingress


def _ingress_if_running():
    with _ingress_lock:
        return _ingress


def http_address() -> str | None:
    with _ingress_lock:
        return _ingress.address if _ingress is not None else None


def _substitute_bound(value, build):
    """Replace Application nodes with live handles inside an argument,
    one container level deep (lists/tuples/dicts of bound nodes are
    common graph shapes)."""
    if isinstance(value, Application):
        return build(value)
    if isinstance(value, (list, tuple)):
        out = [build(v) if isinstance(v, Application) else v
               for v in value]
        return type(value)(out)
    if isinstance(value, dict):
        return {k: build(v) if isinstance(v, Application) else v
                for k, v in value.items()}
    return value


def run(app: Application, *, name: str = "default",
        route_prefix: str | None = None) -> DeploymentHandle:
    import ray_tpu
    from ray_tpu.runtime.serialization import serialize
    if route_prefix is not None:
        # validate BEFORE materializing actors: a bad prefix must not
        # leak a live replica set nothing can reach or tear down
        from .http_proxy import _norm_prefix
        route_prefix = _norm_prefix(route_prefix)
    # materialize the bound DAG depth-first: nested Application args
    # become live DeploymentHandles (shared nodes materialize once)
    materialized: dict[int, DeploymentHandle] = {}
    building: set[int] = set()
    controllers: list = []

    def build(a: Application) -> DeploymentHandle:
        got = materialized.get(id(a))
        if got is not None:
            return got
        if id(a) in building:
            raise ValueError(
                f"deployment graph cycle through {a.deployment.name!r}")
        building.add(id(a))
        d = a.deployment
        b_args = tuple(_substitute_bound(x, build) for x in a.args)
        b_kwargs = {k: _substitute_bound(v, build)
                    for k, v in a.kwargs.items()}
        from ray_tpu.common.config import get_config
        max_queued = d._max_queued if d._max_queued is not None \
            else get_config().serve_max_queued_requests
        controller_cls = ray_tpu.remote(_Controller)
        ctl = controller_cls.remote(
            serialize(d._target), serialize((b_args, b_kwargs)),
            d._num_replicas, d._autoscaling, d._actor_options,
            d._max_ongoing, max_queued, d.name)
        # materialize the replica set before handing the handle out
        ray_tpu.get(ctl.num_replicas.remote(), timeout=60)
        h = DeploymentHandle(ctl)
        building.discard(id(a))
        materialized[id(a)] = h
        controllers.append(ctl)
        return h

    dep = app.deployment
    try:
        handle = build(app)
    except BaseException:
        # a mid-build failure (cycle, replica init hang/raise) must not
        # leak the child controllers already materialized — nothing
        # else would ever reference them
        for ctl in reversed(controllers):
            try:
                ray_tpu.get(ctl.shutdown.remote(), timeout=30)
                ray_tpu.kill(ctl)
            except Exception:   # noqa: BLE001 — best-effort teardown
                pass
        raise
    controller = controllers.pop()      # the root's (built last)
    if route_prefix is not None:
        # a generator __call__ makes the HTTP route STREAMING: chunked
        # transfer of each yielded item (reference streaming responses)
        import inspect
        http_stream = (
            inspect.isgeneratorfunction(
                getattr(dep._target, "__call__", None))
            or getattr(dep._target, "_serve_http_stream", False))
        _ensure_ingress().add_route(route_prefix, handle,
                                    stream=http_stream)
    with _apps_lock:
        old = _apps.pop(name, None)
        _apps[name] = _Running(controller, handle, dep, route_prefix,
                               controllers)
    if old is not None:
        ingress = _ingress_if_running()
        if old.route_prefix is not None and ingress is not None:
            # ownership-checked: only drops the route if the OLD handle
            # still holds it (same-prefix re-run already swapped it)
            ingress.remove_route(old.route_prefix, old.handle)
        _teardown(old)
    return handle


def get_deployment_handle(name: str = "default") -> DeploymentHandle:
    with _apps_lock:
        running = _apps.get(name)
    if running is None:
        raise KeyError(f"no running serve app {name!r}")
    return running.handle


def status(name: str = "default") -> dict:
    import ray_tpu
    with _apps_lock:
        running = _apps.get(name)
    if running is None:
        return {"status": "NOT_RUNNING"}
    n = ray_tpu.get(running.controller.num_replicas.remote(),
                    timeout=30)
    out = {"status": "RUNNING",
           "deployment": running.deployment.name,
           "num_replicas": n}
    try:
        plane = ray_tpu.get(running.controller.stats.remote(),
                            timeout=30)
        from .router import RequestRouter
        plane.update(
            RequestRouter.for_controller(running.controller)
            .snapshot())
        out["request_plane"] = plane
    except Exception:   # noqa: BLE001 — status must answer regardless
        pass
    return out


def _teardown(running: _Running) -> None:
    import ray_tpu

    from .router import RequestRouter
    # root first (nothing routes into the children once it is gone),
    # then the graph's children; each router is discarded BEFORE its
    # controller dies so queued requests poison cleanly instead of
    # dispatching into a dead replica set
    for ctl in [running.controller] + \
            list(reversed(running.child_controllers)):
        try:
            RequestRouter.discard(ctl)
        except Exception:   # noqa: BLE001
            pass
        try:
            ray_tpu.get(ctl.shutdown.remote(), timeout=30)
            ray_tpu.kill(ctl)
        except Exception:   # noqa: BLE001 — already dead
            pass


def delete(name: str = "default") -> None:
    with _apps_lock:
        running = _apps.pop(name, None)
    if running is not None:
        ingress = _ingress_if_running()
        if running.route_prefix is not None and ingress is not None:
            ingress.remove_route(running.route_prefix, running.handle)
        _teardown(running)


def shutdown() -> None:
    """Tear down every app and the HTTP ingress (reference:
    ``serve.shutdown()``)."""
    global _ingress
    with _apps_lock:
        names = list(_apps)
    for n in names:
        delete(n)
    with _ingress_lock:
        if _ingress is not None:
            _ingress.shutdown()
            _ingress = None
