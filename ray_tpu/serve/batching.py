"""Dynamic request micro-batching inside serve replicas.

Reference parity: ``@serve.batch(max_batch_size, batch_wait_timeout_s)``
(``python/ray/serve/batching.py``) turns a method taking ONE item into a
method taking a LIST of items: concurrent calls coalesce into a batch,
the handler runs once per batch, and each caller gets its own element of
the result list (SURVEY.md §1 layer 14; mount empty).

Accelerator inference lives on batch occupancy, so the batcher must
neither starve (ship singletons while peers are in flight) nor stall
(hold a full window when no more callers can possibly arrive).  The
policy here:

- a batch ships when it reaches ``max_batch_size``,
- or when ``batch_wait_timeout_s`` expires,
- or EARLY, when every request currently executing on the replica has
  already joined the batch — the replica shell publishes its live call
  count (``_shell_ctx``), so the batch leader knows nobody else can
  join and waiting out the timeout would be pure added latency.  The
  router's per-replica in-flight cap makes this signal tight: at most
  ``max_ongoing_requests`` calls are ever in flight.

Mechanics: callers append to a shared pending list; the first becomes
the batch LEADER, collects the window, runs the user function once
OUTSIDE the lock, and distributes results.  Leadership releases at
extraction, so the next batch collects while the current one executes
(replicas are threaded actors).  A caller left behind by a full batch
promotes itself to leader of the remainder.

Every executed batch records its size into a process-local histogram
(``util.metrics``) and into GCS KV bucket counters keyed by the
deployment (``_shell_ctx``), which the driver-side metrics/status
surfaces aggregate across replicas.
"""

from __future__ import annotations

import contextvars
import threading
import time

# Set by _ReplicaShell around every __serve_call__: lets the batcher
# find the deployment's KV key (cross-process histogram) and the
# replica's live request count (early batch cut).  Created eagerly at
# import — a lazily-raced creation could hand threads DIFFERENT vars.
_shell_ctx: contextvars.ContextVar[dict | None] = contextvars.ContextVar(
    "serve_shell_ctx", default=None)

# Batch-size histogram buckets; each batch lands in exactly ONE bucket
# (first `size <= le`); readers cumsum for Prometheus `le` semantics.
BATCH_BUCKETS = (1, 2, 4, 8, 16, 32, 64)

_hist_lock = threading.Lock()
_hist = None


def _record_batch(batch_size: int) -> None:
    global _hist
    with _hist_lock:
        if _hist is None:
            from ray_tpu.util.metrics import Histogram
            _hist = Histogram(
                "serve_batch_size",
                "Executed micro-batch sizes in this replica process.",
                boundaries=list(BATCH_BUCKETS))
        _hist.observe(batch_size)
    ctx = _shell_ctx.get()
    base = ctx.get("kv_base") if ctx else None
    if not base:
        return
    try:
        from ray_tpu.experimental.internal_kv import _internal_kv_incr
        _internal_kv_incr(f"batchcnt-{base}".encode(), 1,
                          namespace="serve")
        _internal_kv_incr(f"batchsum-{base}".encode(), batch_size,
                          namespace="serve")
        for le in BATCH_BUCKETS:
            if batch_size <= le:
                bucket = str(le)
                break
        else:
            bucket = "inf"
        _internal_kv_incr(f"batchb-{bucket}-{base}".encode(), 1,
                          namespace="serve")
    except Exception:   # noqa: BLE001 — stats must never fail a batch
        pass


def _active_calls() -> int | None:
    """Live __serve_call__ count on this replica, or None outside one."""
    ctx = _shell_ctx.get()
    if not ctx:
        return None
    getter = ctx.get("active")
    return getter() if getter is not None else None


class _Entry:
    __slots__ = ("value", "result", "error", "done")

    def __init__(self, value):
        self.value = value
        self.result = None
        self.error = None
        self.done = False


class _BatchQueue:
    __slots__ = ("cv", "pending", "leading")

    def __init__(self):
        self.cv = threading.Condition()
        self.pending: list[_Entry] = []
        self.leading = False


# Free-function wrappers keep their per-process queue here (keyed by the
# wrapper object itself — cloudpickle re-creates one per process, which
# is exactly the scope a queue must have).  Method wrappers store the
# queue ON the instance, like @multiplexed's cache.
_FREE_LOCK = threading.Lock()
_FREE_QUEUES: dict[int, _BatchQueue] = {}


def _queue_on_instance(obj, attr: str) -> _BatchQueue:
    from ray_tpu.serve.batching import _FREE_LOCK
    q = getattr(obj, attr, None)
    if q is None:
        with _FREE_LOCK:
            q = getattr(obj, attr, None)
            if q is None:
                q = _BatchQueue()
                setattr(obj, attr, q)
    return q


def _free_queue(key: int) -> _BatchQueue:
    from ray_tpu.serve.batching import _FREE_LOCK, _FREE_QUEUES
    with _FREE_LOCK:
        q = _FREE_QUEUES.get(key)
        if q is None:
            q = _FREE_QUEUES[key] = _BatchQueue()
        return q


def batch(fn=None, *, max_batch_size: int = 8,
          batch_wait_timeout_s: float = 0.01):
    """``@serve.batch`` — coalesce concurrent single-item calls into one
    list-in/list-out invocation of the wrapped function.

    The wrapped function must take exactly one positional argument (plus
    ``self`` for methods) and, when batched, receives a LIST of those
    arguments; it must return a list of equal length.  A returned
    element that is an ``Exception`` instance is raised for that caller
    alone.
    """
    import functools
    import inspect
    size_cap = max(int(max_batch_size), 1)
    wait_s = max(float(batch_wait_timeout_s), 0.0)

    def deco(handler):
        params = list(inspect.signature(handler).parameters)
        is_method = bool(params) and params[0] == "self"
        queue_attr = f"_serve_batch_q_{handler.__name__}"

        @functools.wraps(handler)
        def wrapper(*args, **kwargs):
            # late imports: the closure must capture only plain values
            # (cloudpickle ships the enclosing user class to replicas)
            from ray_tpu.serve.batching import (_Entry, _active_calls,
                                                _free_queue,
                                                _queue_on_instance,
                                                _record_batch)
            if kwargs or len(args) != (2 if is_method else 1):
                raise TypeError(
                    f"@serve.batch handler {handler.__name__} takes "
                    "exactly one positional argument (the request item)")
            if is_method:
                self_obj, payload = args
                q = _queue_on_instance(self_obj, queue_attr)
            else:
                self_obj, payload = None, args[0]
                q = _free_queue(id(wrapper))
            e = _Entry(payload)
            with q.cv:
                q.pending.append(e)
                q.cv.notify_all()       # wake a collecting leader
            while True:
                with q.cv:
                    if e.done:
                        break
                    if q.leading or e not in q.pending:
                        # someone else leads, or our entry already rode
                        # out in a batch that is executing now — wait
                        # for its completion notify
                        q.cv.wait()
                        continue
                    q.leading = True    # we lead the next batch
                    deadline = time.monotonic() + wait_s
                    while True:
                        n = len(q.pending)
                        if n >= size_cap:
                            break
                        active = _active_calls()
                        if active is not None and n >= active:
                            break   # nobody left to join: cut early
                        left = deadline - time.monotonic()
                        if left <= 0:
                            break
                        q.cv.wait(left)
                    batch_entries = q.pending[:size_cap]
                    del q.pending[:len(batch_entries)]
                    # release leadership BEFORE executing so the next
                    # batch collects while this one runs; a caller left
                    # in pending promotes itself on wake
                    q.leading = False
                    q.cv.notify_all()
                if not batch_entries:
                    continue
                inputs = [en.value for en in batch_entries]
                try:
                    outs = handler(self_obj, inputs) if is_method \
                        else handler(inputs)
                    if not isinstance(outs, (list, tuple)) \
                            or len(outs) != len(inputs):
                        raise TypeError(
                            f"@serve.batch handler {handler.__name__} "
                            f"must return a list of {len(inputs)} "
                            f"results, got {type(outs).__name__}"
                            + (f" of length {len(outs)}"
                               if isinstance(outs, (list, tuple))
                               else ""))
                except BaseException as err:    # noqa: BLE001
                    for en in batch_entries:
                        en.error, en.done = err, True
                else:
                    for en, out in zip(batch_entries, outs):
                        if isinstance(out, Exception):
                            en.error = out
                        else:
                            en.result = out
                        en.done = True
                try:
                    _record_batch(len(batch_entries))
                finally:
                    with q.cv:
                        q.cv.notify_all()
                # our own entry rode in this batch unless a full window
                # formed ahead of us — then lead again for the rest
                if e.done:
                    break
            if e.error is not None:
                raise e.error
            return e.result

        wrapper._serve_batch = True
        wrapper._serve_batch_size = size_cap
        wrapper._serve_batch_wait_s = wait_s
        return wrapper
    return deco if fn is None else deco(fn)
