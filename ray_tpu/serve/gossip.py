"""Gossiped per-replica load digests for the sharded serve router.

With one router per deployment (PR 3) the per-replica inflight map was
a single coherent dict.  Sharding the router per-ingress
(``serve_router_shards``) splits that view: each shard only *observes*
its own dispatches.  Instead of re-centralizing behind a lock — the
bottleneck sharding exists to remove — shards exchange **load
digests**: each shard's ``{replica_key: inflight}`` map is folded into
a per-deployment board at most every ``serve_gossip_interval_s``, and
a shard routes power-of-two-choices on

    own live count  +  (folded total − own count at fold time)

i.e. its *exact* local contribution plus a bounded-stale view of every
peer shard's.  Folds piggyback on the health manager's probe round
(``runtime/health.py``), the same beat that already carries node
liveness — no new RPC — and happen opportunistically at submit time
when the board is older than the gossip interval.  The distributed
form of the same protocol (digests riding node heartbeats to the head)
runs at 1k nodes in the simulator (``sim/serve.py``).

Staleness vs. caps: a stale digest can *under*-count a replica and let
two shards both dispatch to its last free slot.  That cannot
oversubscribe execution — replicas are threaded actors whose
``max_concurrency`` IS ``max_ongoing_requests``, so the excess call
queues in the replica mailbox instead of running, shows up in the next
digest, and p2c steers away.  Staleness degrades placement quality,
never the cap.

The board also fixes the unbounded per-replica growth bug: every fold
evicts digest entries whose replica left the controller's membership
(scale-down, death, loan reclaim), and ``evict()`` drops a
deployment's whole board entry at teardown.
"""

from __future__ import annotations

import threading

from ..common import clock as _clk
from ..common import locksets

__all__ = ["LoadBoard", "board", "fold_all"]


class _Folded:
    """One deployment's folded digest: the per-replica totals plus each
    shard's contribution at fold time (so a shard can subtract itself
    back out and never double-count its own live dispatches)."""

    __slots__ = ("t", "total", "per_shard", "versions")

    def __init__(self, t: float, total: dict, per_shard: dict,
                 versions: dict | None = None):
        self.t = t
        self.total = total          # replica_key -> summed inflight
        self.per_shard = per_shard  # shard_id -> {replica_key: inflight}
        self.versions = versions or {}  # replica_key -> model version


@locksets.track("folds", "evicted_replicas")
class LoadBoard:
    """Process-local gossip board, one entry per deployment (keyed by
    the controller's KV base).  A leaf lock: callers snapshot shard
    state first, then publish — the board never calls back out."""

    def __init__(self):
        self._lock = threading.Lock()
        self._folded: dict[str, _Folded] = {}
        self.folds = 0
        self.evicted_replicas = 0

    # -- publish -------------------------------------------------------------
    def fold(self, base: str, shard_digests: dict[int, dict[bytes, int]],
             live: set[bytes],
             versions: dict[bytes, str] | None = None) -> None:
        """Merge the shards' digest maps for one deployment.  Entries
        for replicas outside ``live`` (the controller's current
        membership) are evicted — dead, downscaled, and reclaimed
        replicas must not haunt the load view (or grow it forever).
        ``versions`` tags each live replica with its model version so
        digest readers (metrics, status) can see rollout progress
        without an extra controller RPC."""
        total: dict[bytes, int] = {}
        per_shard: dict[int, dict[bytes, int]] = {}
        dropped = 0
        for sid, digest in shard_digests.items():
            kept: dict[bytes, int] = {}
            for key, n in digest.items():
                if key not in live:
                    dropped += 1
                    continue
                kept[key] = n
                total[key] = total.get(key, 0) + n
            per_shard[sid] = kept
        ver = {k: v for k, v in (versions or {}).items() if k in live}
        with self._lock:
            self._folded[base] = _Folded(_clk.monotonic(), total,
                                         per_shard, ver)
            self.folds += 1
            self.evicted_replicas += dropped

    def evict(self, base: str) -> None:
        with self._lock:
            self._folded.pop(base, None)

    # -- read ----------------------------------------------------------------
    def age(self, base: str) -> float:
        with self._lock:
            f = self._folded.get(base)
        if f is None:
            return float("inf")
        return _clk.monotonic() - f.t

    def remote_load(self, base: str, shard_id: int, key: bytes) -> int:
        """Peer shards' folded inflight count for one replica: the
        total minus the asking shard's own contribution at fold time
        (its live count is added back by the caller)."""
        with self._lock:
            f = self._folded.get(base)
            if f is None:
                return 0
            own = f.per_shard.get(shard_id, {}).get(key, 0)
            return max(f.total.get(key, 0) - own, 0)

    def digest_size(self, base: str) -> int:
        with self._lock:
            f = self._folded.get(base)
            return len(f.total) if f is not None else 0

    def version_counts(self, base: str) -> dict[str, int]:
        """Replicas per model version in the folded digest — the
        gossip-eye view of rollout progress."""
        with self._lock:
            f = self._folded.get(base)
            if f is None:
                return {}
            out: dict[str, int] = {}
            for v in f.versions.values():
                out[v] = out.get(v, 0) + 1
            return out

    def stats(self) -> dict:
        with self._lock:
            ages = [_clk.monotonic() - f.t
                    for f in self._folded.values()]
            return {
                "deployments": len(self._folded),
                "folds": self.folds,
                "evicted_replicas": self.evicted_replicas,
                "max_age_s": round(max(ages), 4) if ages else 0.0,
            }


board = LoadBoard()


def fold_all() -> int:
    """Fold every router group in this process — the health manager's
    probe round calls this (gossip piggybacks on the liveness beat).
    Returns the number of deployments folded."""
    from .router import RouterGroup
    n = 0
    for group in RouterGroup._groups():
        try:
            group.fold()
            n += 1
        except Exception:   # noqa: BLE001 — gossip is best-effort
            pass
    return n
