"""Per-deployment request plane: admission control, bounded queueing,
deadline-aware dispatch, load shedding, and request-level stats.

Reference parity: upstream Serve's router
(``python/ray/serve/_private/router.py``) sits between the
``DeploymentHandle`` and the replica set — it caps per-replica in-flight
requests at ``max_ongoing_requests`` (excess requests QUEUE client-side
instead of over-submitting), bounds that queue at
``max_queued_requests`` (a full queue sheds with ``BackPressureError``),
and picks replicas with power-of-two-choices on observed load
(SURVEY.md §1 layer 14; mount empty).

Here the request plane is SHARDED (the per-ingress router model): a
``RouterGroup`` per controller owns ``serve_router_shards``
``RequestRouter`` shards, sessions (multiplexed model ids, HTTP
``X-Session-Id``) consistent-hash onto shards, and each shard routes
power-of-two-choices on its own exact counts plus the peer shards'
gossiped load digests (``serve/gossip.py`` — folded at most every
``serve_gossip_interval_s``, piggybacked on the health probe round).
Replica stickiness itself is rendezvous hashing over *replica* ids, so
it is shard-independent by construction: re-sharding, shard restarts,
and refreshes cannot move a model's traffic.  Queued requests are
PROMISE object refs: ``remote()`` never blocks — when all replicas are saturated it
allocates a fresh object id, parks the request in the bounded queue,
and returns a ref to the not-yet-submitted result.  A dispatcher
thread submits parked requests as completions free replica slots,
copying each real result into its promise (or poisoning it on deadline
expiry, so ``ray_tpu.get`` surfaces ``GetTimeoutError`` instead of
hanging on work that was never done).

Load accounting feeds the ``_Controller`` autoscaler through GCS KV:

- ``inflight-<base>``  +1 at dispatch (router), -1 at completion
  (replica shell) — or by the router itself when the completion is a
  TRANSPORT error (dead replica): the shell never ran, so the router
  must settle the counter or the backlog signal inflates forever.
  Requests whose replica DIED are then failed over — dead replica
  evicted from the shard view, request re-routed — so a stale view
  window (replica release, loan reclaim, crash) degrades to a retry,
  not a caller-visible ActorDiedError.  Driver-side requests are ALL
  promise-backed (even the unsaturated fast path) precisely so this
  retry has a promise to re-point.
- ``queued-<base>``    +1 at enqueue, -1 at dispatch/expiry/shed.
- ``lat-<base>``       request-latency EWMA (ms), written by the router
  on every completion; the autoscaler and ``serve.status`` read it.
- ``batch*-<base>``    batch-size histogram counters written by the
  replicas' ``@serve.batch`` wrappers.

Workers and replicas (no driver store, so completions are unobservable)
fall back to direct dispatch with optimistic accounting — their KV
increment still happens before submit and is rolled back if the submit
itself fails.
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque

from ..common.status import BackPressureError, GetTimeoutError
from .batching import BATCH_BUCKETS


def _api():
    import ray_tpu
    return ray_tpu


def _now() -> float:
    return time.monotonic()


# -- stats -------------------------------------------------------------------

_QPS_WINDOW_S = 5.0


class _Stats:
    """Driver-side request counters for one deployment (feeds the
    Prometheus endpoint, the dashboard, and ``ray_tpu status``)."""

    def __init__(self):
        self.lock = threading.Lock()
        self.completed = 0
        self.user_errors = 0
        self.transport_errors = 0
        self.shed = 0
        self.expired = 0
        self.ewma_ms = 0.0
        self._lat_ms = deque(maxlen=512)
        self._done_t = deque(maxlen=4096)

    def record_completion(self, lat_ms: float, alpha: float,
                          user_error: bool) -> float:
        with self.lock:
            self.completed += 1
            if user_error:
                self.user_errors += 1
            self._lat_ms.append(lat_ms)
            self._done_t.append(time.monotonic())
            self.ewma_ms = lat_ms if self.completed == 1 else \
                alpha * lat_ms + (1.0 - alpha) * self.ewma_ms
            return self.ewma_ms

    def snapshot(self) -> dict:
        with self.lock:
            lats = sorted(self._lat_ms)
            now = time.monotonic()
            recent = sum(1 for t in self._done_t
                         if now - t <= _QPS_WINDOW_S)
            out = {
                "completed": self.completed,
                "user_errors": self.user_errors,
                "transport_errors": self.transport_errors,
                "shed": self.shed,
                "expired": self.expired,
                "qps": round(recent / _QPS_WINDOW_S, 2),
                "latency_ewma_ms": round(self.ewma_ms, 3),
            }
        if lats:
            out["p50_ms"] = round(lats[len(lats) // 2], 3)
            out["p99_ms"] = round(lats[min(len(lats) - 1,
                                           int(len(lats) * 0.99))], 3)
        else:
            out["p50_ms"] = out["p99_ms"] = 0.0
        return out


class _Queued:
    __slots__ = ("method", "args", "kwargs", "mux", "deadline", "ref",
                 "t_enq")

    def __init__(self, method, args, kwargs, mux, deadline, ref):
        self.method = method
        self.args = args
        self.kwargs = kwargs
        self.mux = mux
        self.deadline = deadline    # monotonic, or None
        self.ref = ref              # promise ObjectRef
        self.t_enq = _now()


# -- router ------------------------------------------------------------------

class RequestRouter:
    """One router SHARD (see module docstring).  ``for_controller`` /
    ``discard`` remain as compatibility entry points but resolve
    through the :class:`RouterGroup` registry — the group is the
    per-deployment object now."""

    @classmethod
    def for_controller(cls, controller) -> "RouterGroup":
        return RouterGroup.for_controller(controller)

    @classmethod
    def discard(cls, controller) -> None:
        RouterGroup.discard(controller)

    @classmethod
    def _routers(cls) -> list["RequestRouter"]:
        return [s for g in RouterGroup._groups() for s in g._shards]

    def __init__(self, controller, shard_id: int = 0, group=None):
        self._controller = controller
        self._shard_id = shard_id
        self._group = group
        self._cv = threading.Condition()
        self._version = -1
        self._replicas: list = []
        self._kv_inflight = b""
        self._kv_base = ""
        self._cfg: dict = {}
        self._inflight: dict[bytes, int] = {}
        self._queue: deque[_Queued] = deque()
        self._rr = 0
        self._calls = 0
        self._refreshing = False
        self._closed = False
        self._dispatcher: threading.Thread | None = None
        self._stats = _Stats()
        self._store = None
        self._store_checked = False
        self._suspect_keys: set[bytes] = set()  # replicas on gray nodes
        self._suspect_at = 0.0

    # -- environment ---------------------------------------------------------
    def _driver_store(self):
        """The owner's memory store, or None outside the driver (workers
        cannot observe completions, so they run in fallback mode)."""
        if not self._store_checked:
            # Idempotent lazy init: concurrent callers compute the
            # same value, so the last-writer-wins race is benign.
            try:
                from ray_tpu.api import _get_runtime
                self._store = getattr(  # rtlint: disable=W7
                    _get_runtime(), "store", None)
            except Exception:   # noqa: BLE001
                self._store = None  # rtlint: disable=W7
            self._store_checked = True  # rtlint: disable=W7
        return self._store

    def _kv(self, key: bytes, delta: int) -> None:
        from ray_tpu.experimental.internal_kv import _internal_kv_incr
        try:
            _internal_kv_incr(key, delta, namespace="serve")
        except Exception:   # noqa: BLE001 — accounting must not fail a call
            pass

    # -- replica view (satellite: fetch OUTSIDE the lock) --------------------
    def _refresh(self, force: bool = False) -> None:
        """Pick up controller-side scaling.  The RPC happens with no
        router lock held — a slow controller must not stall concurrent
        callers that already have a usable (if stale) view; only
        view-LESS callers wait, on the fetching leader's result."""
        with self._cv:
            self._calls += 1
            if not force and self._replicas and self._calls % 16 != 0:
                return
            while self._refreshing:
                if self._replicas and not force:
                    return          # stale view beats stalling
                self._cv.wait(1.0)  # viewless: ride the leader's fetch
                if self._replicas and not force:
                    return
                force = False       # the leader's result satisfies us
            self._refreshing = True
        got = None
        try:
            got = _api().get(self._controller.get_replicas.remote(),
                             timeout=30)
        finally:
            with self._cv:
                self._refreshing = False
                if got is not None:
                    version, replicas, kv_key, cfg = got
                    if version != self._version:
                        live = {r._actor_id.binary() for r in replicas}
                        self._inflight = {
                            k: v for k, v in self._inflight.items()
                            if k in live}
                    self._version, self._replicas = version, replicas
                    # Whole-object publishes of immutable values: racy
                    # readers see either the old or new snapshot, and a
                    # stale view is valid by design (see docstring).
                    self._kv_inflight = kv_key.encode()  # rtlint: disable=W7
                    self._kv_base = cfg.get("base", "")  # rtlint: disable=W7
                    was_rolling = self._cfg.get("rollout_active", False)
                    self._cfg = cfg  # rtlint: disable=W7
                    if was_rolling and not cfg.get("rollout_active") \
                            and self._group is not None:
                        # rollout sealed/rolled back: one version again
                        self._group.clear_version_pins()
                self._cv.notify_all()

    def _ensure_view(self) -> None:
        self._refresh()
        if not self._replicas:
            # scale-to-zero cold start: ask for a replica, blocking
            _api().get(self._controller.ensure_replica.remote(),
                       timeout=60)
            self._refresh(force=True)

    # -- replica choice ------------------------------------------------------
    def _load_locked(self, replica) -> int:
        """Routing load for one replica: this shard's exact live count
        plus the peer shards' gossiped (bounded-stale) contribution.
        With one shard the remote term is identically zero and this is
        the PR-3 single-router behavior, bit for bit."""
        key = replica._actor_id.binary()
        own = self._inflight.get(key, 0)
        if self._group is None or self._group.num_shards == 1:
            return own
        from .gossip import board
        return own + board.remote_load(self._kv_base, self._shard_id,
                                       key)

    def _refresh_suspects_locked(self) -> set[bytes]:
        """Actor-id binaries of replicas on SUSPECT nodes (gray
        failures flagged by the health manager).  Observable only on
        the in-process driver — client mode and workers see an empty
        set (the head's scheduler still soft-avoids those nodes).
        Cached ~1 s so the per-request cost is a clock read."""
        now = _now()
        if now - self._suspect_at < 1.0:
            return self._suspect_keys
        self._suspect_at = now
        keys: set[bytes] = set()
        try:
            from ray_tpu.api import _get_runtime
            rt = _get_runtime()
            cluster = getattr(rt, "cluster", None)
            am = getattr(rt, "actor_manager", None)
            if cluster is not None and am is not None:
                rows = cluster.crm.suspect_rows()
                if rows:
                    keys = am.actors_on_rows(rows)
        except Exception:   # noqa: BLE001 — health view is best-effort
            keys = set()
        self._suspect_keys = keys
        return keys

    def _pick_locked(self, mux: str, capped: bool = True):
        """Power-of-two-choices among replicas with a free slot; a
        multiplexed model id overrides with rendezvous hashing so one
        model's calls stick to one replica (its ``@multiplexed`` LRU
        stays hot) — a saturated sticky replica returns None (the
        request queues rather than breaking stickiness).  While a
        rolling update is in flight the candidate set first narrows to
        the session's pinned model version (never to empty — the pin
        migrates when its version has no replica left), so no sticky
        session straddles two weight versions mid-flip."""
        import random
        reps = self._replicas
        if not reps:
            return None
        # demote replicas on quarantined/suspect nodes: route around
        # them while ANY healthy replica exists (a fully-suspect
        # replica set keeps serving — degraded beats down)
        suspects = self._refresh_suspects_locked()
        if suspects:
            healthy = [r for r in reps
                       if r._actor_id.binary() not in suspects]
            if healthy:
                reps = healthy
        if mux and self._group is not None and \
                self._cfg.get("rollout_active"):
            reps = self._group.pin_candidates(mux, reps, self._cfg)
        cap = self._cfg.get("max_ongoing", 4)
        if mux and len(reps) > 1:
            import hashlib
            rep = max(reps, key=lambda r: hashlib.md5(
                r._actor_id.binary() + mux.encode()).digest())
            self._rr += 1
            if capped and self._load_locked(rep) >= cap:
                return None
            return rep
        elig = [r for r in reps
                if not capped or self._load_locked(r) < cap]
        if not elig:
            return None
        self._rr += 1
        if len(elig) == 1:
            return elig[0]
        i, j = random.sample(range(len(elig)), 2)
        li, lj = self._load_locked(elig[i]), self._load_locked(elig[j])
        if li == lj:
            return elig[(i, j)[self._rr % 2]]
        return elig[i] if li < lj else elig[j]

    def _acquire_locked(self, replica) -> None:
        key = replica._actor_id.binary()
        self._inflight[key] = self._inflight.get(key, 0) + 1

    def _release(self, replica_key: bytes) -> None:
        with self._cv:
            c = self._inflight.get(replica_key, 0)
            if c > 0:
                self._inflight[replica_key] = c - 1
            self._cv.notify_all()

    # -- submission ----------------------------------------------------------
    def submit(self, method: str, args: tuple, kwargs: dict, mux: str,
               stream: bool, timeout_s: float | None):
        self._ensure_view()
        if self._group is not None:
            self._group.maybe_fold()    # gossip: refresh stale digests
        self._controller.tick.remote()  # fire-and-forget scale poke
        if stream:
            return self._submit_stream(method, args, kwargs, mux)
        store = self._driver_store()
        if store is None:
            return self._submit_fallback(method, args, kwargs, mux)
        deadline = None if timeout_s is None else _now() + timeout_s
        if deadline is not None and timeout_s <= 0:
            with self._stats.lock:
                self._stats.expired += 1
            raise GetTimeoutError(
                f"request deadline expired before submission "
                f"(timeout_s={timeout_s})")
        with self._cv:
            replica = self._pick_locked(mux)
            if replica is not None:
                self._acquire_locked(replica)
            else:
                return self._enqueue_locked(method, args, kwargs, mux,
                                            deadline)
        # even the fast path hands back a PROMISE ref, never the raw
        # submit ref: a replica that dies under a stale view (release,
        # loan reclaim, crash) then re-routes invisibly instead of
        # surfacing ActorDiedError to a caller who picked nothing
        from ray_tpu.common.ids import ObjectID
        from ray_tpu.runtime.object_ref import ObjectRef
        promise = ObjectRef(ObjectID.from_random())
        self._dispatch(replica, method, args, kwargs, mux,
                       promise=promise)
        return promise

    def _enqueue_locked(self, method, args, kwargs, mux, deadline):
        """All replicas saturated: park the request (bounded) and return
        a promise ref.  Caller holds the router lock."""
        from ray_tpu.common.ids import ObjectID
        from ray_tpu.runtime.object_ref import ObjectRef
        limit = self._cfg.get("max_queued", 200)
        if self._group is not None and self._group.num_shards > 1:
            # the deployment-level queue bound splits across shards, so
            # total parked work stays ~max_queued regardless of shards
            limit = max(1, limit // self._group.num_shards)
        if len(self._queue) >= limit:
            with self._stats.lock:
                self._stats.shed += 1
            name = self._cfg.get("name", "?")
            raise BackPressureError(
                f"deployment {name!r} rejected the request: all "
                f"replicas are at max_ongoing_requests and the request "
                f"queue is full ({limit} queued); retry later")
        ref = ObjectRef(ObjectID.from_random())
        item = _Queued(method, args, kwargs, mux, deadline, ref)
        self._queue.append(item)
        self._kv(b"queued-" + self._kv_base.encode(), 1)
        self._ensure_dispatcher_locked()
        self._cv.notify_all()
        return ref

    def _ensure_dispatcher_locked(self) -> None:
        if self._dispatcher is None or not self._dispatcher.is_alive():
            self._dispatcher = threading.Thread(
                target=self._dispatch_loop, daemon=True,
                name=(f"serve-router-{self._cfg.get('name', '?')}"
                      f"-s{self._shard_id}"))
            self._dispatcher.start()

    def _submit_call(self, replica, method, args, kwargs, mux,
                     streaming: bool = False):
        """KV-accounted submit: +1 inflight BEFORE the call (backlog
        drives upscaling), rolled back if the submit itself raises —
        a failed submit must not permanently inflate the signal."""
        from ray_tpu.actor_api import ActorMethod
        self._kv(self._kv_inflight, 1)
        try:
            if streaming:
                return ActorMethod(replica, "__serve_call__",
                                   num_returns="streaming").remote(
                    method, args, kwargs, mux)
            return ActorMethod(replica, "__serve_call__").remote(
                method, args, kwargs, mux)
        except BaseException:
            self._kv(self._kv_inflight, -1)
            raise

    def _dispatch(self, replica, method, args, kwargs, mux, promise):
        """Submit to an acquired replica slot and watch the completion.
        Returns the real ref (inline path) — queued requests get their
        promise fulfilled instead."""
        rkey = replica._actor_id.binary()
        try:
            ref = self._submit_call(replica, method, args, kwargs, mux)
        except BaseException as err:
            self._release(rkey)
            if promise is None:
                raise
            self._poison(promise, err)
            return None
        self._watch(rkey, ref, promise, (method, args, kwargs, mux))
        return ref

    def _watch(self, replica_key: bytes, ref, promise,
               request=None) -> None:
        """Completion observer: frees the replica slot, classifies the
        result (transport errors settle the shell's KV debt), records
        latency, and fulfills the promise for queued requests.  A
        replica-death completion on a promise fails OVER instead of
        failing the request: the dead replica is evicted from the local
        view and the request re-routed to a live one (membership
        changed under a stale view — planned releases and loan reclaims
        land here)."""
        store = self._driver_store()
        t0 = _now()

        def done(_oid=None):
            from ray_tpu.runtime.serialization import (ActorDiedError,
                                                       TaskCancelledError,
                                                       WorkerCrashedError)
            lat_ms = (_now() - t0) * 1000.0
            err = store.error_of(ref.id)
            transport = err is not None and isinstance(
                err.cause,
                (ActorDiedError, WorkerCrashedError, TaskCancelledError))
            if transport:
                # the replica shell never ran: settle its -1 ourselves
                self._kv(self._kv_inflight, -1)
                with self._stats.lock:
                    self._stats.transport_errors += 1
                self._release(replica_key)
                # cancellation is deliberate — surface it; replica
                # DEATH evicts the stale view entry immediately (the
                # next pick skips the corpse) and re-routes the request
                # (at-least-once, matching upstream serve's
                # retry-on-replica-failure)
                if isinstance(err.cause,
                              (ActorDiedError, WorkerCrashedError)):
                    self._evict_dead(replica_key)
                    if promise is not None and request is not None:
                        self._redispatch(promise, request)
                        return
            else:
                from ray_tpu.common.config import get_config
                alpha = get_config().serve_latency_ewma_alpha
                ewma = self._stats.record_completion(
                    lat_ms, alpha, user_error=err is not None)
                self._write_latency(ewma)
                self._release(replica_key)
            if promise is not None:
                self._fulfill(promise, ref)
        store.on_ready(ref.id, done)

    def _evict_dead(self, replica_key: bytes) -> None:
        """Drop a dead replica from the local routing view NOW — the
        transport error proves it is gone; waiting for the periodic
        refresh would keep landing requests on it."""
        with self._cv:
            self._replicas = [r for r in self._replicas
                              if r._actor_id.binary() != replica_key]
            self._inflight.pop(replica_key, None)
            self._cv.notify_all()

    def _redispatch(self, promise, request) -> None:
        """Re-route a request whose replica died before running it:
        straight to a free live replica, or parked with its EXISTING
        promise ref for the dispatcher thread.  Each hop evicts a dead
        replica first, so the fail-over chain is bounded by the view."""
        method, args, kwargs, mux = request
        with self._cv:
            replica = self._pick_locked(mux)
            if replica is not None:
                self._acquire_locked(replica)
            else:
                self._queue.append(_Queued(method, args, kwargs, mux,
                                           None, promise))
                self._kv(b"queued-" + self._kv_base.encode(), 1)
                self._ensure_dispatcher_locked()
                self._cv.notify_all()
                return
        self._dispatch(replica, method, args, kwargs, mux, promise)

    def _write_latency(self, ewma_ms: float) -> None:
        from ray_tpu.experimental.internal_kv import _internal_kv_put
        try:
            _internal_kv_put(b"lat-" + self._kv_base.encode(),
                             f"{ewma_ms:.3f}".encode(),
                             namespace="serve")
        except Exception:   # noqa: BLE001
            pass

    def _fulfill(self, promise, real_ref) -> None:
        """Copy the settled real result into the promise entry.  Runs on
        a store sealer thread: the common case (in-band or local value)
        is a dict copy; the rare remote-resident case is handed to a
        one-shot thread so the sealer never blocks on a pull."""
        store = self._driver_store()
        try:
            vals = store.get_raw_blocking([real_ref.id], timeout=0.0)
            if vals is None:
                raise KeyError("result not present")
            store.put(promise.id, vals[0])
        except Exception:   # noqa: BLE001 — remote entry: pull off-thread
            def pull():
                try:
                    store.put(promise.id,
                              _api().get(real_ref, timeout=60))
                except BaseException as err:    # noqa: BLE001
                    self._poison(promise, err)
            threading.Thread(target=pull, daemon=True,
                             name="serve-promise-pull").start()

    def _poison(self, promise, err: BaseException) -> None:
        from ray_tpu.runtime.serialization import RayTaskError
        store = self._driver_store()
        if isinstance(err, RayTaskError):
            store.poison(promise.id, err)
        else:
            store.poison(promise.id, RayTaskError(
                "serve request", f"{type(err).__name__}: {err}", err))

    # -- queued dispatch -----------------------------------------------------
    def _dispatch_loop(self) -> None:
        while True:
            if self._group is not None:
                self._group.maybe_fold()
            expired: list[_Queued] = []
            to_send: list[tuple[_Queued, object]] = []
            with self._cv:
                if self._closed:
                    return
                now = _now()
                remaining: deque[_Queued] = deque()
                while self._queue:
                    item = self._queue.popleft()
                    if item.deadline is not None \
                            and item.deadline <= now:
                        expired.append(item)
                        continue
                    replica = self._pick_locked(item.mux)
                    if replica is None:
                        remaining.append(item)
                        continue
                    self._acquire_locked(replica)
                    to_send.append((item, replica))
                self._queue = remaining
                if not expired and not to_send:
                    wait = 0.5
                    deadlines = [i.deadline for i in self._queue
                                 if i.deadline is not None]
                    if deadlines:
                        wait = min(wait,
                                   max(min(deadlines) - _now(), 0.0))
                    self._cv.wait(wait)
                    continue
            qkey = b"queued-" + self._kv_base.encode()
            for item in expired:
                self._kv(qkey, -1)
                with self._stats.lock:
                    self._stats.expired += 1
                self._poison(item.ref, GetTimeoutError(
                    f"request expired after "
                    f"{_now() - item.t_enq:.3f}s in the "
                    f"{self._cfg.get('name', '?')!r} queue, before "
                    "dispatch"))
            for item, replica in to_send:
                self._kv(qkey, -1)
                self._dispatch(replica, item.method, item.args,
                               item.kwargs, item.mux, promise=item.ref)

    # -- non-driver / streaming paths ----------------------------------------
    def _submit_fallback(self, method, args, kwargs, mux):
        """Worker-side handles cannot observe completions: dispatch
        directly (uncapped) with round-robin-ish p2c."""
        with self._cv:
            replica = self._pick_locked(mux, capped=False)
        if replica is None:
            raise RuntimeError("no replicas available")
        return self._submit_call(replica, method, args, kwargs, mux)

    def _submit_stream(self, method, args, kwargs, mux):
        """Streaming calls bypass the queue and the in-flight cap:
        there is no single seal to observe, and a long-lived stream
        pinning a slot would starve unary traffic.  The KV inflight
        count still covers them (the shell settles at stream end)."""
        with self._cv:
            replica = self._pick_locked(mux, capped=False)
        if replica is None:
            raise RuntimeError("no replicas available")
        return self._submit_call(replica, method, args, kwargs, mux,
                                 streaming=True)

    # -- teardown / introspection -------------------------------------------
    def _close(self) -> None:
        with self._cv:
            self._closed = True
            pending = list(self._queue)
            self._queue.clear()
            self._cv.notify_all()
        if self._driver_store() is not None:
            for item in pending:
                self._poison(item.ref, GetTimeoutError(
                    "deployment deleted while the request was queued"))

    def snapshot(self) -> dict:
        with self._cv:
            out = {
                "deployment": self._cfg.get("name", ""),
                "shard": self._shard_id,
                "replicas": len(self._replicas),
                "queued": len(self._queue),
                "inflight": sum(self._inflight.values()),
                "max_ongoing_requests": self._cfg.get("max_ongoing", 0),
                "max_queued_requests": self._cfg.get("max_queued", 0),
            }
        out.update(self._stats.snapshot())
        out.update(batch_stats(self._kv_base))
        return out


# -- router group (the per-deployment object) --------------------------------

class RouterGroup:
    """All router shards for one deployment.  Sessions consistent-hash
    onto shards (rendezvous over shard ids — stable across shard
    restarts); sessionless traffic round-robins.  The group is also the
    gossip publisher: ``fold()`` snapshots every shard's digest and
    merges it onto the process :data:`~ray_tpu.serve.gossip.board`."""

    _registry: dict[bytes, "RouterGroup"] = {}
    _reg_lock = threading.Lock()

    @classmethod
    def for_controller(cls, controller,
                       num_shards: int | None = None) -> "RouterGroup":
        key = controller._actor_id.binary()
        with cls._reg_lock:
            group = cls._registry.get(key)
            if group is None:
                group = cls._registry[key] = cls(controller, num_shards)
            return group

    @classmethod
    def discard(cls, controller) -> None:
        key = controller._actor_id.binary()
        with cls._reg_lock:
            group = cls._registry.pop(key, None)
        if group is not None:
            group._close()

    @classmethod
    def _groups(cls) -> list["RouterGroup"]:
        with cls._reg_lock:
            return list(cls._registry.values())

    def __init__(self, controller, num_shards: int | None = None):
        if num_shards is None:
            from ray_tpu.common.config import get_config
            num_shards = get_config().serve_router_shards
        self.num_shards = max(1, int(num_shards))
        self._controller = controller
        self._shards = [RequestRouter(controller, shard_id=i, group=self)
                        for i in range(self.num_shards)]
        self._rr = itertools.count()
        self._fold_lock = threading.Lock()
        self._folded_at = 0.0
        # session/mux -> pinned model version, only populated while the
        # controller reports a rollout in flight.  Group-level (not
        # per-shard) so restart_shard cannot drop a live session's pin.
        self._version_pins: dict[str, str] = {}
        self._pin_lock = threading.Lock()
        self.pin_migrations = 0

    # -- shard choice --------------------------------------------------------
    def shard_for(self, session: str | None) -> RequestRouter:
        """Consistent-hash session stickiness: the same session key
        always lands on the same shard (warm queue position, coherent
        per-session ordering); shard ids are stable, so the mapping
        survives a shard restart.  Sessionless calls round-robin."""
        if self.num_shards == 1:
            return self._shards[0]
        if session:
            import hashlib
            i = max(range(self.num_shards),
                    key=lambda k: hashlib.md5(
                        b"%d|" % k + session.encode()).digest())
            return self._shards[i]
        return self._shards[next(self._rr) % self.num_shards]

    def submit(self, method: str, args: tuple, kwargs: dict, mux: str,
               stream: bool, timeout_s: float | None,
               session: str | None = None):
        return self.shard_for(session or mux).submit(
            method, args, kwargs, mux, stream, timeout_s)

    # -- model-version pinning (rolling updates) -----------------------------
    def pin_candidates(self, key: str, reps: list, cfg: dict) -> list:
        """Narrow ``reps`` to the session's pinned model version while
        a rollout is in flight.  First sight pins to the version
        serving right now; a pin whose version has no replica left
        migrates to the current serving version rather than starving
        the session.  Never returns empty given non-empty ``reps``."""
        rv = cfg.get("replica_versions", {})
        serving = cfg.get("model_version", "v1")
        with self._pin_lock:
            pin = self._version_pins.setdefault(key, serving)
        subset = [r for r in reps
                  if rv.get(r._actor_id.binary().hex(), serving) == pin]
        if subset:
            return subset
        if pin != serving:
            with self._pin_lock:
                self._version_pins[key] = serving
                self.pin_migrations += 1
            subset = [r for r in reps
                      if rv.get(r._actor_id.binary().hex(),
                                serving) == serving]
        return subset or reps

    def clear_version_pins(self) -> None:
        """Called when a refresh observes the rollout over (sealed or
        rolled back): every replica is back on one version, so pins
        would only misfilter the NEXT rollout."""
        with self._pin_lock:
            self._version_pins.clear()

    def version_pins(self) -> dict[str, str]:
        with self._pin_lock:
            return dict(self._version_pins)

    # -- gossip --------------------------------------------------------------
    def fold(self) -> None:
        """Snapshot every shard's digest (each under its own lock, none
        held while publishing) and merge onto the board, evicting
        replicas that left the controller's membership."""
        from .gossip import board
        digests: dict[int, dict[bytes, int]] = {}
        live: set[bytes] = set()
        base = ""
        versions: dict[bytes, str] = {}
        for s in self._shards:
            with s._cv:
                digests[s._shard_id] = dict(s._inflight)
                live.update(r._actor_id.binary() for r in s._replicas)
                base = base or s._kv_base
                rv = s._cfg.get("replica_versions")
                if rv:
                    serving = s._cfg.get("model_version", "v1")
                    for r in s._replicas:
                        key = r._actor_id.binary()
                        versions[key] = rv.get(key.hex(), serving)
        if base:
            board.fold(base, digests, live, versions=versions)
            # Monotonic freshness stamp: a lost store only makes the
            # next maybe_fold() re-fold a little early — harmless.
            self._folded_at = _now()  # rtlint: disable=W7

    def maybe_fold(self) -> None:
        """Fold when the board view is older than the gossip interval —
        the opportunistic half of the protocol (the periodic half rides
        the health probe round)."""
        if self.num_shards == 1:
            return
        from ray_tpu.common.config import get_config
        interval = get_config().serve_gossip_interval_s
        if _now() - self._folded_at < interval:
            return
        if self._fold_lock.acquire(blocking=False):
            try:
                if _now() - self._folded_at >= interval:
                    self.fold()
            finally:
                self._fold_lock.release()

    # -- compat with the single-router surface -------------------------------
    def _refresh(self, force: bool = False) -> None:
        for s in self._shards:
            s._refresh(force=force)

    def restart_shard(self, i: int) -> RequestRouter:
        """Replace one shard in place (crash-and-recreate model).  The
        new shard re-fetches the replica view; session->shard and
        mux->replica hashes are both id-stable, so stickiness holds."""
        old = self._shards[i]
        # Single-slot list store is atomic under the GIL; concurrent
        # readers iterate either the old or new shard, both valid.
        self._shards[i] = RequestRouter(  # rtlint: disable=W7
            self._controller, shard_id=i, group=self)
        old._close()
        return self._shards[i]

    def _close(self) -> None:
        from .gossip import board
        base = ""
        for s in self._shards:
            base = base or s._kv_base
            s._close()
        if base:
            board.evict(base)

    # -- merged stats --------------------------------------------------------
    def backlog(self) -> tuple[int, int, float]:
        """(queued, inflight, latency_ewma_ms) across shards — the
        driver-side load signal the capacity-loan manager reads."""
        queued = inflight = 0
        ewma = 0.0
        for s in self._shards:
            with s._cv:
                queued += len(s._queue)
                inflight += sum(s._inflight.values())
            ewma = max(ewma, s._stats.ewma_ms)
        return queued, inflight, ewma

    def cfg(self) -> dict:
        for s in self._shards:
            if s._cfg:
                return s._cfg
        return {}

    def snapshot(self) -> dict:
        """The deployment-level view: counters summed across shards,
        latency percentiles over the merged sample window, batch stats
        read once (they are deployment-level KV counters)."""
        from .gossip import board
        base = ""
        replicas = queued = inflight = 0
        completed = user_errors = transport_errors = shed = expired = 0
        lats: list[float] = []
        recent = 0
        ewma = 0.0
        cfg: dict = {}
        now = time.monotonic()
        for s in self._shards:
            with s._cv:
                replicas = max(replicas, len(s._replicas))
                queued += len(s._queue)
                inflight += sum(s._inflight.values())
                base = base or s._kv_base
                cfg = cfg or s._cfg
            st = s._stats
            with st.lock:
                completed += st.completed
                user_errors += st.user_errors
                transport_errors += st.transport_errors
                shed += st.shed
                expired += st.expired
                lats.extend(st._lat_ms)
                recent += sum(1 for t in st._done_t
                              if now - t <= _QPS_WINDOW_S)
                if st.completed:
                    ewma = max(ewma, st.ewma_ms)
        lats.sort()
        out = {
            "deployment": cfg.get("name", ""),
            "replicas": replicas,
            "queued": queued,
            "inflight": inflight,
            "max_ongoing_requests": cfg.get("max_ongoing", 0),
            "max_queued_requests": cfg.get("max_queued", 0),
            "shards": self.num_shards,
            "completed": completed,
            "user_errors": user_errors,
            "transport_errors": transport_errors,
            "shed": shed,
            "expired": expired,
            "qps": round(recent / _QPS_WINDOW_S, 2),
            "latency_ewma_ms": round(ewma, 3),
            "gossip_digest": board.digest_size(base),
        }
        if lats:
            out["p50_ms"] = round(lats[len(lats) // 2], 3)
            out["p99_ms"] = round(lats[min(len(lats) - 1,
                                           int(len(lats) * 0.99))], 3)
        else:
            out["p50_ms"] = out["p99_ms"] = 0.0
        out.update(batch_stats(base))
        return out


def batch_stats(kv_base: str) -> dict:
    """Aggregate the replicas' batch-size KV counters for one
    deployment: count, mean, and the raw (non-cumulative) buckets."""
    if not kv_base:
        return {}
    from ray_tpu.experimental.internal_kv import _internal_kv_incr
    try:
        cnt = _internal_kv_incr(f"batchcnt-{kv_base}".encode(), 0,
                                namespace="serve")
        if not cnt:
            return {}
        total = _internal_kv_incr(f"batchsum-{kv_base}".encode(), 0,
                                  namespace="serve")
        buckets = {}
        for le in list(BATCH_BUCKETS) + ["inf"]:
            n = _internal_kv_incr(f"batchb-{le}-{kv_base}".encode(), 0,
                                  namespace="serve")
            if n:
                buckets[str(le)] = n
        return {"batches": cnt,
                "batch_size_mean": round(total / cnt, 2),
                "batch_size_buckets": buckets}
    except Exception:   # noqa: BLE001
        return {}


def request_plane_stats() -> dict[str, dict]:
    """Per-deployment request-plane stats for every router group in
    this process, keyed by deployment name (metrics/dashboard/status
    hook).  Counters are merged across the group's shards."""
    out: dict[str, dict] = {}
    for group in RouterGroup._groups():
        try:
            snap = group.snapshot()
        except Exception:   # noqa: BLE001
            continue
        name = snap.get("deployment") or "?"
        if name in out:
            base = ""
            for s in group._shards:
                base = base or s._kv_base
            name = f"{name}@{base[:4]}"
        out[name] = snap
    return out
