"""Elastic serve<->batch capacity loaning (driver-side loan manager).

When a deployment's backlog crosses the scale-up bar but its replica
pool is already at ``max_replicas`` (the controller's ``at_max``
signal), the cluster can *borrow* an idle batch node instead of shedding:
the node's CRM row is marked ``LOANED``, its generic availability is
force-subtracted to zero (batch placement cannot fit), and a shaped
``serve_loaned`` resource — exposed only on loaned rows — is added, onto
which the controller starts one extra replica (``add_loaner``).  Router
shards pick the loaner up on their next refresh like any other replica.

Reclaim reuses the DRAINING machine's semantics with a restore epilogue
instead of a removal: ``begin_retire_loaner`` pulls the replica out of
the routing set (version bump — shards stop dispatching), the row is
marked draining, the manager polls the replica shell's in-flight count
across ticks until it hits zero (or ``serve_loan_drain_timeout_s``),
then ``finish_retire_loaner`` kills the replica and the row's original
availability is added back.  The node never leaves the cluster, so
reclaim latency is a drain, not a cold boot.

A loaned node that DIES mid-loan or mid-reclaim is booked as a loss
exactly once: the loan record is popped under the manager lock, the
controller drops the dead replica from its membership, and the router's
transport-error path settles the in-flight accounting (the next gossip
fold evicts the dead replica's digest).

Loaning also runs in REVERSE: when batch/train demand is unmet, no idle
batch row exists, and a deployment is quiet, the manager borrows a
serve node — ``begin_release_replica`` pulls the newest replica out of
routing (same drain semantics as a reclaim), the manager polls its
in-flight count to zero, then ``finish_release_replica`` kills it so
the node's full availability returns to the CRM for batch placement.
Serve backlog pressure ends the lend (``restore_replica`` starts a
fresh replica); a lent node that dies is booked as a loss exactly once
by the same popped-record rule, and serve is made whole with a
replacement replica elsewhere.

Ticks ride existing beats — the autoscaler's ``update()`` round (which
also supplies batch pressure as ``unmet``) and the health manager's
probe round — so loaning adds no thread and no new RPC.
"""

from __future__ import annotations

import threading

import numpy as np

from ..common import clock as _clk
from ..common import locksets
from ..common.config import get_config
from ..common.resources import ResourceRequest, to_cu

__all__ = ["CapacityLoanManager"]


def _api():
    import ray_tpu
    return ray_tpu


class _Loan:
    __slots__ = ("node_id", "row", "handle", "key_hex", "ctl_key",
                 "controller", "borrowed", "state", "t_loaned",
                 "t_drain", "drain_deadline")

    def __init__(self, node_id, row, handle, ctl_key, controller,
                 borrowed):
        self.node_id = node_id
        self.row = row
        self.handle = handle            # the loaner replica's handle
        self.key_hex = handle._actor_id.binary().hex()
        self.ctl_key = ctl_key          # controller actor-id binary
        self.controller = controller
        self.borrowed = borrowed        # cu dict force-subtracted at loan
        self.state = "active"           # active -> draining -> (gone)
        self.t_loaned = _clk.monotonic()
        self.t_drain = 0.0
        self.drain_deadline = 0.0


class _ReverseLend:
    __slots__ = ("node_id", "row", "handle", "key_hex", "ctl_key",
                 "controller", "state", "t_start", "drain_deadline")

    def __init__(self, node_id, row, handle, ctl_key, controller):
        self.node_id = node_id
        self.row = row
        self.handle = handle            # the released replica's handle
        self.key_hex = handle._actor_id.binary().hex()
        self.ctl_key = ctl_key
        self.controller = controller
        self.state = "draining"         # draining -> lent -> (gone)
        self.t_start = _clk.monotonic()
        self.drain_deadline = 0.0


@locksets.track("loans_total", "reclaims_total", "loans_lost",
                "reverse_lends_total", "reverse_lends_returned",
                "reverse_lends_lost", "last_reclaim_latency_s")
class CapacityLoanManager:
    """Tracks LOANED rows atop the CRM and drives the loan/reclaim
    state machine.  Driver-side: it reads the driver-local router
    groups' backlog and talks to controllers over plain actor calls."""

    def __init__(self, cluster):
        self._cluster = cluster
        self._lock = threading.Lock()
        self._loans: list[_Loan] = []
        self._rloans: list[_ReverseLend] = []
        self._cooldown_until = 0.0
        self._serve_idle: dict[bytes, float] = {}   # ctl_key -> since
        self.loans_total = 0
        self.reclaims_total = 0
        self.loans_lost = 0
        self.reverse_lends_total = 0
        self.reverse_lends_returned = 0
        self.reverse_lends_lost = 0
        self.last_reclaim_latency_s = 0.0

    # -- the tick (autoscaler round / health probe round) --------------------
    def tick(self, unmet: int = 0) -> None:
        """One loan-manager round.  Non-reentrant by design: overlapping
        beats (autoscaler vs health) skip instead of queueing — the next
        beat re-derives everything from current state."""
        if not self._lock.acquire(blocking=False):
            return
        try:
            self._book_deaths()
            self._advance_reclaims()
            self._advance_releases()
            self._start_reclaims(unmet)
            self._end_stale_releases()
            self._maybe_loan()
            self._maybe_release(unmet)
        finally:
            self._lock.release()

    # -- loss booking (node death mid-loan / mid-reclaim) --------------------
    def _book_deaths(self) -> None:
        crm = self._cluster.crm
        for loan in list(self._loans):
            if crm.row_of(loan.node_id) is not None:
                continue
            # popping the record under the lock IS the exactly-once
            # bookkeeping: later beats see no loan to re-book
            self._loans.remove(loan)
            self.loans_lost += 1
            try:
                if loan.state == "active":
                    _api().get(loan.controller.begin_retire_loaner.remote(
                        loan.key_hex), timeout=10)
                _api().get(loan.controller.finish_retire_loaner.remote(
                    loan.key_hex), timeout=10)
            except Exception:   # noqa: BLE001 — controller may be gone too
                pass
            self._cluster.events.emit(
                "loans", "loan_lost", node_row=loan.row,
                node_id=loan.node_id.hex(), state=loan.state)
        for rl in list(self._rloans):
            if rl.node_id is None or crm.row_of(rl.node_id) is not None:
                continue
            # same exactly-once rule: popping the record IS the booking
            self._rloans.remove(rl)
            self.reverse_lends_lost += 1
            try:
                if rl.state == "draining":
                    _api().get(rl.controller.finish_release_replica.remote(
                        rl.key_hex), timeout=10)
                # serve is made whole with a replacement elsewhere
                _api().get(rl.controller.restore_replica.remote(),
                           timeout=10)
            except Exception:   # noqa: BLE001 — controller may be gone too
                pass
            self._cluster.events.emit(
                "loans", "reverse_lend_lost", node_row=rl.row,
                node_id=rl.node_id.hex(), state=rl.state)

    # -- reclaim state machine -----------------------------------------------
    def _start_reclaims(self, unmet: int) -> None:
        """Begin draining active loans when batch wants its capacity
        back (``unmet`` demand classes) or serve has gone idle for
        ``serve_loan_reclaim_idle_s``."""
        cfg = get_config()
        now = _clk.monotonic()
        idle_keys = set()
        for group in self._groups():
            key = group._controller._actor_id.binary()
            queued, inflight, _ewma = group.backlog()
            if queued == 0 and inflight == 0:
                since = self._serve_idle.setdefault(key, now)
                if now - since >= cfg.serve_loan_reclaim_idle_s:
                    idle_keys.add(key)
            else:
                self._serve_idle.pop(key, None)
        for loan in reversed(self._loans):          # LIFO: newest first
            if loan.state != "active":
                continue
            if unmet > 0 or loan.ctl_key in idle_keys:
                self._begin_reclaim(loan)
                if unmet > 0:
                    unmet -= 1      # one node per pressure unit per tick

    def _begin_reclaim(self, loan: _Loan) -> None:
        try:
            _api().get(loan.controller.begin_retire_loaner.remote(
                loan.key_hex), timeout=10)
        except Exception:   # noqa: BLE001 — death path books it next beat
            return
        # DRAINING semantics: the row leaves every placement view while
        # in-flight work finishes; unlike a node drain there is no
        # removal — the epilogue restores availability instead
        self._cluster.crm.set_draining(loan.node_id, True)
        loan.state = "draining"
        loan.t_drain = _clk.monotonic()
        loan.drain_deadline = loan.t_drain + \
            get_config().serve_loan_drain_timeout_s
        self._cluster.events.emit(
            "loans", "loan_reclaim_started", node_row=loan.row,
            node_id=loan.node_id.hex())

    def _advance_reclaims(self) -> None:
        from ray_tpu.actor_api import ActorMethod
        for loan in list(self._loans):
            if loan.state != "draining":
                continue
            active = 0
            try:
                active = _api().get(
                    ActorMethod(loan.handle, "_active_count").remote(),
                    timeout=5)
            except Exception:   # noqa: BLE001 — unreachable counts as done
                active = 0
            if active > 0 and _clk.monotonic() < loan.drain_deadline:
                continue        # keep draining; poll again next beat
            self._finish_reclaim(loan)

    def _finish_reclaim(self, loan: _Loan) -> None:
        try:
            _api().get(loan.controller.finish_retire_loaner.remote(
                loan.key_hex), timeout=10)
        except Exception:   # noqa: BLE001
            pass
        self._restore_row(loan)
        self._loans.remove(loan)
        self.reclaims_total += 1
        self.last_reclaim_latency_s = \
            round(_clk.monotonic() - loan.t_drain, 4)
        self._cluster.events.emit(
            "loans", "loan_reclaimed", node_row=loan.row,
            node_id=loan.node_id.hex(),
            latency_s=self.last_reclaim_latency_s)

    def _restore_row(self, loan: _Loan) -> None:
        """The restore epilogue: un-drain, drop the loan-shaped
        resource, and add the borrowed availability back (clamped to
        totals by ``add_back``, so a double restore cannot overfill)."""
        crm = self._cluster.crm
        if crm.set_draining(loan.node_id, False) is None:
            return              # node died as the drain converged
        crm.remove_shaped_resources(loan.row,
                                    {"serve_loaned": to_cu(1)})
        if loan.borrowed:
            crm.add_back(loan.row,
                         ResourceRequest.from_cu_dict(loan.borrowed))
        crm.set_loaned(loan.row, False)
        self._cluster.wake_raylets()    # parked batch work fits again

    # -- reverse lend state machine ------------------------------------------
    def _advance_releases(self) -> None:
        """Poll draining released replicas; once in-flight hits zero
        (or the drain deadline passes) kill the replica — the node's
        availability returns to the CRM and batch placement fits."""
        from ray_tpu.actor_api import ActorMethod
        for rl in list(self._rloans):
            if rl.state != "draining":
                continue
            active = 0
            try:
                active = _api().get(
                    ActorMethod(rl.handle, "_active_count").remote(),
                    timeout=5)
            except Exception:   # noqa: BLE001 — unreachable counts as done
                active = 0
            if active > 0 and _clk.monotonic() < rl.drain_deadline:
                continue
            try:
                _api().get(rl.controller.finish_release_replica.remote(
                    rl.key_hex), timeout=10)
            except Exception:   # noqa: BLE001 — death path books it next beat
                continue
            rl.state = "lent"
            self._cluster.wake_raylets()    # parked batch work fits now
            self._cluster.events.emit(
                "loans", "reverse_lend_active", node_row=rl.row,
                node_id=rl.node_id.hex() if rl.node_id else "")

    def _end_stale_releases(self) -> None:
        """Serve wants its capacity back: a deployment whose replica is
        out on a reverse lend built up backlog — end the lend (a fresh
        replica replaces the lent one)."""
        if not self._rloans:
            return
        cfg = get_config()
        bar = max(1, cfg.serve_loan_backlog // 2)
        pressured = set()
        for group in self._groups():
            queued, _inflight, _ewma = group.backlog()
            if queued >= bar:
                pressured.add(group._controller._actor_id.binary())
        for rl in reversed(list(self._rloans)):     # LIFO: newest first
            if rl.ctl_key in pressured:
                self._end_release(rl)

    def _end_release(self, rl: _ReverseLend) -> None:
        # reclaim notice BEFORE the replica returns: batch/train work
        # on the lent row (the elastic trainer's gang) vacates as a
        # PLANNED resize, making room for the restored replica
        try:
            self._cluster.pubsub.publish(
                "node", {"event": "loan_reclaim", "row": rl.row,
                         "node_id": rl.node_id.hex() if rl.node_id
                         else ""})
        except Exception:   # noqa: BLE001 — notice is best-effort
            pass
        try:
            if rl.state == "draining":
                _api().get(rl.controller.finish_release_replica.remote(
                    rl.key_hex), timeout=10)
            _api().get(rl.controller.restore_replica.remote(), timeout=10)
        except Exception:   # noqa: BLE001 — death path books it next beat
            return
        self._rloans.remove(rl)
        self.reverse_lends_returned += 1
        self._cluster.events.emit(
            "loans", "reverse_lend_returned", node_row=rl.row,
            node_id=rl.node_id.hex() if rl.node_id else "")

    def _maybe_release(self, unmet: int) -> None:
        """Reverse direction: batch/train demand is unmet, no idle
        batch row exists to loan the normal way, and a deployment is
        quiet — borrow a serve node by releasing its newest replica."""
        cfg = get_config()
        now = _clk.monotonic()
        if unmet <= 0 or now < self._cooldown_until:
            return
        if self._loans or len(self._rloans) >= cfg.train_borrow_max:
            return      # never both directions at once
        if self._pick_idle_row() is not None:
            return      # plain batch capacity exists; no need to raid serve
        for group in self._groups():
            queued, _inflight, _ewma = group.backlog()
            if queued > 0:
                continue
            controller = group._controller
            try:
                handle = _api().get(
                    controller.begin_release_replica.remote(), timeout=10)
            except Exception:   # noqa: BLE001
                continue
            if handle is None:
                continue        # at the autoscaling floor
            row = self._row_of_handle(handle)
            node_id = self._cluster.crm.id_of(row) if row >= 0 else None
            rl = _ReverseLend(node_id, row, handle,
                              controller._actor_id.binary(), controller)
            rl.drain_deadline = now + cfg.serve_loan_drain_timeout_s
            self._rloans.append(rl)
            self.reverse_lends_total += 1
            self._cooldown_until = now + cfg.serve_loan_cooldown_s
            self._cluster.events.emit(
                "loans", "reverse_lend_started", node_row=row,
                node_id=node_id.hex() if node_id else "",
                deployment=group.cfg().get("name", ""))
            return              # at most one lend per tick

    def _row_of_handle(self, handle) -> int:
        am = getattr(self._cluster, "actor_manager", None)
        if am is None:
            return -1
        rec = am._actors.get(handle._actor_id)
        return rec.row if rec is not None else -1

    # -- loan path ------------------------------------------------------------
    def _maybe_loan(self) -> None:
        cfg = get_config()
        now = _clk.monotonic()
        if now < self._cooldown_until:
            return
        if len(self._loans) >= cfg.serve_loan_max_nodes:
            return
        for group in self._groups():
            gcfg = group.cfg()
            if not gcfg or not gcfg.get("at_max"):
                continue
            queued, _inflight, _ewma = group.backlog()
            if queued < cfg.serve_loan_backlog:
                continue
            if self._loan_to(group):
                self._cooldown_until = _clk.monotonic() + \
                    cfg.serve_loan_cooldown_s
                return              # at most one loan per tick

    def _loan_to(self, group) -> bool:
        row = self._pick_idle_row()
        if row is None:
            return False
        cluster = self._cluster
        crm = cluster.crm
        node_id = crm.id_of(row)
        if node_id is None:
            return False
        totals, avail, _mask = crm.arrays()
        borrowed = {crm.resource_index.name(int(col)):
                    int(avail[row][col])
                    for col in np.flatnonzero(avail[row])}
        # order matters: mark LOANED and zero availability BEFORE the
        # shaped resource appears, so no batch round can slip work in
        crm.set_loaned(row, True)
        if borrowed:
            crm.force_subtract(row,
                               ResourceRequest.from_cu_dict(borrowed))
        crm.add_shaped_resources(row, {"serve_loaned": to_cu(1)})
        controller = group._controller
        try:
            handle = _api().get(controller.add_loaner.remote(
                {"resources": {"serve_loaned": 1}, "num_cpus": 0}),
                timeout=30)
        except Exception:   # noqa: BLE001 — unwind: the row stays batch
            crm.remove_shaped_resources(row, {"serve_loaned": to_cu(1)})
            if borrowed:
                crm.add_back(row,
                             ResourceRequest.from_cu_dict(borrowed))
            crm.set_loaned(row, False)
            return False
        self._loans.append(_Loan(node_id, row, handle,
                                 controller._actor_id.binary(),
                                 controller, borrowed))
        self.loans_total += 1
        cluster.events.emit("loans", "loan_started", node_row=row,
                            node_id=node_id.hex(),
                            deployment=group.cfg().get("name", ""))
        return True

    def _pick_idle_row(self) -> int | None:
        """An idle, fully-free, healthy batch row (never the head,
        never a draining/suspect/already-loaned one)."""
        cluster = self._cluster
        crm = cluster.crm
        totals, avail, mask = crm.arrays()
        for row, raylet in sorted(cluster.raylets.items()):
            if row == cluster._head_row or not mask[row]:
                continue
            if crm.is_draining(row) or crm.is_loaned(row) or \
                    bool(crm.suspect[row]):
                continue
            if not (avail[row] == totals[row]).all():
                continue
            if not raylet.is_idle():
                continue
            return row
        return None

    # -- introspection ---------------------------------------------------------
    def _groups(self) -> list:
        from .router import RouterGroup
        return RouterGroup._groups()

    def active_loans(self) -> list[dict]:
        with self._lock:
            return [{"node_id": loan.node_id.hex(), "row": loan.row,
                     "state": loan.state,
                     "age_s": round(_clk.monotonic() - loan.t_loaned, 3)}
                    for loan in self._loans]

    def stats(self) -> dict:
        with self._lock:
            return self._stats_locked()

    def _stats_locked(self) -> dict:
        return {"loans_total": self.loans_total,
                "reclaims_total": self.reclaims_total,
                "loans_lost": self.loans_lost,
                "loans_active": len(self._loans),
                "reverse_lends_total": self.reverse_lends_total,
                "reverse_lends_returned": self.reverse_lends_returned,
                "reverse_lends_lost": self.reverse_lends_lost,
                "reverse_lends_active": len(self._rloans),
                "last_reclaim_latency_s": self.last_reclaim_latency_s}
