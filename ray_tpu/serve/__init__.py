"""ray_tpu.serve — scalable deployments over actor replica pools.

Reference parity: ``ray.serve`` (``python/ray/serve/``) —
``@serve.deployment`` wraps a class/function, ``.bind(...)`` builds an
application graph, ``serve.run`` materializes it as a controller +
replica actors, ``DeploymentHandle.remote`` routes requests across
replicas, autoscaling tracks ongoing requests against a target, and
handles compose (a deployment takes another's handle), and an HTTP
proxy routes ``route_prefix`` requests into the replica sets — SURVEY.md
§1 layer 14; mount empty.
"""

from .deployment import (Application, Deployment, DeploymentHandle,
                         delete, deployment, get_deployment_handle,
                         get_multiplexed_model_id, http_address,
                         multiplexed, run, shutdown, start, status)
from .http_proxy import HTTPRequest

__all__ = ["Application", "Deployment", "DeploymentHandle", "delete",
           "deployment", "get_deployment_handle",
           "get_multiplexed_model_id", "http_address", "HTTPRequest",
           "multiplexed", "run", "shutdown", "start", "status"]
