"""ray_tpu.serve — scalable deployments over actor replica pools.

Reference parity: ``ray.serve`` (``python/ray/serve/``) —
``@serve.deployment`` wraps a class/function, ``.bind(...)`` builds an
application graph, ``serve.run`` materializes it as a controller +
replica actors, ``DeploymentHandle.remote`` routes requests across
replicas, autoscaling tracks ongoing requests against a target, and
handles compose (a deployment takes another's handle), and an HTTP
proxy routes ``route_prefix`` requests into the replica sets — SURVEY.md
§1 layer 14; mount empty.
"""

from ..common.status import BackPressureError
from .batching import batch
from .deployment import (Application, Deployment, DeploymentHandle,
                         delete, deployment, get_deployment_handle,
                         get_multiplexed_model_id, http_address,
                         multiplexed, run, shutdown, start, status)
from .http_proxy import HTTPRequest
from .router import RequestRouter, RouterGroup

__all__ = ["Application", "BackPressureError", "batch", "Deployment",
           "DeploymentHandle", "delete", "deployment",
           "get_deployment_handle", "get_multiplexed_model_id",
           "http_address", "HTTPRequest", "multiplexed",
           "RequestRouter", "RouterGroup", "run", "shutdown", "start",
           "status"]
