"""Broadcast tree shaping: fan-out plans over the bandwidth cost model.

A plan is the static shape of one 1->N distribution tree: who relays
from whom (parent assignment) and in what order members attach (the
chunk schedule follows attach order — see ``ops/broadcast_kernel.py``
for the scoring).  Two constructors:

* ``build_plan`` — topology-aware: the fan-out kernel over the cluster's
  node-bandwidth matrix plus per-node uplink in-flight load, device-
  evaluated for big member sets (same backend-switch discipline as the
  pull manager's source selection).
* ``balanced_plan`` — index-ordered balanced F-ary tree over a plain
  member list, for callers with no bandwidth matrix (the plane-level
  ``ObjectPlane.broadcast`` primitive, benches).

Both emit the same ``BroadcastPlan``; the relay protocol never sees the
difference.
"""

from __future__ import annotations

from ..common.config import get_config


class BroadcastPlan:
    """One tree: ``root`` plus, per attached member, its parent and the
    ancestor fallback chain the relay protocol re-parents through."""

    def __init__(self, root, parent: dict, order: list):
        self.root = root
        self.parent = parent        # member -> parent (root included)
        self.order = order          # members, attach order
        self.children: dict = {}
        for c, p in parent.items():
            self.children.setdefault(p, []).append(c)

    def fallbacks(self, member) -> list:
        """Ancestor chain above ``member``'s parent, ending at the root:
        the re-parent targets when the parent dies mid-broadcast."""
        out = []
        node = self.parent.get(member)
        while node is not None and node not in out and node != member:
            out.append(node)
            node = self.parent.get(node)
        if self.root not in out:
            out.append(self.root)
        return out

    def relay_fanout(self) -> float:
        """Mean children per relaying (non-leaf) node — the observability
        gauge ``broadcast_relay_fanout``."""
        if not self.children:
            return 0.0
        return sum(len(v) for v in self.children.values()) \
            / len(self.children)

    def depth(self) -> int:
        d = 0
        for m in self.order:
            hops = len(self.fallbacks(m))
            d = max(d, hops)
        return d


def build_plan(member_rows, bw, root_row: int, fanout: int | None = None,
               inflight_kb=None) -> BroadcastPlan:
    """Shape a tree over the node-bandwidth matrix.  ``member_rows`` are
    CRM rows wanting a replica (root excluded or included — it is
    always covered); rows the matrix cannot reach stay unattached and
    are absent from the plan (callers fall back to a plain pull)."""
    cfg = get_config()
    fanout = int(fanout or cfg.broadcast_fanout)
    n = bw.shape[0]
    import numpy as np
    member = np.zeros(n, dtype=bool)
    for r in member_rows:
        if 0 <= r < n:
            member[r] = True
    member[root_row] = True
    if len(member_rows) >= cfg.broadcast_device_batch_min:
        from ..ops.broadcast_kernel import plan_fanout_np
        parent, order = plan_fanout_np(member, bw, root_row, fanout,
                                       inflight_kb)
    else:
        from ..ops.broadcast_kernel import plan_fanout_oracle
        parent, order = plan_fanout_oracle(member, bw, root_row, fanout,
                                           inflight_kb)
    pmap = {int(c): int(parent[c]) for c in range(n) if parent[c] >= 0}
    attach = sorted(pmap, key=lambda c: int(order[c]))
    return BroadcastPlan(int(root_row), pmap, attach)


def balanced_plan(members: list, root, fanout: int | None = None
                  ) -> BroadcastPlan:
    """Index-ordered balanced F-ary tree over an explicit member list
    (no bandwidth matrix): member i's parent is the root for i < F,
    else member (i - F) // F.  Depth ~log_F(M)."""
    fanout = int(fanout or get_config().broadcast_fanout)
    fanout = max(1, fanout)
    parent = {}
    for i, m in enumerate(members):
        parent[m] = root if i < fanout else members[(i - fanout) // fanout]
    return BroadcastPlan(root, parent, list(members))
