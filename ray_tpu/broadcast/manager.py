"""BroadcastManager: the head-side coordinator for 1->N distribution.

Builds the fan-out plan (``broadcast/plan.py`` over the cluster's
node-bandwidth matrix + the pull manager's per-node uplink in-flight
ledger), fires one ``bc_begin`` per member — all concurrently, so the
relay pipeline forms immediately — and records directory locations as
replicas seal.  Members whose relay session fails (every fallback
gone) are retried through the pull manager's striped machinery, so a
broadcast degrades to pulls rather than failing outright.

Concurrent-pull integration: while a tree is active for an object, the
pull manager offers each new pull of that object to ``join()`` first —
the destination grafts onto the tree as a fresh leaf (parented to a
completed member or the root) instead of opening an independent source
stream against the cost model's favorite replica.
"""

from __future__ import annotations

import itertools
import threading

from ..common.config import get_config
from ..common import clock as _clk
from .plan import BroadcastPlan, build_plan


class _ActiveTree:
    """Coordinator-side record of one in-flight broadcast."""

    def __init__(self, bcast_id: str, oid, size: int, chunk: int,
                 root_addr: str, plan: BroadcastPlan):
        self.bcast_id = bcast_id
        self.oid = oid
        self.size = size
        self.chunk = chunk
        self.root_addr = root_addr
        self.plan = plan
        self.lock = threading.Lock()
        self.completed_addrs: list[str] = []    # sealed replicas, oldest
        #                                         first (graft parents)
        self.joins = 0


class BroadcastManager:
    def __init__(self, cluster):
        self._cluster = cluster
        self._lock = threading.Lock()
        self._seq = itertools.count()
        self._active: dict[bytes, _ActiveTree] = {}   # oid bin -> tree
        # stats
        self.trees_started = 0
        self.trees_completed = 0
        self.trees_failed = 0           # >= 1 member fell back to pull
        self.members_reached = 0
        self.members_fallback = 0
        self.joins = 0
        self.last_relay_fanout = 0.0
        self.ewma_time_to_all_s = 0.0

    # -- public API ----------------------------------------------------------
    def broadcast(self, object_id, node_rows=None, fanout=None,
                  timeout: float | None = None) -> dict:
        """Distribute ``object_id`` to ``node_rows`` (default: every
        node with a plane).  Blocks until every member holds a sealed
        replica (relay tree first, pull-manager fallback for stragglers)
        and returns a summary dict."""
        cluster = self._cluster
        oid = getattr(object_id, "object_id", object_id)
        cfg = get_config()
        rows = cluster.directory.locations(oid)
        if not rows:
            return {"ok": False, "error": "object has no tracked "
                    "location (in-band or lost)", "members": 0}
        root_row, root_addr = self._pick_root(rows)
        if root_addr is None:
            return {"ok": False, "error": "no servable root replica "
                    "(no plane is serving the object)", "members": 0}
        size = self._object_size(oid, root_addr)
        if size <= 0:
            return {"ok": False, "error": "object size unknown",
                    "members": 0}
        if node_rows is None:
            node_rows = sorted(cluster.planes)
        members = [r for r in node_rows
                   if not cluster.directory.has_location(oid, r)]
        # head-resident rows (no plane address) share the head store:
        # bytes are either already there or one plain pull away
        local_rows = [r for r in members
                      if cluster.planes.get(r) is None]
        members = [r for r in members if r not in local_rows]
        t0 = _clk.monotonic()
        summary = {"ok": True, "bcast_id": None, "members": len(members),
                   "reached": 0, "joined_rows": [], "fallbacks": 0,
                   "depth": 0, "relay_fanout": 0.0, "seconds": 0.0}
        for r in local_rows:
            if self._pull_fallback(oid, size, r, root_addr):
                cluster.directory.add_location(oid, r)
                summary["reached"] += 1
            else:
                summary["ok"] = False
        if not members:
            summary["seconds"] = _clk.monotonic() - t0
            return summary
        plan = build_plan(members, cluster.bandwidth_mbps, root_row,
                          fanout=fanout,
                          inflight_kb=cluster.pull_manager.inflight_kb(
                              cluster.bandwidth_mbps.shape[0]))
        bcast_id = f"{oid.hex()[:16]}.{next(self._seq)}"
        chunk = cfg.broadcast_chunk_mb * (1 << 20)
        tree = _ActiveTree(bcast_id, oid, size, chunk, root_addr, plan)
        with self._lock:
            self._active[oid.binary()] = tree
            self.trees_started += 1
        self.last_relay_fanout = plan.relay_fanout()
        summary["bcast_id"] = bcast_id
        summary["depth"] = plan.depth()
        summary["relay_fanout"] = round(self.last_relay_fanout, 2)
        try:
            reached, fell_back = self._run_tree(tree, timeout)
        finally:
            with self._lock:
                self._active.pop(oid.binary(), None)
        summary["reached"] += len(reached)
        summary["fallbacks"] = len(fell_back)
        summary["joined_rows"] = sorted(reached | set(fell_back))
        unattached = [r for r in members
                      if r not in reached and r not in fell_back]
        summary["fallbacks"] += len(unattached)
        for r in (*fell_back, *unattached):
            if self._pull_fallback(oid, size, r, root_addr):
                cluster.directory.add_location(oid, r)
                summary["reached"] += 1
            else:
                summary["ok"] = False
        dt = _clk.monotonic() - t0
        summary["seconds"] = round(dt, 4)
        with self._lock:
            self.members_reached += summary["reached"]
            self.members_fallback += summary["fallbacks"]
            self.joins += tree.joins
            if summary["fallbacks"] or not summary["ok"]:
                self.trees_failed += 1
            else:
                self.trees_completed += 1
            self.ewma_time_to_all_s = (
                dt if self.ewma_time_to_all_s == 0
                else 0.8 * self.ewma_time_to_all_s + 0.2 * dt)
        return summary

    def join(self, object_id, dest_row: int) -> bool:
        """Pull-manager integration: a concurrent pull of an object with
        an ACTIVE broadcast grafts onto the tree as a fresh leaf instead
        of opening a new source stream.  True when the graft sealed a
        replica at ``dest_row`` (the caller then records the location
        exactly like a finished pull)."""
        if not get_config().broadcast_join_pulls:
            return False
        cluster = self._cluster
        with self._lock:
            tree = self._active.get(object_id.binary())
        if tree is None:
            return False
        dest_addr = cluster.planes.get(dest_row)
        if dest_addr is None:
            return False        # head-resident: a plain pull is local
        with tree.lock:
            # graft under a completed member when one exists (spreads
            # uplink load off the root), else under the root itself
            parents = [*tree.completed_addrs[:2], tree.root_addr]
            tree.joins += 1
        try:
            res = cluster.plane._peer(dest_addr).call(
                "bc_begin", tree.bcast_id, tree.oid.binary(), tree.size,
                tuple(dict.fromkeys(parents)), tree.chunk,
                timeout=self._tree_timeout(tree.size))
        except Exception:   # noqa: BLE001 — graft failed: plain pull
            cluster.plane._drop_peer(dest_addr)
            return False
        return bool(res.get("ok"))

    def stats(self) -> dict:
        with self._lock:
            active = len(self._active)
        return {
            "bcast_trees_started": self.trees_started,
            "bcast_trees_completed": self.trees_completed,
            "bcast_trees_failed": self.trees_failed,
            "bcast_active_trees": active,
            "bcast_members_reached": self.members_reached,
            "bcast_members_fallback": self.members_fallback,
            "bcast_joins": self.joins,
            "bcast_relay_fanout": round(self.last_relay_fanout, 2),
            "bcast_time_to_all_ewma_s": round(self.ewma_time_to_all_s,
                                              4),
        }

    def shutdown(self) -> None:
        with self._lock:
            self._active.clear()

    # -- internals -----------------------------------------------------------
    def _pick_root(self, rows) -> tuple[int, str | None]:
        """First location with a servable plane address (head-resident
        replicas serve through the head's own plane)."""
        cluster = self._cluster
        for row in rows:
            addr = cluster.planes.get(row)
            if addr is None:
                addr = cluster.plane.serve_address
            if addr is not None:
                return int(row), addr
        return int(rows[0]), None

    def _object_size(self, oid, root_addr: str) -> int:
        kind, size = self._cluster.store.plasma_info(oid)
        if kind in ("shm", "spill"):
            return int(size)
        try:
            _kind, size = self._cluster.plane._peer(root_addr).call(
                "op_stat", oid.binary(), timeout=30.0)
            return int(size)
        except Exception:   # noqa: BLE001 — root unreachable
            return 0

    def _tree_timeout(self, size: int) -> float:
        """Generous per-member deadline: whole-object at 1 MB/s plus
        the configured chunk-stall allowance."""
        return get_config().broadcast_fetch_timeout_s + \
            max(60.0, size / (1 << 20))

    def _run_tree(self, tree: _ActiveTree, timeout: float | None
                  ) -> tuple[set, list]:
        """Fire bc_begin at every member concurrently (the pipeline
        forms as ancestors start landing chunks) and wait for the
        results.  Returns (reached rows, fallback rows)."""
        cluster = self._cluster
        plan = tree.plan
        addr_of = {plan.root: tree.root_addr}
        for row in plan.order:
            addr_of[row] = cluster.planes.get(row)
        deadline = (_clk.monotonic() + timeout) if timeout else None
        futs: list[tuple[int, object]] = []
        fell_back: list[int] = []
        for row in plan.order:
            dest = addr_of.get(row)
            if dest is None:
                fell_back.append(row)
                continue
            sources = []
            for anc in plan.fallbacks(row):
                a = addr_of.get(anc)
                if a is not None and a not in sources and a != dest:
                    sources.append(a)
            if tree.root_addr not in sources:
                sources.append(tree.root_addr)
            try:
                fut = cluster.plane._peer(dest).call_async(
                    "bc_begin", tree.bcast_id, tree.oid.binary(),
                    tree.size, tuple(sources), tree.chunk)
            except Exception:   # noqa: BLE001 — member unreachable
                cluster.plane._drop_peer(dest)
                fell_back.append(row)
                continue
            futs.append((row, fut))
        reached: set[int] = set()
        per_member = self._tree_timeout(tree.size)
        for row, fut in futs:
            left = per_member
            if deadline is not None:
                left = min(left, max(0.0, deadline - _clk.monotonic()))
            ok = False
            try:
                res = fut.result(left)
                ok = bool(res.get("ok"))
            except Exception:   # noqa: BLE001 — member died mid-session
                cluster.plane._drop_peer(addr_of[row])
            if ok:
                # bytes land BEFORE the directory update (same ordering
                # discipline as the pull manager)
                cluster.directory.add_location(tree.oid, row)
                reached.add(row)
                with tree.lock:
                    tree.completed_addrs.append(addr_of[row])
            else:
                fell_back.append(row)
        return reached, fell_back

    def _pull_fallback(self, oid, size: int, row: int,
                       root_addr: str) -> bool:
        """A member the tree could not reach still gets its replica —
        through the plane's striped pull machinery."""
        cluster = self._cluster
        self_addr = cluster.planes.get(row)
        extra = tuple(a for a in (cluster.plane.serve_address,)
                      if a and a != root_addr)
        if self_addr is None:
            return cluster.plane.pull_into_local(oid, size, root_addr,
                                                 extra)
        return cluster.plane.request_remote_pull(self_addr, oid, size,
                                                 root_addr, extra)
