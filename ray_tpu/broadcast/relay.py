"""Relay-as-you-receive: the broadcast plane's wire protocol.

A broadcast tree moves one sealed object from a root to N members over
the existing raw-frame channel.  Each member runs ONE relay session
(``bc_begin``): it pulls chunks in order from its parent and, the
moment a chunk lands in its ingest block, serves that chunk to its own
children (``bc_fetch``) — a receiver becomes a source chunk by chunk,
so the tree pipelines and time-to-all-replicas scales with tree depth
(~log N), not member count.

Wire surface (attached next to the op_* handlers on every plane):

    bc_begin(bcast_id, oid, size, sources, chunk)
        -> run the relay session INLINE on the request thread (each
           request gets its own thread); returns a result dict once the
           local replica is sealed.  ``sources`` is the parent followed
           by the ancestor fallback chain ending at the root.
    bc_fetch(bcast_id, oid, offset, length)
        -> one raw chunk.  Served from the LIVE session's ingest block
           when the chunk has landed (blocking server-side until it
           does — the relay pipeline), from the sealed store after
           commit, or from the sealed store directly when no session
           exists (the root's case).

Failure protocol: a child that loses its parent mid-broadcast (chunk
error, connection loss, stall past ``broadcast_fetch_timeout_s``)
re-parents itself to the next fallback and resumes its missing chunks
— the orphan's own children never notice (they keep fetching from the
orphan).  Only when every fallback incl. the root is gone does
``bc_begin`` fail, and the coordinator falls back to the pull manager's
striped machinery.

Commit discipline: ``commit()`` flips the arena block's birth pin off,
making it spillable — so the session counts outstanding chunk serves
and commits only once the last in-flight serve releases (bounded wait;
a wedged child must not pin the block forever).
"""

from __future__ import annotations

import queue as _queue
import threading
from collections import deque

from ..common.config import get_config
from ..common.ids import ObjectID
from ..common import clock as _clk

# payload-serving kinds, mirrored from the object plane (a "remote"
# entry has no local bytes to serve)
_SERVABLE = ("shm", "spill")


class BroadcastRelayError(RuntimeError):
    """A relay session failed (every source incl. the root is gone, or
    the local store could not stage the ingest)."""


class _RelaySession:
    """One member's side of one broadcast: ingest + relay state."""

    def __init__(self, endpoint, bcast_id: str, oid: ObjectID,
                 size: int, chunk: int, handle):
        self.ep = endpoint
        self.bcast_id = bcast_id
        self.oid = oid
        self.size = size
        self.chunk = chunk
        self.handle = handle
        self.nchunks = max(1, -(-size // chunk))
        self.cv = threading.Condition()
        self.have = [False] * self.nchunks
        self.state = "running"          # -> "committed" | "failed"
        self.serving = 0                # in-flight chunk serves (views)
        self.result: dict | None = None
        # chunk cache for non-arena ingests (spill file / in-memory
        # buffer): handle.view() is None there, so relayed chunks are
        # kept in memory until commit (children then read the sealed
        # entry).  Bounded by the object size; the common shm path
        # never populates it.
        self._cache: dict[int, bytes] = {}
        self.pulled = 0
        self.relayed = 0
        self.reparents = 0

    # -- serving side (bc_fetch) --------------------------------------------
    def serve(self, off: int, ln: int):
        """A child wants ``[off, off+ln)``: block until the covering
        chunk lands, then serve straight from the ingest block (pinned
        via the serving counter until the bytes hit the socket).
        Returns a RawResult, or None when the session has committed and
        the caller should serve the sealed entry instead."""
        from ..rpc.wire import RawResult
        k = min(off // self.chunk, self.nchunks - 1)
        deadline = _clk.monotonic() + get_config().broadcast_fetch_timeout_s
        with self.cv:
            while True:
                if self.state == "committed":
                    return None
                if self.state == "failed":
                    return RawResult((None, 0))
                if self.have[k]:
                    ln2 = max(0, min(ln, self.size - off))
                    view = self.handle.view(off, ln2) if ln2 else None
                    if view is not None:
                        self.serving += 1
                        self.relayed += 1
                        self.ep.chunks_relayed += 1
                        return RawResult(("relay", self.size), view,
                                         release=self._release)
                    data = self._cache.get(k)
                    if data is not None:
                        lo = off - k * self.chunk
                        self.relayed += 1
                        self.ep.chunks_relayed += 1
                        return RawResult(("relay", self.size),
                                         data[lo:lo + ln2])
                    return RawResult((None, 0))
                left = deadline - _clk.monotonic()
                if left <= 0:
                    return RawResult((None, 0))
                self.cv.wait(left)

    def _release(self) -> None:
        with self.cv:
            self.serving -= 1
            self.cv.notify_all()

    # -- receiving side (bc_begin) ------------------------------------------
    def run(self, sources: list[str]) -> dict:
        try:
            self._fetch_all(sources)
            self._finalize_commit()
            res = {"ok": True, "pulled": self.pulled,
                   "relayed": self.relayed, "reparents": self.reparents}
        except Exception as exc:    # noqa: BLE001 — any failure aborts
            self._finalize_abort()
            res = {"ok": False, "error": str(exc), "pulled": self.pulled,
                   "relayed": self.relayed, "reparents": self.reparents}
        with self.cv:
            self.result = res
            self.cv.notify_all()
        return res

    def wait_result(self, timeout: float) -> dict:
        """A duplicate bc_begin (coordinator retry) parks here."""
        deadline = _clk.monotonic() + timeout
        with self.cv:
            while self.result is None:
                left = deadline - _clk.monotonic()
                if left <= 0:
                    return {"ok": False, "error": "duplicate begin timed "
                            "out awaiting the original session"}
                self.cv.wait(left)
            return self.result

    def _fetch_all(self, sources: list[str]) -> None:
        """Windowed in-order chunk fetch from the current source;
        re-parent to the next fallback on failure.  In-order issue is
        deliberate: chunk k lands before k+1, so children waiting on
        the relay pipeline progress front-to-back with no holes."""
        cfg = get_config()
        plane = self.ep.plane
        window = max(1, int(cfg.broadcast_window))
        timeout = cfg.broadcast_fetch_timeout_s
        oid_bin = self.oid.binary()
        can_sink = getattr(self.handle, "view", None) is not None and \
            self.handle.view(0, min(self.chunk, self.size)) is not None
        sink_live = [True]
        done_q: _queue.Queue = _queue.Queue()
        pend: deque = deque(range(self.nchunks))
        inflight: dict[tuple, object] = {}      # (addr, k) -> fut
        si = 0                                  # current source index

        def make_sink(off: int, ln: int):
            if not can_sink:
                return None

            def sink(payload_len: int):
                if not sink_live[0] or payload_len != ln:
                    return None
                return self.handle.view(off, ln)
            return sink

        def reparent(addr: str) -> None:
            """Advance past a dead source (only if it is the CURRENT
            one — stale failures from an already-abandoned parent must
            not skip a healthy fallback)."""
            nonlocal si
            plane._drop_peer(addr)
            if si < len(sources) and sources[si] == addr:
                si += 1
                self.reparents += 1
                self.ep.reparents += 1

        def pump() -> None:
            while pend and len(inflight) < window:
                if si >= len(sources):
                    raise BroadcastRelayError(
                        f"broadcast {self.bcast_id}: every source "
                        f"gone after {self.reparents} re-parents")
                addr = sources[si]
                k = pend.popleft()
                off = k * self.chunk
                ln = min(self.chunk, self.size - off)
                token = (addr, k)
                try:
                    fut = plane._peer(addr).call_async(
                        "bc_fetch", self.bcast_id, oid_bin, off, ln,
                        on_done=lambda t=token: done_q.put(t),
                        sink=make_sink(off, ln))
                except Exception:   # noqa: BLE001 — connect/send failed
                    pend.appendleft(k)
                    reparent(addr)
                    continue
                inflight[token] = fut

        try:
            pump()
            while inflight:
                try:
                    token = done_q.get(timeout=timeout)
                except _queue.Empty:
                    # total stall: the current parent is wedged (gray
                    # link) — re-parent and re-issue its stripes
                    addr = sources[si] if si < len(sources) else None
                    if addr is None:
                        raise BroadcastRelayError(
                            f"broadcast {self.bcast_id}: stalled with "
                            "no fallback left") from None
                    for (a, k) in list(inflight):
                        if a == addr:
                            inflight.pop((a, k))
                            pend.appendleft(k)
                    reparent(addr)
                    pump()
                    continue
                fut = inflight.pop(token, None)
                if fut is None:
                    continue        # re-issued elsewhere already
                addr, k = token
                off = k * self.chunk
                ln = min(self.chunk, self.size - off)
                data = landed = None
                try:
                    rep = fut.result(0)
                    meta = rep.meta
                    if isinstance(meta, tuple) and meta and \
                            meta[0] in (*_SERVABLE, "relay"):
                        data = rep.payload
                        landed = data is None
                except Exception:   # noqa: BLE001 — chunk RPC died
                    data = None
                if self.have[k]:
                    continue        # duplicate landing (late re-issue)
                if landed or (data is not None and len(data) == ln):
                    if not landed:
                        self.handle.write(off, bytes(data))
                        if not can_sink:
                            self._cache[k] = bytes(data)
                    self.pulled += 1
                    self.ep.chunks_pulled += 1
                    with self.cv:
                        self.have[k] = True
                        self.cv.notify_all()
                else:
                    pend.appendleft(k)
                    reparent(addr)
                pump()
        finally:
            sink_live[0] = False
            if inflight:
                # sever connections still owing chunk bytes (a late
                # reply must never land into a freed ingest block) and
                # confirm in-flight receives resolved before unwinding
                for (addr, _k), fut in inflight.items():
                    if not fut.done():
                        plane._drop_peer(addr)
                deadline = _clk.monotonic() + 5.0
                for fut in inflight.values():
                    if not fut.wait(max(0.0,
                                        deadline - _clk.monotonic())):
                        break
        if not all(self.have):
            raise BroadcastRelayError(
                f"broadcast {self.bcast_id}: incomplete "
                f"({sum(self.have)}/{self.nchunks} chunks)")

    def _finalize_commit(self) -> None:
        """Seal: wait (bounded) for in-flight serves to release their
        arena views, commit, then point children at the sealed entry."""
        deadline = _clk.monotonic() + \
            get_config().broadcast_fetch_timeout_s
        with self.cv:
            while self.serving > 0:
                left = deadline - _clk.monotonic()
                if left <= 0:
                    break       # wedged child: commit anyway
                self.cv.wait(left)
        self.handle.commit()
        with self.cv:
            self.state = "committed"
            self._cache.clear()
            self.cv.notify_all()

    def _finalize_abort(self) -> None:
        with self.cv:
            self.state = "failed"
            self.cv.notify_all()
            deadline = _clk.monotonic() + 5.0
            while self.serving > 0:
                left = deadline - _clk.monotonic()
                if left <= 0:
                    break
                self.cv.wait(left)
            self._cache.clear()
        self.handle.abort()


class BroadcastEndpoint:
    """One plane's broadcast surface: live relay sessions plus the
    sealed-store serving path (how a tree's root serves — it has no
    session, just the sealed object)."""

    def __init__(self, plane):
        self.plane = plane
        self._lock = threading.Lock()
        self._sessions: dict[str, _RelaySession] = {}
        # counters (merged into the plane's stats surface)
        self.sessions_started = 0
        self.sessions_completed = 0
        self.sessions_failed = 0
        self.chunks_pulled = 0          # fetched from a parent
        self.chunks_relayed = 0         # served from a LIVE session
        self.chunks_sealed_served = 0   # served from the sealed store
        self.reparents = 0              # fallback advances, all sessions

    def handlers(self) -> dict:
        return {
            "bc_begin": self._bc_begin,
            "bc_fetch": self._bc_fetch,
        }

    def active_sessions(self) -> int:
        with self._lock:
            return len(self._sessions)

    def stats(self) -> dict:
        return {
            "bcast_sessions_started": self.sessions_started,
            "bcast_sessions_completed": self.sessions_completed,
            "bcast_sessions_failed": self.sessions_failed,
            "bcast_active_sessions": self.active_sessions(),
            "bcast_chunks_pulled": self.chunks_pulled,
            "bcast_chunks_relayed": self.chunks_relayed,
            "bcast_chunks_sealed_served": self.chunks_sealed_served,
            "bcast_reparents": self.reparents,
        }

    # -- handlers ------------------------------------------------------------
    def _bc_begin(self, bcast_id: str, oid_bin: bytes, size: int,
                  sources: tuple, chunk: int = 0) -> dict:
        """Join a broadcast tree: ingest the object chunk-by-chunk from
        ``sources[0]`` (falling back along the ancestor chain), relaying
        each landed chunk to any child that asks.  Runs inline on this
        request's thread; returns once the local replica is sealed."""
        oid = ObjectID(oid_bin)
        store = self.plane.store
        kind, _sz = store.plasma_info(oid)
        if kind in (*_SERVABLE, "inband"):
            return {"ok": True, "already": True, "pulled": 0,
                    "relayed": 0, "reparents": 0}
        cfg = get_config()
        chunk = int(chunk) or cfg.broadcast_chunk_mb * (1 << 20)
        with self._lock:
            ses = self._sessions.get(bcast_id)
            if ses is not None:
                owner = False
            else:
                handle = store.begin_ingest(oid, int(size))
                if handle is None:
                    return {"ok": True, "already": True, "pulled": 0,
                            "relayed": 0, "reparents": 0}
                ses = _RelaySession(self, bcast_id, oid, int(size),
                                    chunk, handle)
                self._sessions[bcast_id] = ses
                self.sessions_started += 1
                owner = True
        if not owner:
            return ses.wait_result(cfg.broadcast_fetch_timeout_s * 4)
        if getattr(handle, "view", None) is not None and \
                size > chunk:
            # warm the landing pages while chunks are in flight (same
            # rationale as the plane's pull path)
            threading.Thread(target=handle.prefault,
                             name="bcast-prefault", daemon=True).start()
        try:
            res = ses.run([a for a in sources
                           if a and a != self.plane.serve_address])
        finally:
            with self._lock:
                self._sessions.pop(bcast_id, None)
        if res.get("ok"):
            self.sessions_completed += 1
        else:
            self.sessions_failed += 1
        return res

    def _bc_fetch(self, bcast_id: str, oid_bin: bytes, off: int,
                  ln: int):
        """One raw chunk of an in-flight (or finished) broadcast."""
        from ..rpc.wire import RawResult
        with self._lock:
            ses = self._sessions.get(bcast_id)
        if ses is not None and ses.oid.binary() == oid_bin:
            res = ses.serve(off, ln)
            if res is not None:
                n = (res.payload.nbytes
                     if isinstance(res.payload, memoryview)
                     else len(res.payload))
                self.plane.bytes_sent += n
                self.plane.bytes_sent_raw += n
                self.plane.throttle_uplink(n)
                return res
        # no live session: the sealed-store path (the root, a member
        # that already committed, or any node that happens to hold it)
        oid = ObjectID(oid_bin)
        store = self.plane.store
        kind, size = store.plasma_info(oid)
        if kind not in _SERVABLE:
            return RawResult((kind, size))
        buf, release = store.read_range_view(oid, off, ln)
        if buf is None:
            return RawResult(store.plasma_info(oid))
        n = buf.nbytes if isinstance(buf, memoryview) else len(buf)
        self.chunks_sealed_served += 1
        self.plane.bytes_sent += n
        self.plane.bytes_sent_raw += n
        self.plane.throttle_uplink(n)
        return RawResult((kind, size), buf, release=release)
