"""Broadcast plane: topology-aware 1->N object distribution.

The N->1 half of the object plane (striped multi-source pull) has a
1->N sibling here: a relay tree shaped over the node-bandwidth matrix
(``plan.py`` / ``ops/broadcast_kernel.py``), executed by per-node relay
sessions that serve each chunk onward the moment it lands
(``relay.py``), coordinated head-side with directory updates, pull
grafting and failure fallback (``manager.py``).
"""

from .manager import BroadcastManager
from .plan import BroadcastPlan, balanced_plan, build_plan
from .relay import BroadcastEndpoint, BroadcastRelayError

__all__ = ["BroadcastManager", "BroadcastPlan", "BroadcastEndpoint",
           "BroadcastRelayError", "balanced_plan", "build_plan"]
