"""The state API: queryable live cluster state.

Reference parity: ``ray.util.state`` — ``list_tasks/list_actors/
list_objects/list_nodes/list_placement_groups`` return structured rows
sourced from GCS/raylet state, with simple equality filters and a task
summary (``python/ray/util/state/`` — SURVEY.md §1 layer 12, §2.2;
mount empty).  Driver-only, like the reference's default source (the
head's state aggregator).
"""

from __future__ import annotations

from collections import Counter
from typing import Any


def _cluster():
    from ..api import _get_runtime
    rt = _get_runtime()
    if not hasattr(rt, "cluster"):
        raise RuntimeError("the state API is driver-only")
    return rt


def _apply_filters(rows: list[dict],
                   filters: list[tuple] | None) -> list[dict]:
    """``[(key, "=", value)]`` equality filters (the reference's
    predicate shape)."""
    if not filters:
        return rows
    for key, op, value in filters:
        if op not in ("=", "=="):
            raise ValueError(f"unsupported filter op {op!r}")
        # string-coerced fallback: CLI filters arrive as strings, so
        # `--filter row=0` must match the int field (the reference's
        # state CLI compares string forms the same way)
        rows = [r for r in rows
                if r.get(key) == value or str(r.get(key)) == str(value)]
    return rows


def list_nodes(filters: list[tuple] | None = None) -> list[dict]:
    from .. import api
    rows = [{"node_id": n["NodeID"],
             "state": n.get("Status", "ALIVE"),
             "row": n["Row"], "labels": n["Labels"]}
            for n in api.nodes()]
    return _apply_filters(rows, filters)


def list_actors(filters: list[tuple] | None = None) -> list[dict]:
    rt = _cluster()
    rows = [{"actor_id": r["ActorID"], "state": r["State"],
             "name": r["Name"], "pending_calls": r["Pending"],
             "inflight_calls": r["InFlight"]}
            for r in rt.actor_manager.list_actors()]
    return _apply_filters(rows, filters)


def list_tasks(filters: list[tuple] | None = None) -> list[dict]:
    rt = _cluster()
    return _apply_filters(rt.cluster.task_manager.list_rows(), filters)


def list_objects(filters: list[tuple] | None = None) -> list[dict]:
    rt = _cluster()
    store = rt.cluster.store
    directory = rt.cluster.directory
    rows = []
    for oid, size, kind in store.list_objects():
        rows.append({"object_id": oid.hex(), "size_bytes": size,
                     "kind": kind,
                     "locations": list(directory.locations(oid))})
    return _apply_filters(rows, filters)


def list_placement_groups(filters: list[tuple] | None = None) \
        -> list[dict]:
    from .placement_group import placement_group_table
    table = placement_group_table()
    rows = [dict(v, placement_group_id=k) for k, v in table.items()]
    return _apply_filters(rows, filters)


def summarize_tasks() -> dict[str, Any]:
    counts = Counter(r["state"] for r in list_tasks())
    return {"total": sum(counts.values()), "by_state": dict(counts)}


def summarize_actors() -> dict[str, Any]:
    counts = Counter(r["state"] for r in list_actors())
    return {"total": sum(counts.values()), "by_state": dict(counts)}
