"""JAX version compatibility shims shared across the codebase.

One place for the ``jax.shard_map`` vs ``jax.experimental.shard_map``
split (and its ``check_vma``/``check_rep`` kwarg rename) — parallel
copies of this try/except drifted across modules and must move together
on a JAX upgrade.
"""

from __future__ import annotations

from functools import partial


def shard_map_compat(*, check: bool = False):
    """The current JAX's ``shard_map``, with replication checking
    disabled by default (our collective bodies return deliberately
    replicated outputs that the checker cannot always prove)."""
    try:
        from jax import shard_map              # jax >= 0.8
        return shard_map if check else partial(shard_map,
                                               check_vma=False)
    except ImportError:
        from jax.experimental.shard_map import shard_map
        return shard_map if check else partial(shard_map,
                                               check_rep=False)
