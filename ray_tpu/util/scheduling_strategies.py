"""User-facing scheduling strategy objects.

Reference parity: ``python/ray/util/scheduling_strategies.py`` —
``PlacementGroupSchedulingStrategy`` and ``NodeAffinitySchedulingStrategy``
passed as ``.options(scheduling_strategy=...)`` (plus the plain strings
"DEFAULT" / "SPREAD") — SURVEY.md §1 layer 9; mount empty.  These resolve
to the internal ``common.task_spec.SchedulingStrategy`` at submission.
"""

from __future__ import annotations

from dataclasses import dataclass

from ..common.ids import NodeID
from ..common.task_spec import SchedulingStrategy, SchedulingStrategyKind
from .placement_group import PlacementGroup

__all__ = ["PlacementGroupSchedulingStrategy",
           "NodeAffinitySchedulingStrategy",
           "NodeLabelSchedulingStrategy", "resolve_strategy"]


@dataclass
class PlacementGroupSchedulingStrategy:
    placement_group: PlacementGroup
    placement_group_bundle_index: int = -1


@dataclass
class NodeAffinitySchedulingStrategy:
    node_id: NodeID
    soft: bool = False


@dataclass
class NodeLabelSchedulingStrategy:
    """Restrict placement to nodes whose labels match ``hard`` (all pairs
    must match); ``soft=True`` falls back to any node when no labeled
    node can take the task (reference
    ``NodeLabelSchedulingStrategy(hard=..., soft=...)``)."""
    hard: dict
    soft: bool = False


def resolve_strategy(value) -> SchedulingStrategy:
    """Map a user-facing strategy (string or strategy object) to the
    internal SchedulingStrategy."""
    if value is None or value == "DEFAULT":
        return SchedulingStrategy()
    if value == "SPREAD":
        return SchedulingStrategy(kind=SchedulingStrategyKind.SPREAD)
    if isinstance(value, PlacementGroupSchedulingStrategy):
        from ..api import _check_bundle_index
        _check_bundle_index(value.placement_group,
                            value.placement_group_bundle_index)
        return SchedulingStrategy(
            kind=SchedulingStrategyKind.PLACEMENT_GROUP,
            placement_group_id=value.placement_group.id,
            bundle_index=value.placement_group_bundle_index)
    if isinstance(value, NodeAffinitySchedulingStrategy):
        return SchedulingStrategy(
            kind=SchedulingStrategyKind.NODE_AFFINITY,
            node_id=value.node_id, soft=value.soft)
    if isinstance(value, NodeLabelSchedulingStrategy):
        return SchedulingStrategy(
            kind=SchedulingStrategyKind.NODE_LABEL,
            label_selector=tuple(sorted(value.hard.items())),
            soft=value.soft)
    if isinstance(value, SchedulingStrategy):
        return value
    raise TypeError(f"unsupported scheduling_strategy {value!r}")
