"""User-defined metrics exported on the Prometheus endpoint.

Reference parity: ``ray.util.metrics`` — ``Counter``/``Gauge``/
``Histogram`` with tag keys, registered into the same exporter that
serves the core metrics (``python/ray/util/metrics.py`` +
``src/ray/stats/`` — SURVEY.md §1 layer 12, §5.5; mount empty).

Process-local (the driver's endpoint exports the driver's metrics —
the reference aggregates per-node through agents; here the cluster is
one process, so one registry suffices).
"""

from __future__ import annotations

import bisect
import threading

_lock = threading.Lock()
_registry: dict[str, "_Metric"] = {}


def _tags_key(tags: dict | None) -> tuple:
    return tuple(sorted((tags or {}).items()))


class _Metric:
    def __init__(self, name: str, description: str = "",
                 tag_keys: tuple = ()):
        if not name.replace("_", "").isalnum():
            raise ValueError(f"invalid metric name {name!r}")
        self.name = name
        self.description = description
        self.tag_keys = tuple(tag_keys)
        self._default_tags: dict = {}
        with _lock:
            prev = _registry.get(name)
            if prev is not None:
                # re-creation (module reload, per-job setup re-run)
                # ADOPTS the existing series — two registry entries
                # would emit duplicate HELP/TYPE blocks, which
                # Prometheus rejects for the whole scrape
                if type(prev) is not type(self):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{type(prev).__name__}")
                self._adopt(prev)
            else:
                self._series: dict[tuple, float] = {}
                _registry[name] = self

    def _adopt(self, prev: "_Metric") -> None:
        self._series = prev._series

    def set_default_tags(self, tags: dict) -> "_Metric":
        self._default_tags = dict(tags)
        return self

    def _resolve_tags(self, tags: dict | None) -> dict:
        merged = {**self._default_tags, **(tags or {})}
        extra = set(merged) - set(self.tag_keys)
        if extra:
            raise ValueError(
                f"tags {sorted(extra)} not in declared tag_keys "
                f"{self.tag_keys}")
        return merged

    def _rows(self) -> list[tuple[str, dict, float]]:
        with _lock:
            return [(self.name, dict(k), v)
                    for k, v in self._series.items()]


class Counter(_Metric):
    TYPE = "counter"

    def inc(self, value: float = 1.0, tags: dict | None = None) -> None:
        if value < 0:
            raise ValueError("counters only go up")
        key = _tags_key(self._resolve_tags(tags))
        with _lock:
            self._series[key] = self._series.get(key, 0.0) + value


class Gauge(_Metric):
    TYPE = "gauge"

    def set(self, value: float, tags: dict | None = None) -> None:
        key = _tags_key(self._resolve_tags(tags))
        with _lock:
            self._series[key] = float(value)


class Histogram(_Metric):
    TYPE = "histogram"

    def __init__(self, name: str, description: str = "",
                 boundaries: list[float] | None = None,
                 tag_keys: tuple = ()):
        self.boundaries = sorted(boundaries or
                                 [0.001, 0.01, 0.1, 1.0, 10.0])
        self._counts: dict[tuple, list[int]] = {}
        self._sums: dict[tuple, float] = {}
        super().__init__(name, description, tag_keys)

    def _adopt(self, prev: "Histogram") -> None:
        super()._adopt(prev)
        self.boundaries = prev.boundaries   # bucket layout must match
        self._counts = prev._counts
        self._sums = prev._sums

    def observe(self, value: float, tags: dict | None = None) -> None:
        key = _tags_key(self._resolve_tags(tags))
        with _lock:
            counts = self._counts.setdefault(
                key, [0] * (len(self.boundaries) + 1))
            counts[bisect.bisect_left(self.boundaries, value)] += 1
            self._sums[key] = self._sums.get(key, 0.0) + value

    def _rows(self):
        # rendered specially in render_user_metrics
        return []


def _escape(value) -> str:
    """Prometheus label-value escaping: one bad tag must not corrupt
    the whole exposition (the endpoint also serves core metrics)."""
    return str(value).replace("\\", r"\\").replace('"', r"\"") \
        .replace("\n", r"\n")


def _fmt_labels(tags: dict, extra: dict | None = None) -> str:
    merged = {**tags, **(extra or {})}
    if not merged:
        return ""
    return "{" + ",".join(f'{k}="{_escape(v)}"'
                          for k, v in sorted(merged.items())) + "}"


def render_user_metrics() -> list[str]:
    """Prometheus text lines for every registered user metric (the
    exporter appends these after the core gauges)."""
    out: list[str] = []
    with _lock:
        metrics = list(_registry.values())
    for m in metrics:
        full = f"ray_tpu_user_{m.name}"
        out.append(f"# HELP {full} {m.description}")
        out.append(f"# TYPE {full} {m.TYPE}")
        if isinstance(m, Histogram):
            with _lock:
                items = [(dict(k), list(c), m._sums.get(k, 0.0))
                         for k, c in m._counts.items()]
            for tags, counts, total in items:
                cum = 0
                for bound, c in zip(m.boundaries, counts):
                    cum += c
                    out.append(
                        f"{full}_bucket"
                        f"{_fmt_labels(tags, {'le': bound})} {cum}")
                cum += counts[-1]
                out.append(
                    f"{full}_bucket"
                    f"{_fmt_labels(tags, {'le': '+Inf'})} {cum}")
                out.append(f"{full}_sum{_fmt_labels(tags)} {total}")
                out.append(f"{full}_count{_fmt_labels(tags)} {cum}")
        else:
            for _name, tags, value in m._rows():
                out.append(f"{full}{_fmt_labels(tags)} {value}")
    return out


def _reset_registry() -> None:
    """Test helper: drop all registered metrics."""
    with _lock:
        _registry.clear()


__all__ = ["Counter", "Gauge", "Histogram", "render_user_metrics"]
