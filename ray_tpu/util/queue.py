"""Distributed FIFO queue backed by an actor.

Reference parity: ``ray.util.queue.Queue``
(``python/ray/util/queue.py`` — SURVEY.md §2.2 util family; mount
empty): a bounded/unbounded FIFO shared by tasks and actors, with
blocking/non-blocking put/get, batch variants, and Empty/Full
exceptions matching ``queue``'s.
"""

from __future__ import annotations

from queue import Empty, Full  # noqa: F401 — re-exported, stdlib-compatible


def _api():
    import ray_tpu
    return ray_tpu


class _QueueActor:
    """The queue's state lives in one actor; blocking semantics come
    from the actor being ASYNC (waiters yield the event loop instead
    of wedging the replica)."""

    def __init__(self, maxsize: int):
        import asyncio
        self._q = asyncio.Queue(maxsize=maxsize)

    async def put(self, item, timeout=None):
        import asyncio
        if timeout is None:
            await self._q.put(item)
            return True
        try:
            await asyncio.wait_for(self._q.put(item), timeout)
            return True
        except asyncio.TimeoutError:
            return False

    def put_nowait(self, item):
        try:
            self._q.put_nowait(item)
            return True
        except Exception:   # asyncio.QueueFull
            return False

    async def get(self, timeout=None):
        import asyncio
        if timeout is None:
            return True, await self._q.get()
        try:
            return True, await asyncio.wait_for(self._q.get(), timeout)
        except asyncio.TimeoutError:
            return False, None

    def get_nowait(self):
        try:
            return True, self._q.get_nowait()
        except Exception:   # asyncio.QueueEmpty
            return False, None

    def put_nowait_batch(self, items) -> bool:
        """ATOMIC: all items insert or none do (size-checked first)."""
        if self._q.maxsize and \
                self._q.qsize() + len(items) > self._q.maxsize:
            return False
        for it in items:
            self._q.put_nowait(it)
        return True

    def get_nowait_batch(self, n: int):
        """ATOMIC: returns n items or None without consuming any."""
        if self._q.qsize() < n:
            return None
        return [self._q.get_nowait() for _ in range(n)]

    def qsize(self) -> int:
        return self._q.qsize()

    def empty(self) -> bool:
        return self._q.empty()

    def full(self) -> bool:
        return self._q.full()


class Queue:
    """Shareable FIFO: pass the Queue object into tasks/actors (it
    serializes to its actor handle)."""

    def __init__(self, maxsize: int = 0, *, actor_options: dict | None
                 = None, _actor=None):
        if _actor is not None:
            self._actor = _actor
            return
        ray = _api()
        cls = ray.remote(_QueueActor)
        if actor_options:
            cls = cls.options(**actor_options)
        self._actor = cls.remote(maxsize)

    # -- producer ------------------------------------------------------------
    def put(self, item, block: bool = True,
            timeout: float | None = None) -> None:
        ray = _api()
        if not block:
            if not ray.get(self._actor.put_nowait.remote(item),
                           timeout=30):
                raise Full
            return
        ok = ray.get(self._actor.put.remote(item, timeout),
                     timeout=None if timeout is None else timeout + 30)
        if not ok:
            raise Full

    def put_nowait(self, item) -> None:
        self.put(item, block=False)

    def put_nowait_batch(self, items) -> None:
        """All-or-nothing (the actor size-checks before inserting)."""
        items = list(items)
        if not items:
            return
        if not _api().get(self._actor.put_nowait_batch.remote(items),
                          timeout=30):
            raise Full

    # -- consumer ------------------------------------------------------------
    def get(self, block: bool = True, timeout: float | None = None):
        ray = _api()
        if not block:
            ok, item = ray.get(self._actor.get_nowait.remote(),
                               timeout=30)
            if not ok:
                raise Empty
            return item
        ok, item = ray.get(
            self._actor.get.remote(timeout),
            timeout=None if timeout is None else timeout + 30)
        if not ok:
            raise Empty
        return item

    def get_nowait(self):
        return self.get(block=False)

    def get_nowait_batch(self, num_items: int) -> list:
        """All-or-nothing: raises Empty (consuming NOTHING) when fewer
        than ``num_items`` are queued."""
        if num_items <= 0:
            return []
        out = _api().get(
            self._actor.get_nowait_batch.remote(num_items), timeout=30)
        if out is None:
            raise Empty
        return out

    # -- introspection -------------------------------------------------------
    def qsize(self) -> int:
        return _api().get(self._actor.qsize.remote(), timeout=30)

    def empty(self) -> bool:
        return _api().get(self._actor.empty.remote(), timeout=30)

    def full(self) -> bool:
        return _api().get(self._actor.full.remote(), timeout=30)

    def shutdown(self) -> None:
        _api().kill(self._actor)

    @classmethod
    def _from_handle(cls, actor) -> "Queue":
        return cls(_actor=actor)

    def __reduce__(self):
        # serialize to the ACTOR HANDLE only — reconstructing through
        # __init__ would spawn a fresh (leaked) queue actor
        return (Queue._from_handle, (self._actor,))
