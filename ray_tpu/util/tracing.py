"""Trace-context propagation + trace reconstruction.

Reference parity: ``python/ray/util/tracing/`` — OpenTelemetry spans
behind ``RAY_TRACING_ENABLED``, with trace context carried inside task
specs so a request's task tree links up across workers (SURVEY.md
§5.1; mount empty).

Here the context is ``(trace_id, parent_span_id)``: the driver mints a
trace id per root submission, every task's span id is its task id, and
nested submissions inherit the executing task's span as parent.  Spans
land in the cluster timeline (``runtime/events.py``) tagged with both
ids; ``get_trace`` rebuilds the tree.
"""

from __future__ import annotations

import os
import threading

_local = threading.local()      # driver-side ambient context


def enabled() -> bool:
    """Tracing needs BOTH knobs: spans land in the event log, so with
    ``event_log_enabled`` off they could never be recorded — better a
    consistent no-op than specs stamped with contexts nobody stores."""
    from ..common.config import get_config
    cfg = get_config()
    return bool(cfg.tracing_enabled and cfg.event_log_enabled)


def current_context() -> tuple | None:
    """(trace_id, span_id) of the active scope, or None."""
    return getattr(_local, "ctx", None)


def context_for_new_task(task_id) -> tuple | None:
    """The trace_ctx for a spec being submitted from THIS scope.

    An ambient scope always propagates (workers inherit it from the
    exec frame and do NOT share the driver's config, so the flag is
    only consulted at the ROOT); with no ambient scope, a fresh trace
    starts when tracing is enabled."""
    ambient = current_context()
    if ambient is not None:
        return (ambient[0], ambient[1])
    if not enabled():
        return None
    return (os.urandom(8).hex(), "driver")


class span_scope:       # noqa: N801 — context-manager idiom
    """Make ``(trace_id, span_id)`` the ambient scope (worker exec
    loops enter this around task execution; drivers may use it to group
    submissions under one trace)."""

    def __init__(self, trace_id: str, span_id: str):
        self._ctx = (trace_id, span_id)
        self._prev = None

    def __enter__(self):
        self._prev = getattr(_local, "ctx", None)
        _local.ctx = self._ctx
        return self

    def __exit__(self, *exc):
        _local.ctx = self._prev
        return False


def get_trace(trace_id: str) -> list[dict]:
    """All spans of one trace from the driver's timeline, each with
    ``span_id``/``parent_id``, sorted by start time."""
    from ..api import _get_runtime
    rt = _get_runtime()
    if not hasattr(rt, "cluster"):
        raise RuntimeError("get_trace is driver-only")
    by_span: dict[str, dict] = {}
    for ev in rt.cluster.events.timeline():
        args = ev.get("args") or {}
        if args.get("trace_id") != trace_id:
            continue
        span = {"name": ev.get("name"),
                "start_us": ev.get("ts"),
                "duration_us": ev.get("dur"),
                "span_id": args.get("span_id"),
                "parent_id": args.get("parent_id")}
        prev = by_span.get(span["span_id"])
        # lineage reconstruction re-executes a spec under the SAME span
        # id: keep the latest attempt only, or the tree would duplicate
        # the re-executed subtree once per attempt
        if prev is None or (span["start_us"] or 0) > \
                (prev["start_us"] or 0):
            by_span[span["span_id"]] = span
    spans = sorted(by_span.values(), key=lambda s: s["start_us"] or 0)
    return spans


def trace_tree(trace_id: str) -> dict:
    """Spans nested parent->children.  Roots are spans whose parent has
    no span in this trace — the synthetic ``"driver"`` parent, custom
    ``span_scope`` roots, and orphans whose parent span is missing
    (still running, or evicted from the timeline ring) all surface
    instead of silently disappearing."""
    spans = get_trace(trace_id)
    span_ids = {s["span_id"] for s in spans}
    children: dict[str, list] = {}
    roots: list[dict] = []
    for s in spans:
        if s["parent_id"] in span_ids:
            children.setdefault(s["parent_id"], []).append(s)
        else:
            roots.append(s)

    def build(s: dict) -> dict:
        return dict(s, children=[build(c)
                                 for c in children.get(s["span_id"], ())])

    return {"trace_id": trace_id, "roots": [build(s) for s in roots]}
