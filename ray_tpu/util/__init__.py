"""ray_tpu.util — utility namespace.

Reference parity: upstream ``ray.util`` hosts ``placement_group``, the
state API, and user metrics (``python/ray/util/`` — SURVEY.md §1 layers
9/12; mount empty).  Populated incrementally; importing the package must
always succeed because ``ray_tpu.__getattr__`` resolves ``ray_tpu.util``
lazily.
"""

from __future__ import annotations

from .actor_pool import ActorPool
from .placement_group import (PlacementGroup, placement_group,
                              placement_group_table,
                              remove_placement_group)
from .scheduling_strategies import (NodeAffinitySchedulingStrategy,
                                    NodeLabelSchedulingStrategy,
                                    PlacementGroupSchedulingStrategy)

__all__ = ["ActorPool", "PlacementGroup", "placement_group",
           "placement_group_table", "remove_placement_group",
           "PlacementGroupSchedulingStrategy",
           "NodeAffinitySchedulingStrategy",
           "NodeLabelSchedulingStrategy"]
