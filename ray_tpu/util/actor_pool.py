"""ActorPool: spread work over a fixed set of actor handles.

Reference parity: ``ray.util.ActorPool``
(``python/ray/util/actor_pool.py`` — SURVEY.md §2.2 util family;
mount empty): submit ``fn(actor, value)`` pairs, collect results in
submission order (``get_next``) or completion order
(``get_next_unordered``); ``map``/``map_unordered`` batch the pattern;
idle actors are reusable across rounds and can be pushed/popped.
"""

from __future__ import annotations


def _api():
    import ray_tpu
    return ray_tpu


class ActorPool:
    def __init__(self, actors):
        self._idle = list(actors)
        self._future_to_actor: dict = {}    # ref key -> (index, actor)
        self._index_to_future: dict = {}    # submit index -> ref
        self._next_task_index = 0
        self._next_return_index = 0         # ordered get cursor
        self._pending_submits: list = []    # (fn, value) awaiting actor

    # -- submission ----------------------------------------------------------
    def submit(self, fn, value) -> None:
        """Schedule ``fn(actor, value)`` on an idle actor; queued until
        one frees otherwise."""
        if self._idle:
            actor = self._idle.pop()
            ref = fn(actor, value)
            self._future_to_actor[ref.binary()] = (
                self._next_task_index, actor)
            self._index_to_future[self._next_task_index] = ref
            self._next_task_index += 1
        else:
            self._pending_submits.append((fn, value))

    def has_next(self) -> bool:
        return bool(self._index_to_future) or bool(self._pending_submits)

    def has_free(self) -> bool:
        return bool(self._idle) and not self._pending_submits

    # -- collection ----------------------------------------------------------
    def get_next(self, timeout: float | None = None):
        """Next result in SUBMISSION order.  A timeout raises
        TimeoutError WITHOUT consuming anything (retryable: wait
        first, consume after).  The actor returns to the pool before
        the final get, so a task exception never leaks it (actors
        serialize their calls — an early re-submit just queues)."""
        if not self.has_next():
            raise StopIteration("no pending results")
        ref = self._index_to_future.get(self._next_return_index)
        if ref is None:
            raise RuntimeError(
                "submissions are queued but the pool has no actors "
                "to run them (all popped?)")
        ready, _ = _api().wait([ref], num_returns=1, timeout=timeout)
        if not ready:
            raise TimeoutError("no result within timeout")
        del self._index_to_future[self._next_return_index]
        self._next_return_index += 1
        self._return_actor(ref)
        return _api().get(ref)

    def get_next_unordered(self, timeout: float | None = None):
        """Next result in COMPLETION order."""
        if not self.has_next():
            raise StopIteration("no pending results")
        refs = list(self._index_to_future.values())
        if not refs:
            raise RuntimeError(
                "submissions are queued but the pool has no actors "
                "to run them (all popped?)")
        ready, _ = _api().wait(refs, num_returns=1, timeout=timeout)
        if not ready:
            raise TimeoutError("no result within timeout")
        ref = ready[0]
        for idx, f in list(self._index_to_future.items()):
            if f.binary() == ref.binary():
                del self._index_to_future[idx]
                break
        self._return_actor(ref)
        return _api().get(ref)

    def _return_actor(self, ref) -> None:
        _idx, actor = self._future_to_actor.pop(ref.binary())
        self._idle.append(actor)
        self._drain_pending()

    def _drain_pending(self) -> None:
        while self._pending_submits and self._idle:
            fn, value = self._pending_submits.pop(0)
            self.submit(fn, value)

    # -- batch helpers -------------------------------------------------------
    def map(self, fn, values):
        """Results in submission order (lazy iterator)."""
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next()

    def map_unordered(self, fn, values):
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next_unordered()

    # -- pool membership -----------------------------------------------------
    def push(self, actor) -> None:
        self._idle.append(actor)
        self._drain_pending()

    def pop_idle(self):
        return self._idle.pop() if self._idle else None
