"""Client mode: attach a driver to a running head daemon over RPC.

Reference parity: ``ray.init("ray://host:port")`` — the ray client
(``python/ray/util/client/``) proxies the full task/actor/object API
through a gRPC server colocated with the cluster (SURVEY.md §2.2; mount
empty).  Here the proxy speaks ``ray_tpu.rpc`` to ``runtime/head.py``.

The ClientRuntime presents the WORKER-context surface (``is_driver``
False): ``RemoteFunction.remote``/``ActorClass.remote`` take their
non-driver path, deriving task ids from a synthetic driver task id under
the server-assigned job id.  The client is a refcount HOLDER: its local
ObjectRef events batch to the head (``refs_flush``, piggybacked on the
next RPC) and fold under ``("c", job_id)``; disconnect — graceful
``client_bye`` or an abrupt connection drop — retires every count it
held, so two concurrent drivers on one head have disjoint object
lifetimes (reference: per-process ownership, SURVEY.md §1 layer 7).
"""

from __future__ import annotations

import threading

from ..common.ids import ActorID, ObjectID, TaskID
from ..runtime.object_ref import ObjectRef
from ..runtime.serialization import deserialize, serialize


class _RemoteFnRegistry:
    """Dict-shaped shim over the head's function table: eager stub
    registration in ``RemoteFunction.__reduce__`` works unchanged."""

    def __init__(self, client: "ClientRuntime"):
        self._client = client
        self._known: set[str] = set()   # avoid re-shipping bytes

    def setdefault(self, fn_id: str, fn_bytes: bytes | None):
        if fn_bytes is not None and fn_id not in self._known:
            self._client._call("fn_register", fn_id, fn_bytes)
            self._known.add(fn_id)
        return fn_bytes

    def __contains__(self, fn_id: str) -> bool:
        return fn_id in self._known


class ClientRuntime:
    is_driver = False

    def __init__(self, address: str, runtime_env: dict | None = None,
                 namespace: str | None = None):
        from ..rpc import transport as _transport
        self.address = address
        self.namespace = namespace or ""
        # idempotent head READS transparently retry on timeout/conn
        # loss (backoff + full jitter); mutations (submit/put/create)
        # never do — re-issuing those would double-execute
        self._rpc = _transport.connect(address, retryable=frozenset({
            "ping", "status", "nodes", "available_resources",
            "cluster_resources", "list_named_actors",
            "get_actor_by_name", "job_status", "job_list", "job_logs",
            "state_list", "timeline", "memory",
        }))
        self._lock = threading.Lock()
        # this process's share of distributed refcounting: ObjectRefs
        # built here count locally; batches ship ahead of the next RPC
        # (constructed BEFORE the first _call — it flushes through this)
        from ..runtime.object_ref import install_counter_if_absent
        from ..runtime.worker import WorkerRefCounter
        self.ref_counter = WorkerRefCounter()
        self._refs_lock = threading.Lock()
        info = self._call("connect", runtime_env)
        from ..common.ids import JobID
        self.job_id = JobID(info["job_id"])
        self.session_dir = info["session_dir"]
        # non-driver submission paths derive ids from current_task_id
        self.current_task_id = TaskID.for_task(self.job_id)
        self.fn_registry = _RemoteFnRegistry(self)
        # no-op when this process already counts (embedded client in a
        # head/worker process: refs keep their original holder)
        self._counter_installed = \
            install_counter_if_absent(self.ref_counter)

    def _call(self, method: str, *args, **kwargs):
        self._flush_refs()
        return self._rpc.call(method, *args, **kwargs)

    def _flush_refs(self) -> None:
        # one flusher at a time: interleaved drains could split a +/-
        # pair across two batches whose handler threads race server-side
        # (the synchronous call also serializes batch arrival order)
        with self._refs_lock:
            events = self.ref_counter.drain()
            if events:
                try:
                    self._rpc.call("refs_flush", self.job_id.binary(),
                                   events)
                except Exception:   # noqa: BLE001 — conn gone: the
                    pass            # server's close hook retires us

    # -- core API (the surface api.py/actor_api.py dispatch to) --------------
    def submit_spec(self, spec, fn_id: str, fn_bytes: bytes | None) -> None:
        from ..runtime.object_ref import (mark_transferred,
                                          transfer_generators)
        with transfer_generators() as gens:
            payload = serialize(spec)
        self._call("submit_spec", payload, fn_id, fn_bytes,
                   self.job_id.binary())
        mark_transferred(gens)

    def get(self, refs: list[ObjectRef], timeout: float | None = None):
        kind, payload = self._call(
            "get", [r.binary() for r in refs], timeout,
            timeout=None if timeout is None else timeout + 30.0)
        result = deserialize(payload)
        if kind == "exc":
            raise result
        return result

    def put(self, value) -> ObjectRef:
        from ..runtime.object_ref import serialize_collecting
        data, contained = serialize_collecting(value)
        oid_bin = self._call("put", data, self.job_id.binary(),
                             contained)
        return ObjectRef(ObjectID(oid_bin))

    def wait(self, refs, num_returns, timeout):
        ready_bins, not_ready_bins = self._call(
            "wait", [r.binary() for r in refs], num_returns, timeout,
            timeout=None if timeout is None else timeout + 30.0)
        by_id = {r.binary(): r for r in refs}
        return ([by_id[b] for b in ready_bins],
                [by_id[b] for b in not_ready_bins])

    def create_actor(self, actor_id, cls_id, cls_bytes, args, kwargs,
                     max_restarts, max_task_retries, name,
                     resources=None, strategy=None,
                     runtime_env=None, concurrency=None,
                     namespace="", lifetime=None) -> None:
        self._call("create_actor", actor_id.binary(), cls_id, cls_bytes,
                   serialize((args, kwargs, max_restarts,
                              max_task_retries, name, resources,
                              strategy, runtime_env, concurrency,
                              namespace, lifetime)))

    def submit_actor_call(self, actor_id, task_id, method: str, args,
                          kwargs, num_returns: int,
                          trace_ctx: tuple | None = None,
                          concurrency_group: str | None = None) -> None:
        from ..runtime.object_ref import (mark_transferred,
                                          transfer_generators)
        with transfer_generators() as gens:
            payload = serialize((args, kwargs, trace_ctx,
                                 concurrency_group))
        self._call("submit_actor_call", actor_id.binary(),
                   task_id.binary(), method, payload, num_returns)
        mark_transferred(gens)

    def stream_wait(self, task_id, index: int,
                    timeout: float | None = None):
        # bounded server-side waits so one stream doesn't pin an RPC
        # worker thread forever; loop client-side for timeout=None
        while True:
            server_wait = 30.0 if timeout is None else timeout
            reply = self._call(
                "stream_wait", task_id.binary(), index, server_wait,
                timeout=server_wait + 30.0)
            sealed, done, err_bytes = reply[0], reply[1], reply[2]
            known = reply[3] if len(reply) > 3 else True
            err = deserialize(err_bytes) if err_bytes else None
            if sealed > index or done or timeout is not None:
                return sealed, done, err, known

    def stream_ack(self, task_id, consumed: int) -> None:
        self._call("stream_ack", task_id.binary(), consumed)

    def stream_close(self, task_id, consumed: int) -> None:
        self._call("stream_close", task_id.binary(), consumed)

    def kill_actor(self, actor_id, no_restart: bool = True) -> None:
        self._call("kill_actor", actor_id.binary(), no_restart)

    def get_actor_id_by_name(self, name: str,
                             namespace: str = "") -> bytes | None:
        return self._call("get_actor_by_name", name, namespace)

    def cancel_task(self, task_id, force: bool = False) -> None:
        self._call("cancel", task_id.binary(), force)

    def kv_op(self, op: str, key: bytes, value: bytes | None = None,
              namespace: str = "", overwrite: bool = True):
        """internal_kv from a client driver (same surface as workers)."""
        return self._call("kv", op, key, value, namespace, overwrite)

    # -- introspection (api module functions duck-type onto these) -----------
    def request_resources(self, bundles: list[dict]) -> None:
        self._call("request_resources", bundles)

    def list_named_actors(self, all_namespaces: bool = False,
                          namespace: str = "") -> list:
        # the CALLER's namespace rides along: the head must filter by
        # it, not by its own driver's
        return self._call("list_named_actors", all_namespaces,
                          namespace)

    def worker_stacks(self, node_row: int | None = None,
                      timeout: float = 5.0) -> dict:
        return self._call("worker_stacks", node_row, timeout,
                          timeout=timeout + 30.0)

    def nodes(self) -> list[dict]:
        return self._call("nodes")

    def drain_node(self, node_id_hex: str, reason: str = "",
                   deadline_s: float | None = None) -> dict:
        return self._call("drain_node", node_id_hex, reason,
                          deadline_s, timeout=30.0)

    def available_resources(self) -> dict:
        return self._call("available_resources")

    def cluster_resources(self) -> dict:
        return self._call("cluster_resources")

    def timeline(self) -> list[dict]:
        return self._call("timeline")

    def status(self) -> dict:
        return self._call("status")

    def close(self) -> None:
        from ..runtime.object_ref import uninstall_counter
        self._flush_refs()
        try:
            self._rpc.call("client_bye", self.job_id.binary(),
                           timeout=5.0)
        except Exception:       # noqa: BLE001 — head already gone; its
            pass                # conn-close hook retires this holder
        if self._counter_installed:
            uninstall_counter(self.ref_counter)
        self._rpc.close()


def get_head_actor_id(client: ClientRuntime, name: str):
    raw = client.get_actor_id_by_name(name)
    return ActorID(raw) if raw else None
