"""Collective communication — ``ray.util.collective`` rebuilt TPU-first.

Reference parity: ``python/ray/util/collective/`` — named groups created
with ``init_collective_group(world_size, rank, backend, group_name)``,
then ``allreduce/allgather/reducescatter/broadcast/barrier/send/recv``;
NCCL backend for device tensors, Gloo for host tensors (SURVEY.md §1
layer 13; mount empty).

Two backends, both real:

- **Device mesh (the NCCL analogue, TPU-first)**: collectives over this
  host's accelerator devices as ONE compiled XLA program —
  ``shard_map`` over a ``jax.sharding.Mesh`` with ``lax.psum`` /
  ``all_gather`` / ``psum_scatter`` / ``ppermute``, so the transfers
  ride ICI and fuse with surrounding compute instead of translating
  NCCL ringcalls.  ``DeviceCollectiveGroup`` below.

- **Process group (the Gloo analogue)**: named groups spanning worker
  processes/actors/driver, rendezvoused through the GCS KV store; every
  collective is a full barrier, and a two-phase-lagged garbage sweep
  (rank 0 deletes round ``s`` keys at round ``s+2`` — by the time any
  rank reaches ``s+2`` every rank has finished reading ``s``) keeps KV
  memory bounded.  ``init_collective_group`` + module-level ops below.
"""

from __future__ import annotations

import time
from functools import partial

import numpy as np

from ..common.config import get_config
from ..runtime.serialization import deserialize, serialize

_NAMESPACE = "collective"


class GangMemberLost(TimeoutError):
    """A collective round timed out with specific ranks missing — the
    signature of a gang peer SIGKILLed between barrier and reduce.
    Subclasses TimeoutError so pre-existing deadline handling still
    catches it; carries the group/round/ranks so an elastic trainer can
    convert it into a planned gang re-form instead of a failure."""

    def __init__(self, group: str, seq: int, missing, timeout: float):
        self.group = group
        self.seq = int(seq)
        self.missing_ranks = sorted(int(r) for r in missing)
        self.timeout_s = float(timeout)
        super().__init__(
            f"collective {group} round {seq}: ranks "
            f"{self.missing_ranks} missing after {timeout}s "
            f"(gang member lost)")

    def __reduce__(self):
        # Exception's default reduce replays the formatted message into
        # the 4-arg __init__; rebuild from the typed fields instead so
        # the error survives the task-result pickle round-trip
        return (GangMemberLost, (self.group, self.seq,
                                 self.missing_ranks, self.timeout_s))


def _resolve_timeout(timeout: float | None) -> float:
    """Per-call override, else the W3-wired collective_timeout_s knob."""
    if timeout is not None:
        return float(timeout)
    return float(get_config().collective_timeout_s)


_REDUCERS = {
    "sum": lambda arrs: np.sum(arrs, axis=0),
    "prod": lambda arrs: np.prod(arrs, axis=0),
    "max": lambda arrs: np.max(arrs, axis=0),
    "min": lambda arrs: np.min(arrs, axis=0),
}


# ---------------------------------------------------------------------------
# device-mesh backend (NCCL analogue; XLA collectives over ICI)
# ---------------------------------------------------------------------------

class DeviceCollectiveGroup:
    """Collectives across this host's devices as one jitted XLA program.

    Input arrays carry a leading ``world_size`` axis (one slice per
    device rank); outputs keep that axis, matching the per-rank view of
    the reference API.
    """

    def __init__(self, devices=None):
        import jax
        from jax.sharding import Mesh
        self.devices = list(devices) if devices is not None \
            else list(jax.devices())
        self.world_size = len(self.devices)
        self._mesh = Mesh(np.array(self.devices), ("ranks",))
        self._cache: dict = {}

    def _sharded(self, fn, key):
        import jax
        from jax.sharding import PartitionSpec as P

        from .jax_compat import shard_map_compat
        cached = self._cache.get(key)
        if cached is None:
            cached = jax.jit(shard_map_compat(check=True)(
                fn, mesh=self._mesh, in_specs=P("ranks"),
                out_specs=P("ranks")))
            self._cache[key] = cached
        return cached

    def allreduce(self, stacked, op: str = "sum"):
        """(W, ...) -> (W, ...): every rank's slice becomes the
        reduction over all ranks (lax.psum/pmax/pmin over ICI; prod has
        no XLA primitive and lowers to all_gather + local reduce)."""
        import jax
        import jax.numpy as jnp
        if op == "prod":
            def f(x):
                return jnp.prod(jax.lax.all_gather(x, "ranks"), axis=0)
            return self._sharded(f, ("allreduce", op))(stacked)
        try:
            red = {"sum": partial(jax.lax.psum, axis_name="ranks"),
                   "max": partial(jax.lax.pmax, axis_name="ranks"),
                   "min": partial(jax.lax.pmin, axis_name="ranks")}[op]
        except KeyError:
            raise ValueError(f"unsupported allreduce op {op!r}") from None
        return self._sharded(lambda x: red(x), ("allreduce", op))(stacked)

    def allgather(self, stacked):
        """(W, ...) -> (W, W, ...): every rank sees every slice."""
        import jax

        def f(x):
            return jax.lax.all_gather(x[0], "ranks")[None]
        return self._sharded(f, ("allgather",))(stacked)

    def reducescatter(self, stacked, op: str = "sum"):
        """(W, W_chunks...) -> (W, chunk): rank r holds the r-th chunk of
        the reduction (sum rides lax.psum_scatter; max/min reduce fully
        then keep the local rank's chunk)."""
        import jax

        if op == "sum":
            def f(x):
                return jax.lax.psum_scatter(
                    x, "ranks", scatter_dimension=1, tiled=False)
        elif op in ("max", "min"):
            red = jax.lax.pmax if op == "max" else jax.lax.pmin
            def f(x):
                full = red(x, "ranks")
                me = jax.lax.axis_index("ranks")
                return jax.lax.dynamic_index_in_dim(
                    full, me, axis=1, keepdims=False)
        else:
            raise ValueError(f"unsupported reducescatter op {op!r}")
        return self._sharded(f, ("reducescatter", op))(stacked)

    def broadcast(self, stacked, src_rank: int = 0):
        """(W, ...) -> (W, ...): every rank gets rank ``src_rank``'s
        slice (masked psum — compiler-friendly one-hot select)."""
        import jax
        import jax.numpy as jnp

        def f(x):
            me = jax.lax.axis_index("ranks")
            contrib = jnp.where(me == src_rank, x, jnp.zeros_like(x))
            return jax.lax.psum(contrib, "ranks")
        return self._sharded(f, ("broadcast", src_rank))(stacked)

    def ring_shift(self, stacked, shift: int = 1):
        """(W, ...) -> (W, ...): rank r gets rank (r-shift)'s slice via
        lax.ppermute — the send/recv ring primitive."""
        import jax

        def f(x):
            perm = [(i, (i + shift) % self.world_size)
                    for i in range(self.world_size)]
            return jax.lax.ppermute(x, "ranks", perm)
        return self._sharded(f, ("ring", shift))(stacked)


# ---------------------------------------------------------------------------
# process-group backend (Gloo analogue; KV rendezvous)
# ---------------------------------------------------------------------------

class _ProcessGroup:
    def __init__(self, group_name: str, world_size: int, rank: int):
        self.name = group_name
        self.world_size = world_size
        self.rank = rank
        self.seq = 0
        self.sid = None         # incarnation id, agreed in _handshake

    # -- kv plumbing ---------------------------------------------------------
    @staticmethod
    def _kv(op, key, value=None):
        from ..experimental.internal_kv import _kv
        return _kv(op, key, value, namespace=_NAMESPACE)

    def _key(self, seq: int, rank: int) -> str:
        return f"{self.name}/{self.sid}/{seq}/{rank}"

    def _handshake(self, timeout: float = 60.0) -> None:
        """Join barrier that also derives a per-incarnation session id:
        every rank posts a fresh random nonce, hashes all ranks' nonces
        into a candidate ``sid``, posts it as an ack, and loops —
        re-reading nonces — until every rank's ack carries the SAME sid.
        Round/p2p keys live under the sid, so keys left by a PREVIOUS
        incarnation of the same group name (crashed rank retried,
        destroy + re-init) can never be read as this incarnation's data.
        A rank that initially mixes a stale join nonce into its
        candidate sees the ack mismatch and re-reads until the fresh
        nonce lands (convergent: nonces stop changing once every member
        has posted); joining against a generation that will never
        re-ack — half of a dead group — raises TimeoutError instead of
        producing a silently wrong reduction."""
        import hashlib
        from ..common.ids import fast_random_bytes
        nonce = fast_random_bytes(8).hex().encode()
        self._kv("put", f"{self.name}/join/{self.rank}", nonce)
        deadline = time.monotonic() + timeout
        while True:
            nonces = []
            for r in range(self.world_size):
                v = nonce if r == self.rank else \
                    self._kv("get", f"{self.name}/join/{r}")
                if v is None:
                    break               # peer not joined yet
                nonces.append(v)
            if len(nonces) == self.world_size:
                sid = hashlib.sha256(b"|".join(nonces)).hexdigest()[:12]
                self._kv("put", f"{self.name}/ack/{self.rank}",
                         sid.encode())
                if all(self._kv("get", f"{self.name}/ack/{r}")
                       == sid.encode() for r in range(self.world_size)):
                    self.sid = sid
                    return
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"collective group {self.name}: handshake did not "
                    f"converge within {timeout}s")
            time.sleep(0.002)

    def _post(self, seq: int, payload: bytes) -> None:
        self._kv("put", self._key(seq, self.rank), payload)

    def _collect(self, seq: int, timeout: float) -> list[bytes]:
        """All ranks' round-``seq`` payloads (poll until complete).
        Ranks still missing at the deadline raise the typed
        :class:`GangMemberLost` — without the bound, one SIGKILLed peer
        parks every surviving rank here forever."""
        deadline = time.monotonic() + timeout
        out: list = [None] * self.world_size
        missing = set(range(self.world_size))
        while missing:
            # sorted: rank polling order drives per-link chaos draws,
            # so it must not depend on set memory layout
            for r in sorted(missing):
                v = self._kv("get", self._key(seq, r))
                if v is not None:
                    out[r] = v
                    missing.discard(r)
            if not missing:
                break
            if time.monotonic() > deadline:
                raise GangMemberLost(self.name, seq, missing, timeout)
            time.sleep(0.002)
        return out

    def _sweep(self) -> None:
        """Two-phase-lagged GC: by the time this rank runs round s, every
        rank has finished READING round s-2 (each round is a full
        barrier), so rank 0 deletes those keys."""
        if self.rank == 0 and self.seq >= 2:
            for r in range(self.world_size):
                self._kv("del", self._key(self.seq - 2, r))

    def _round(self, payload: bytes,
               timeout: float | None) -> list[bytes]:
        timeout = _resolve_timeout(timeout)
        self._sweep()
        seq = self.seq
        self.seq += 1
        self._post(seq, payload)
        return self._collect(seq, timeout)

    # -- ops -----------------------------------------------------------------
    def allreduce(self, array, op: str = "sum",
                  timeout: float | None = None):
        arrs = [deserialize(p) for p in
                self._round(serialize(np.asarray(array)), timeout)]
        return _REDUCERS[op](arrs)

    def allgather(self, array,
                  timeout: float | None = None) -> list:
        return [deserialize(p) for p in
                self._round(serialize(np.asarray(array)), timeout)]

    def reducescatter(self, array, op: str = "sum",
                      timeout: float | None = None):
        """Each rank returns its chunk of the elementwise reduction
        (arrays split on axis 0 into world_size chunks)."""
        full = _REDUCERS[op]([deserialize(p) for p in
                              self._round(serialize(np.asarray(array)),
                                          timeout)])
        return np.array_split(full, self.world_size)[self.rank]

    def broadcast(self, array, src_rank: int = 0,
                  timeout: float | None = None):
        payloads = self._round(
            serialize(np.asarray(array) if array is not None else None),
            timeout)
        return deserialize(payloads[src_rank])

    def barrier(self, timeout: float | None = None) -> None:
        self._round(serialize(None), timeout)

    def send(self, array, dst_rank: int,
             timeout: float | None = None) -> None:
        key = f"{self.name}/{self.sid}/p2p/{self.rank}->{dst_rank}"
        timeout = _resolve_timeout(timeout)
        deadline = time.monotonic() + timeout
        while self._kv("exists", key):          # previous message unread
            if time.monotonic() > deadline:
                raise TimeoutError(f"send to rank {dst_rank} stalled")
            time.sleep(0.002)
        self._kv("put", key, serialize(np.asarray(array)))

    def recv(self, src_rank: int, timeout: float | None = None):
        key = f"{self.name}/{self.sid}/p2p/{src_rank}->{self.rank}"
        timeout = _resolve_timeout(timeout)
        deadline = time.monotonic() + timeout
        while True:
            v = self._kv("get", key)
            if v is not None:
                self._kv("del", key)
                return deserialize(v)
            if time.monotonic() > deadline:
                raise TimeoutError(f"recv from rank {src_rank} timed out")
            time.sleep(0.002)


_groups: dict[str, _ProcessGroup] = {}


def init_collective_group(world_size: int, rank: int,
                          group_name: str = "default") -> None:
    """Join a named group from this process (driver, task, or actor).
    Blocks until all ranks joined (reference: group handshake)."""
    if not 0 <= rank < world_size:
        raise ValueError(f"rank {rank} out of range for world {world_size}")
    g = _ProcessGroup(group_name, world_size, rank)
    g._handshake()      # join barrier + per-incarnation key namespace
    _groups[group_name] = g


def _group(group_name: str) -> _ProcessGroup:
    g = _groups.get(group_name)
    if g is None:
        raise ValueError(f"collective group {group_name!r} is not "
                         "initialized in this process")
    return g


def allreduce(array, op: str = "sum", group_name: str = "default",
              timeout: float | None = None):
    return _group(group_name).allreduce(array, op, timeout=timeout)


def allgather(array, group_name: str = "default",
              timeout: float | None = None) -> list:
    return _group(group_name).allgather(array, timeout=timeout)


def reducescatter(array, op: str = "sum", group_name: str = "default",
                  timeout: float | None = None):
    return _group(group_name).reducescatter(array, op, timeout=timeout)


def broadcast(array, src_rank: int = 0, group_name: str = "default",
              timeout: float | None = None):
    return _group(group_name).broadcast(array, src_rank, timeout=timeout)


def barrier(group_name: str = "default",
            timeout: float | None = None) -> None:
    _group(group_name).barrier(timeout=timeout)


def send(array, dst_rank: int, group_name: str = "default",
         timeout: float | None = None) -> None:
    _group(group_name).send(array, dst_rank, timeout=timeout)


def recv(src_rank: int, group_name: str = "default",
         timeout: float | None = None):
    return _group(group_name).recv(src_rank, timeout=timeout)


def destroy_collective_group(group_name: str = "default") -> None:
    """Drop this process's handle on the group.  The last two rounds'
    KV keys are deliberately NOT swept here: the lagged GC only
    guarantees rounds <= seq-2 are fully read, so deleting newer keys
    would race slower ranks still polling them in ``_collect`` (they
    would time out on a collective that actually succeeded).  The
    residue is bounded — at most 2 x world_size keys per destroyed
    group — and dies with the session KV."""
    _groups.pop(group_name, None)


def get_rank(group_name: str = "default") -> int:
    return _group(group_name).rank


def get_collective_group_size(group_name: str = "default") -> int:
    return _group(group_name).world_size
