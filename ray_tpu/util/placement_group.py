"""Public placement-group API — gang scheduling of resource bundles.

Reference parity: ``python/ray/util/placement_group.py`` —
``placement_group(bundles, strategy)`` returning a ``PlacementGroup``
handle with ``.ready()``/``.wait()``, ``remove_placement_group``,
``placement_group_table`` (SURVEY.md §3.5; mount empty).  Creation flows
to the cluster's ``PlacementGroupManager`` (the GcsPlacementGroupManager
analogue): bundle placement by the contract in
``ray_tpu/scheduling/bundles.py`` (device twin ``ops/bundle_kernel.py``),
then 2-phase prepare/commit reservation surfacing shaped
``{res}_group_{i}_{pgid}`` resources that pg-strategy tasks consume.

Tasks/actors join a group via ``.options(placement_group=pg,
placement_group_bundle_index=i)``; their resource demand is rewritten onto
the shaped bundle resources (reference: tasks under a
``PlacementGroupSchedulingStrategy`` request ``CPU_group_...``).
"""

from __future__ import annotations

from ..common.ids import ObjectID, PlacementGroupID, TaskID
from ..runtime.object_ref import ObjectRef
from ..scheduling.bundles import PlacementStrategy

__all__ = ["PlacementGroup", "placement_group", "remove_placement_group",
           "placement_group_table"]


def _ready_oid(pg_id: PlacementGroupID) -> ObjectID:
    """Deterministic ready-marker object id (the manager's formula, so
    worker-created groups can await readiness without a round-trip)."""
    from ..runtime.placement_group_manager import ready_oid_for
    return ready_oid_for(pg_id)


class PlacementGroup:
    """Handle to a (possibly still-pending) placement group."""

    def __init__(self, pg_id: PlacementGroupID,
                 bundles: list[dict[str, float]] | None = None):
        self.id = pg_id
        self.bundle_specs = [dict(b) for b in (bundles or [])]

    def ready(self) -> ObjectRef:
        """ObjectRef resolved when all bundles are reserved (reference:
        ``pg.ready()`` is get-able)."""
        return ObjectRef(_ready_oid(self.id))

    def wait(self, timeout_seconds: float | None = None) -> bool:
        from .. import api
        from ..runtime.serialization import RayError
        ready, _ = api.wait([self.ready()], num_returns=1,
                            timeout=timeout_seconds)
        if not ready:
            return False
        # a group removed while pending seals its marker with an error so
        # waiters wake — that is NOT a ready group
        try:
            api.get(self.ready(), timeout=1)
        except RayError:
            return False
        return True

    @property
    def bundle_count(self) -> int:
        return len(self.bundle_specs)

    def __reduce__(self):
        return (PlacementGroup, (self.id, self.bundle_specs))

    def __repr__(self):
        return f"PlacementGroup({self.id.hex()[:12]}…)"


def _check_bundles(bundles: list[dict[str, float]]) -> None:
    if not bundles:
        raise ValueError("placement group needs at least one bundle")
    for b in bundles:
        if not isinstance(b, dict) or not b:
            raise ValueError(f"invalid bundle {b!r}: must be a non-empty "
                             "dict of resource -> amount")
        if any(v < 0 for v in b.values()):
            raise ValueError(f"invalid bundle {b!r}: negative amount")


def placement_group(bundles: list[dict[str, float]],
                    strategy: str = "PACK",
                    name: str | None = None) -> PlacementGroup:
    """Reserve a gang of resource bundles atomically.

    strategy: PACK | SPREAD | STRICT_PACK | STRICT_SPREAD (reference
    semantics: STRICT_SPREAD <=1 bundle/node, STRICT_PACK all on one).
    Returns immediately; the group may still be pending — ``pg.ready()``.
    """
    from .. import api
    _check_bundles(bundles)
    try:
        strat = PlacementStrategy[strategy]
    except KeyError:
        raise ValueError(
            f"unknown placement strategy {strategy!r}; expected one of "
            f"{[s.name for s in PlacementStrategy]}") from None
    rt = api._get_runtime()
    if rt.is_driver:
        pg_id = PlacementGroupID.of(rt.job_id)
        rt.cluster.pg_manager.create(pg_id, bundles, strat, name=name)
    else:
        cur = rt.current_task_id
        from ..common.ids import JobID
        job_id = cur.job_id() if cur else JobID.from_int(0)
        pg_id = PlacementGroupID.of(job_id)
        rt.create_placement_group(pg_id, bundles, strat.name, name)
    return PlacementGroup(pg_id, bundles)


def remove_placement_group(pg: PlacementGroup) -> None:
    """Release the group's reservations (reference:
    ``remove_placement_group``).  Shaped resources vanish; base resources
    return to their nodes."""
    from .. import api
    rt = api._get_runtime()
    if rt.is_driver:
        rt.cluster.pg_manager.remove(pg.id)
    else:
        rt.remove_placement_group(pg.id)


def placement_group_table() -> dict:
    """State of every placement group (reference: ``placement_group_table``)."""
    from .. import api
    rt = api._get_runtime()
    if not rt.is_driver:
        raise RuntimeError("placement_group_table() is driver-only")
    return rt.cluster.pg_manager.table()
