"""Cross-language export registry.

Reference parity: the reference's multi-language frontends call Python
code by *descriptor* (module/function name), not by pickled closure —
``ray.cross_language`` + the function descriptors in
``src/ray/common/function_descriptor.h`` (SURVEY.md §1 layer 8; mount
empty).  Here Python code opts functions and actor classes into the
cross-language surface by exporting them under a stable name; the
C++ frontend (``cpp/``) invokes them through the head daemon's xlang
gateway (``ray_tpu/rpc/xlang_gateway.py``).

    @ray_tpu.cross_language.export("add")
    @ray_tpu.remote
    def add(a, b):
        return a + b

Exports are process-global (the gateway runs in the head process, where
the driver registers its exports).  Arguments and return values must stay
inside the cross-language value subset enforced by ``rpc/xlang.py``.
"""

from __future__ import annotations

import threading

_lock = threading.Lock()
_exports: dict[str, object] = {}


def export(name: str | None = None):
    """Decorator: register a remote function or actor class for
    cross-language callers.  Accepts a plain function/class too and wraps
    it with ``@ray_tpu.remote`` implicitly."""
    def register(obj, export_name: str):
        from .actor_api import ActorClass
        from .api import RemoteFunction, remote
        if not isinstance(obj, (RemoteFunction, ActorClass)):
            wrapped = remote(obj)
        else:
            wrapped = obj
        with _lock:
            existing = _exports.get(export_name)
            if existing is not None and existing is not wrapped \
                    and not _same_descriptor(existing, wrapped):
                raise ValueError(
                    f"cross-language export {export_name!r} already "
                    "registered")
            _exports[export_name] = wrapped
        return wrapped

    if callable(name):          # bare @export with no arguments
        obj, name = name, None
        resolved = _default_name(obj)
        return register(obj, resolved)

    def deco(obj):
        return register(obj, name or _default_name(obj))
    return deco


def _default_name(obj) -> str:
    inner = getattr(obj, "_fn", None) or getattr(obj, "_cls", None) or obj
    return getattr(inner, "__name__", None) or \
        getattr(obj, "_name", None) or repr(obj)


def _same_descriptor(a, b) -> bool:
    """Re-registration of the SAME underlying function/class is
    idempotent: each decorator pass builds a fresh wrapper, so module
    re-import / notebook re-run would otherwise always collide."""
    def descriptor(obj):
        inner = getattr(obj, "_fn", None) or getattr(obj, "_cls", None)
        if inner is None:
            return None
        qn = getattr(inner, "__qualname__", None)
        if qn is None or "<locals>" in qn or "<lambda>" in qn:
            # factory closures / lambdas share a qualname while being
            # genuinely different functions — keep the strict collision
            # guard for them; only module/class-level names (what a
            # re-import recreates) are idempotent
            return None
        return (getattr(inner, "__module__", None), qn)
    da, db = descriptor(a), descriptor(b)
    return da is not None and da == db


def lookup(name: str):
    """The exported RemoteFunction/ActorClass, or None."""
    with _lock:
        return _exports.get(name)


def exports() -> list[str]:
    with _lock:
        return sorted(_exports)


def clear() -> None:
    """Test hook: drop all exports."""
    with _lock:
        _exports.clear()
