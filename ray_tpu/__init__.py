"""ray_tpu — a TPU-native distributed task/actor framework.

A ground-up rebuild of the capabilities of the reference
(``pschafhalter/ray``, a fork of ``ray-project/ray``): dynamic task graph +
actor runtime, two-level scheduling, placement groups, a shared-memory
object store (native C++ arena, zero-copy worker reads, descriptor pinning,
LRU spill/restore), an inter-node object plane (directory + pull manager
with a device-evaluated bandwidth cost model), owner-side reference
counting with lineage reconstruction, an autoscaler runtime loop,
health-check failure detection, runtime environments, a GCS KV store +
pubsub, collectives (XLA device-mesh + KV-rendezvous process groups), an
RPC control plane with a head daemon / client mode / job submission /
CLI / worker-node agents joining over RPC (``start --address=<head>``),
a C++ client frontend over a cross-language gateway (``cpp/``,
``cross_language.export``), observability (metrics endpoint, dashboard HTTP server, structured
logs, Chrome-trace timeline), and the library family (``data``, ``train``, ``tune``,
``serve``, ``rllib``, ``workflow``) — with the scheduling/packing data
planes evaluated as dense TPU computations (JAX/XLA/Pallas) per
BASELINE.json's north star.  Remaining gaps are tracked in VERDICT.md.

Public API mirrors the reference's (``ray.init/remote/get/put/wait/...``,
SURVEY.md §1 layer 9).
"""

__version__ = "0.1.0"

from .common import (Config, NodeResources, ResourceRequest, get_config)

# The runtime API (init/remote/get/put/...) is imported lazily to keep
# `import ray_tpu` light for scheduler-only users (e.g. the bench harness).
_API_NAMES = ("init", "shutdown", "is_initialized", "remote", "get", "put",
              "wait", "cancel", "kill", "get_actor",
              "available_resources", "cluster_resources", "nodes",
              "drain_node",
              "timeline", "worker_stacks", "get_runtime_context",
              "list_named_actors")


def __getattr__(name):
    if name in _API_NAMES:
        from . import api
        return getattr(api, name)
    if name in ("util", "experimental", "cross_language", "data", "train",
                "tune", "serve", "workflow", "rllib"):
        # NOT `from . import util`: that re-enters __getattr__ via the
        # fromlist hasattr probe before the submodule import finishes.
        # Only submodules that EXIST belong here — forwarding a missing
        # name would turn hasattr()'s AttributeError contract into a
        # ModuleNotFoundError escape.
        import importlib
        return importlib.import_module(f".{name}", __name__)
    raise AttributeError(f"module 'ray_tpu' has no attribute {name!r}")


__all__ = ["Config", "get_config", "NodeResources", "ResourceRequest",
           *_API_NAMES]
