"""Workflow DAG build + durable topological execution.

Step results persist to ``<storage>/<workflow_id>/<step_id>.pkl``
BEFORE any dependent runs; resume replays completion state from disk
and only executes the missing suffix of the DAG (the reference's
storage-backed step checkpointing — ``python/ray/workflow/``; mount
empty).  Step ids are deterministic (function name + DAG position) so a
resumed run lines up with the original's artifacts.
"""

from __future__ import annotations

import json
import os
import pickle
import time
from typing import Any, Callable

_DEFAULT_STORAGE = os.path.expanduser("~/.ray_tpu_workflows")


class StepNode:
    """One DAG node: a function plus args that may be other nodes."""

    def __init__(self, fn: Callable, args: tuple, kwargs: dict,
                 name: str | None = None):
        self.fn = fn
        self.args = args
        self.kwargs = kwargs
        self.name = name or getattr(fn, "__name__", "step")

    def bind(self, *args, **kwargs) -> "StepNode":
        raise TypeError("already bound; bind the decorated function")


class _Step:
    """``@workflow.step``-style wrapper: ``.bind`` builds DAG nodes."""

    def __init__(self, fn: Callable):
        self._fn = fn
        self.__name__ = getattr(fn, "__name__", "step")

    def bind(self, *args, **kwargs) -> StepNode:
        return StepNode(self._fn, args, kwargs)

    def __call__(self, *args, **kwargs):
        return self._fn(*args, **kwargs)


def step(fn: Callable) -> _Step:
    return _Step(fn)


# -- storage -----------------------------------------------------------------

def _wf_dir(workflow_id: str, storage: str | None) -> str:
    return os.path.join(storage or _DEFAULT_STORAGE, workflow_id)


def _meta_path(wf_dir: str) -> str:
    return os.path.join(wf_dir, "workflow.json")


def _write_meta(wf_dir: str, meta: dict) -> None:
    tmp = _meta_path(wf_dir) + ".tmp"
    with open(tmp, "w") as f:
        json.dump(meta, f)
    os.replace(tmp, _meta_path(wf_dir))     # atomic: no torn meta


def _read_meta(wf_dir: str) -> dict | None:
    try:
        with open(_meta_path(wf_dir)) as f:
            return json.load(f)
    except FileNotFoundError:
        return None


def _step_path(wf_dir: str, step_id: str) -> str:
    return os.path.join(wf_dir, f"{step_id}.pkl")


# -- execution ---------------------------------------------------------------

def _assign_ids(node: StepNode) -> dict[int, str]:
    """Deterministic step ids by post-order position (stable across a
    re-run of the same DAG shape, which is what resume requires)."""
    ids: dict[int, str] = {}
    counter = [0]

    def visit(n: Any) -> None:
        if not isinstance(n, StepNode) or id(n) in ids:
            return
        for a in list(n.args) + list(n.kwargs.values()):
            visit(a)
        ids[id(n)] = f"{counter[0]:04d}_{n.name}"
        counter[0] += 1

    visit(node)
    return ids


def _execute(node: StepNode, wf_dir: str, ids: dict[int, str],
             done: dict[str, Any], timeout: float) -> Any:
    """Submit the WHOLE remaining DAG up front (ObjectRefs chain the
    dependencies, so independent branches run concurrently on the
    cluster), then collect + persist step results in id order."""
    import ray_tpu
    refs: dict[str, Any] = {}

    def build(n: Any) -> Any:
        if not isinstance(n, StepNode):
            return n
        step_id = ids[id(n)]
        if step_id in done:
            return done[step_id]        # loaded from storage: by value
        if step_id in refs:
            return refs[step_id]        # shared node submits once
        args = [build(a) for a in n.args]
        kwargs = {k: build(v) for k, v in n.kwargs.items()}
        ref = ray_tpu.remote(n.fn).remote(*args, **kwargs)
        refs[step_id] = ref
        return ref

    build(node)
    # collect in post-order id order: when a mid-DAG step fails, every
    # earlier completed step has already been persisted for resume
    for step_id in sorted(refs):
        result = ray_tpu.get(refs[step_id], timeout=timeout)
        tmp = _step_path(wf_dir, step_id) + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump(result, f)
        os.replace(tmp, _step_path(wf_dir, step_id))    # atomic
        done[step_id] = result
    root_id = ids[id(node)]
    return done[root_id] if isinstance(node, StepNode) else node


def run(node: StepNode, *, workflow_id: str,
        storage: str | None = None, timeout: float = 300.0) -> Any:
    """Execute (or re-execute the missing part of) a workflow."""
    wf_dir = _wf_dir(workflow_id, storage)
    os.makedirs(wf_dir, exist_ok=True)
    ids = _assign_ids(node)
    done: dict[str, Any] = {}
    for step_id in ids.values():        # load completed steps
        try:
            with open(_step_path(wf_dir, step_id), "rb") as f:
                done[step_id] = pickle.load(f)
        except FileNotFoundError:
            pass
    _write_meta(wf_dir, {"workflow_id": workflow_id,
                         "status": "RUNNING",
                         "num_steps": len(ids),
                         "start_time": time.time()})
    try:
        result = _execute(node, wf_dir, ids, done, timeout)
    except BaseException:
        _write_meta(wf_dir, {"workflow_id": workflow_id,
                             "status": "FAILED",
                             "num_steps": len(ids),
                             "completed": sorted(done)})
        raise
    _write_meta(wf_dir, {"workflow_id": workflow_id,
                         "status": "SUCCEEDED",
                         "num_steps": len(ids),
                         "completed": sorted(done),
                         "end_time": time.time()})
    return result


def resume(node: StepNode, *, workflow_id: str,
           storage: str | None = None, timeout: float = 300.0) -> Any:
    """Re-drive a workflow: completed steps load from storage, only the
    missing suffix executes (same entry as ``run`` — named for API
    parity and intent)."""
    return run(node, workflow_id=workflow_id, storage=storage,
               timeout=timeout)


def get_status(workflow_id: str, *, storage: str | None = None) -> str:
    meta = _read_meta(_wf_dir(workflow_id, storage))
    return meta["status"] if meta else "NOT_FOUND"


def get_output(workflow_id: str, *, storage: str | None = None) -> Any:
    """The final step's persisted result (the highest-numbered id)."""
    wf_dir = _wf_dir(workflow_id, storage)
    meta = _read_meta(wf_dir)
    if meta is None or meta.get("status") != "SUCCEEDED":
        raise ValueError(f"workflow {workflow_id!r} has no output "
                         f"(status: {get_status(workflow_id, storage=storage)})")
    last = sorted(meta["completed"])[-1]
    with open(_step_path(wf_dir, last), "rb") as f:
        return pickle.load(f)


def list_all(*, storage: str | None = None) -> list[dict]:
    root = storage or _DEFAULT_STORAGE
    out = []
    try:
        entries = sorted(os.listdir(root))
    except FileNotFoundError:
        return []
    for name in entries:
        meta = _read_meta(os.path.join(root, name))
        if meta:
            out.append(meta)
    return out
