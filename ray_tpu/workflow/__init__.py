"""ray_tpu.workflow — durable DAG execution with resume.

Reference parity: ``ray.workflow`` (``python/ray/workflow/``) — a DAG of
task nodes built with ``.bind()`` runs under a workflow id; every step's
result is persisted to workflow storage before dependents run, so a
crashed/interrupted run resumes from the last completed step instead of
recomputing (``workflow.run/resume/get_status/list_all`` — SURVEY.md §1
layer 14, §5.4; mount empty).
"""

from .execution import (StepNode, get_output, get_status, list_all,
                        resume, run, step)

__all__ = ["StepNode", "get_output", "get_status", "list_all", "resume",
           "run", "step"]
