"""Mesh-sharded delta-scheduling heartbeat engine.

``ShardedDeltaScheduler`` is the ``DeltaScheduler`` with every
node-indexed resident partitioned by rows over the two-level
("dcn", "ici") device mesh (ops/shard_reduce.py): each device holds only
its N/S node rows of the CRM mirror, its N/S key columns of the carried
(C, N) packed-key tensor, and receives only ITS shard's dirty rows per
heartbeat — the host stages per-shard upload buckets and the
double-buffered transfer to each device carries nothing another device
owns.  Global decisions (water-fill levels, the placement argmin) lower
to two-level collectives: psum/pmin over ICI within a slice, then DCN
across slices.  The beat still performs exactly ONE readback — a packed
buffer carrying both the water-fill counts and the per-(class, node)
lease budgets each shard priced from its own rows' post-beat avail
(a node-local map, so sharding it is exact; see
``ShardPlane.fused_beat``).

The aggregate mesh HBM — not one chip — now bounds the (classes x
nodes) problem: per-device resident bytes shrink by ~S, so an S-way
mesh holds a problem ~S larger than the single-chip ceiling (bench.py's
sharded stage records the model).

Counts are bit-identical to the single-device engine and the CPU oracle
at any shard count (tests/test_oracle.py randomized 2/4/8-way churn
parity); ``make_delta_scheduler`` is the dispatch-path factory that
falls back to the plain single-device ``DeltaScheduler`` whenever the
mesh resolves to one chip.
"""

from __future__ import annotations

import time

import numpy as np

from .policy import DeltaScheduler, _bucket


class ShardedDeltaScheduler(DeltaScheduler):
    """DeltaScheduler with node rows sharded over the device mesh.

    Overrides only the device-layout hooks of the base engine: sharded
    placement of the mirror/keys/request plane, per-shard dirty-row and
    override staging, and the fused beat with the two-level ICI/DCN
    argmin reduce.  The sync protocol (epoch journal, dirty-fraction
    fallback, class slot registry, double-buffered staging parity) is
    inherited unchanged — so is the public surface.
    """

    def __init__(self, crm, n_shards: int = 0,
                 reduce_mode: str | None = None):
        import jax

        from ..common.config import get_config
        from ..ops import shard_reduce as sr
        super().__init__(crm)
        cfg = get_config()
        if reduce_mode is None:
            reduce_mode = cfg.scheduler_shard_reduce
        if n_shards <= 0:
            n_shards = cfg.scheduler_shards
        self._n_shards = sr.resolve_shards(n_shards,
                                           len(jax.local_devices()))
        self._reduce_mode = reduce_mode
        self._plane_cache = None
        self.stats["shards"] = self._n_shards

    @property
    def _plane(self):
        if self._plane_cache is None:
            from ..ops import shard_reduce as sr
            self._plane_cache = sr.plane_for(self._n_shards,
                                             self._reduce_mode)
        return self._plane_cache

    # -- device-layout hooks ------------------------------------------------
    def _node_pad(self, n_real: int) -> int:
        # the power-of-2 bucket (floor 64) always divides by the
        # power-of-2 shard count resolve_shards guarantees
        n = _bucket(n_real, 64)
        s = self._plane.n_shards
        if n % s:                                    # defensive only
            n = ((n + s - 1) // s) * s
        return n

    def _n_local(self) -> int:
        return self._n // self._plane.n_shards

    def _put_state(self, ht, ha, hm):
        import jax
        pl = self._plane
        self._totals = jax.device_put(ht, pl.sh_rows)
        self._avail = jax.device_put(ha, pl.sh_rows)
        self._mask = jax.device_put(hm, pl.sh_vec)
        self._ones = jax.device_put(np.ones(hm.shape, bool), pl.sh_vec)

    def _put_reqs(self, hr):
        import jax
        self._reqs = jax.device_put(hr, self._plane.sh_repl)

    def _full_rescore_call(self, thr):
        return self._plane.full_rescore(self._totals, self._avail,
                                        self._mask, self._reqs, thr)

    def _install_classes(self, idx, vecs, thr):
        import jax
        pl = self._plane
        self._reqs, self._keys = pl.apply_dirty_classes(
            self._totals, self._avail, self._mask, self._keys,
            self._reqs, jax.device_put(idx, pl.sh_repl),
            jax.device_put(vecs, pl.sh_repl), thr)

    def _put_extra_mask(self, emp):
        import jax
        return jax.device_put(emp, self._plane.sh_vec)

    def _fused_call(self, slots_p, counts_p, em, ov, thr,
                    require_available):
        import jax
        pl = self._plane
        return pl.fused_beat(
            self._totals, self._avail, self._mask, self._keys,
            self._reqs, jax.device_put(slots_p, pl.sh_repl),
            jax.device_put(counts_p, pl.sh_repl), em, ov[0], ov[1],
            thr, require_available=require_available)

    # -- per-shard staging --------------------------------------------------
    def _shard_buckets(self, rows):
        """Group global dirty rows into per-shard buckets of LOCAL row
        indices, padded to a common power-of-2 width so each device's
        upload is one fixed-shape (B,)/(B, R) block of ITS rows only."""
        s = self._plane.n_shards
        n_l = self._n_local()
        buckets: list[list[int]] = [[] for _ in range(s)]
        for r in rows:
            buckets[r // n_l].append(r)
        b = _bucket(max(max((len(bk) for bk in buckets), default=0), 1))
        return buckets, b, n_l

    def _delta_sync(self, rows, totals, avail, mask, thr):
        import jax
        pl = self._plane
        t0 = time.perf_counter() if self.profile else 0.0
        buckets, b, n_l = self._shard_buckets(rows)
        s = pl.n_shards
        idx = np.full((s * b,), n_l, np.int32)   # local idx; pad dropped
        rt = np.zeros((s * b, self._r), np.int32)
        ra = np.zeros((s * b, self._r), np.int32)
        rm = np.zeros((s * b,), bool)
        for si, bk in enumerate(buckets):
            if not bk:
                continue
            sl = slice(si * b, si * b + len(bk))
            idx[sl] = bk
            idx[sl] -= si * n_l
            rt[sl, :self._r_real] = totals[bk]
            ra[sl, :self._r_real] = avail[bk]
            rm[sl] = mask[bk]
        # double-buffered staging, sharded on the bucket axis: the
        # transfer to each device carries only its own shard's rows
        staged = (jax.device_put(idx, pl.sh_vec),
                  jax.device_put(rt, pl.sh_rows),
                  jax.device_put(ra, pl.sh_rows),
                  jax.device_put(rm, pl.sh_vec))
        self._stage[self._parity] = staged
        self._parity ^= 1
        if self.profile:
            jax.block_until_ready(staged)       # rtlint: disable=W6
            self.phase_ms["h2d"] += (time.perf_counter() - t0) * 1e3
            t0 = time.perf_counter()
        self._totals, self._avail, self._mask, self._keys = \
            pl.apply_dirty_rows(self._totals, self._avail, self._mask,
                                self._keys, self._reqs, *staged, thr)
        if self.profile:
            jax.block_until_ready(self._keys)   # rtlint: disable=W6
            self.phase_ms["score"] += (time.perf_counter() - t0) * 1e3

    def _pack_overrides(self, overrides):
        import jax
        pl = self._plane
        s = pl.n_shards
        n_l = self._n_local()
        if not overrides:
            if self._empty_ov is None:
                idx = np.full((s * 8,), n_l, np.int32)
                av = np.zeros((s * 8, self._r), np.int32)
                self._empty_ov = (jax.device_put(idx, pl.sh_vec),
                                  jax.device_put(av, pl.sh_rows))
            return self._empty_ov
        buckets, b, _ = self._shard_buckets(sorted(overrides))
        idx = np.full((s * b,), n_l, np.int32)
        av = np.zeros((s * b, self._r), np.int32)
        for si, bk in enumerate(buckets):
            for j, row in enumerate(bk):
                vec = overrides[row]
                idx[si * b + j] = row - si * n_l
                w = min(self._r, len(vec))
                av[si * b + j, :w] = vec[:w]
        return (jax.device_put(idx, pl.sh_vec),
                jax.device_put(av, pl.sh_rows))


def make_delta_scheduler(crm, n_shards: int | None = None,
                         reduce_mode: str | None = None):
    """The dispatch-path factory: a ``ShardedDeltaScheduler`` when the
    resolved mesh has more than one chip, the plain single-device
    ``DeltaScheduler`` otherwise (graceful fallback — on one chip there
    is nothing to shard and shard_map only adds dispatch overhead).

    ``n_shards``/``reduce_mode`` default to the ``scheduler_shards`` /
    ``scheduler_shard_reduce`` knobs.
    """
    import jax

    from ..common.config import get_config
    from ..ops.shard_reduce import resolve_shards
    cfg = get_config()
    requested = cfg.scheduler_shards if n_shards is None else n_shards
    mode = cfg.scheduler_shard_reduce if reduce_mode is None \
        else reduce_mode
    s = resolve_shards(requested, len(jax.local_devices()))
    if s <= 1:
        return DeltaScheduler(crm)
    return ShardedDeltaScheduler(crm, s, mode)
