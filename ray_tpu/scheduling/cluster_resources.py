"""Cluster-wide resource view: NodeID <-> dense-row mapping + state arrays.

Reference parity: ``ClusterResourceManager`` keeps an
``absl::flat_hash_map<scheduling::NodeID, Node>`` of ``NodeResources`` and is
the state every ``ISchedulingPolicy`` reads
(``src/ray/raylet/scheduling/cluster_resource_manager.h``); a
``LocalResourceManager`` tracks the owning node's instances
(``local_resource_manager.h``).  [SURVEY.md §1 layer 5 / §2.1; mount empty.]

TPU-first: the hash-map becomes *dense arrays in traversal order* — the form
both the numpy oracle and the HBM-resident device state consume.  Node
addition assigns the next free row; node death frees the row (mask=False) for
reuse so traversal indices stay < MAX_NODES.  Row order IS the contract's
deterministic tie-break order, so row assignment is part of observable
scheduling behavior: rows are assigned in registration order, matching the
reference's local-node-first traversal when the local node registers first.
"""

from __future__ import annotations

import sys
import threading
import time
from collections import deque

import numpy as np

from ..common.ids import NodeID
from ..common.resources import NodeResources, ResourceIndex, ResourceRequest
from .contract import MAX_NODES
from .oracle import ClusterState


# Dirty-row journal depth.  At 8k nodes a full resync uploads every row, so
# once more than this many mutations pile up between two heartbeats the
# delta bookkeeping costs more than it saves — truncate and let the consumer
# fall back to a full upload.
_DIRTY_LOG_CAP = 8192
# Interned dense-request vectors (scheduling classes are few; this cap only
# guards against an adversarial stream of unique requests).
_REQ_CACHE_CAP = 4096


class ClusterResourceManager:
    """Owns the dense cluster state + id mapping. Thread-safe."""

    def __init__(self, num_resource_slots: int = 16,
                 capacity: int = 64):
        self._lock = threading.RLock()
        # waiters parked on capacity (wait_subtract); add_back notifies
        self._freed = threading.Condition(self._lock)
        self.resource_index = ResourceIndex()
        self._r_slots = max(num_resource_slots,
                            self.resource_index.num_resources)
        self._capacity = min(capacity, MAX_NODES)
        self.totals = np.zeros((self._capacity, self._r_slots), dtype=np.int32)
        self.avail = np.zeros_like(self.totals)
        self.node_mask = np.zeros(self._capacity, dtype=bool)
        # DRAINING rows stay registered (running tasks keep their debits,
        # heartbeats still sync) but every placement view masks them out,
        # so no new work lands there while the drain completes
        self.draining = np.zeros(self._capacity, dtype=bool)
        # SUSPECT rows (gray failures: slow event loop, open circuit
        # breaker on the node's data-plane link) are SOFT-avoided: the
        # raylet's placement rounds skip them while any healthy node
        # fits, but fall back to them rather than parking feasible work
        # — unlike draining, suspect never hides a node from snapshot()
        self.suspect = np.zeros(self._capacity, dtype=bool)
        # LOANED rows are batch nodes lent to the serve plane: they stay
        # in the placement mask, but the loan manager force-subtracts all
        # generic availability and exposes a shaped "serve_loaned"
        # resource only loaner replicas request — batch work cannot fit
        # until the loan is reclaimed and the availability restored
        self.loaned = np.zeros(self._capacity, dtype=bool)
        self._row_of: dict[NodeID, int] = {}
        self._id_of: dict[int, NodeID] = {}
        self._labels: dict[int, dict[str, str]] = {}
        self.version = 0          # epoch: bumped on every mutation
        # -- delta-heartbeat bookkeeping (see delta_view) -------------------
        # journal of (version, row) per mutation, bounded by _DIRTY_LOG_CAP;
        # consumers synced before _log_floor / _struct_version must resync
        self._dirty_log: deque[tuple[int, int]] = deque()
        self._log_floor = 0
        self._struct_version = 0  # last capacity/width growth epoch
        # epoch-memoized read-only copies handed out by snapshot()/arrays()/
        # delta_view(): (version, totals, avail, raw_mask, place_mask).
        # Two generations rotate so a stale epoch can usually be brought
        # current by patching only the rows dirtied since it was built
        # (see _frozen_locked) instead of re-copying every shard's rows.
        self._frozen: tuple | None = None
        self._frozen_prev: tuple | None = None
        self.frozen_stats = {"full": 0, "patched": 0, "rows_patched": 0}
        # interned dense request vectors: (req.key(), width) -> frozen vec
        self._req_cache: dict[tuple, np.ndarray] = {}

    # -- epoch / dirty tracking ---------------------------------------------
    def _mark(self, row: int | None = None) -> None:
        """Bump the epoch and journal the dirty row (caller holds _lock).

        Every mutation funnels through here so a device-resident mirror
        can ask "what changed since version V?" (delta_view) instead of
        re-uploading the whole state each heartbeat."""
        self.version += 1
        if row is not None:
            if len(self._dirty_log) >= _DIRTY_LOG_CAP:
                self._log_floor = self._dirty_log.popleft()[0]
            self._dirty_log.append((self.version, row))

    def _mark_struct(self) -> None:
        """Capacity or width grew: array shapes moved under every mirror,
        so all of them must full-resync.  Caller holds _lock."""
        self._mark()
        self._struct_version = self.version
        self._dirty_log.clear()
        self._log_floor = self.version

    # -- registration -------------------------------------------------------
    def add_node(self, node_id: NodeID, resources: NodeResources) -> int:
        with self._lock:
            if node_id in self._row_of:
                raise ValueError(f"node {node_id} already registered")
            row = self._alloc_row()
            for name, cu in resources.total_cu.items():
                col = self._col(name)
                self.totals[row, col] = cu
            for name, cu in resources.available_cu.items():
                self.avail[row, self._col(name)] = cu
            self.node_mask[row] = True
            self.draining[row] = False
            self.suspect[row] = False
            self.loaned[row] = False
            self._row_of[node_id] = row
            self._id_of[row] = node_id
            self._labels[row] = dict(resources.labels)
            self._mark(row)
            return row

    def remove_node(self, node_id: NodeID) -> None:
        with self._lock:
            row = self._row_of.pop(node_id, None)
            if row is None:
                return
            self._id_of.pop(row)
            self._labels.pop(row, None)
            self.totals[row] = 0
            self.avail[row] = 0
            self.node_mask[row] = False
            self.draining[row] = False
            self.suspect[row] = False
            # rows are reused by _alloc_row — a stale loaned bit would
            # hide the next tenant of this row from the loan picker
            self.loaned[row] = False
            self._mark(row)

    # -- drain lifecycle (ALIVE -> DRAINING -> removed) ---------------------
    def set_draining(self, node_id: NodeID, flag: bool = True) -> int | None:
        """Mark/unmark a node DRAINING.  Returns its row, or None if the
        node is unknown (already removed — drain raced with death)."""
        with self._lock:
            row = self._row_of.get(node_id)
            if row is None:
                return None
            if bool(self.draining[row]) != flag:
                self.draining[row] = flag
                self._mark(row)
            return row

    def is_draining(self, row: int) -> bool:
        with self._lock:
            return bool(self.draining[row]) if 0 <= row < self._capacity \
                else False

    def draining_rows(self) -> list[int]:
        with self._lock:
            return [int(r) for r in
                    np.flatnonzero(self.node_mask & self.draining)]

    # -- suspect lifecycle (gray failure: soft-avoid, never mask) -----------
    def set_suspect(self, row: int, flag: bool = True) -> None:
        """Mark/unmark a row suspect (the health manager mirrors its
        loop-suspect + breaker-quarantine view here each round)."""
        with self._lock:
            if 0 <= row < self._capacity and \
                    bool(self.suspect[row]) != flag:
                self.suspect[row] = flag
                self._mark(row)

    def suspect_mask(self) -> np.ndarray:
        with self._lock:
            return (self.node_mask & self.suspect).copy()

    def suspect_rows(self) -> list[int]:
        with self._lock:
            return [int(r) for r in
                    np.flatnonzero(self.node_mask & self.suspect)]

    # -- loan lifecycle (batch node lent to the serve plane) ----------------
    def set_loaned(self, row: int, flag: bool = True) -> None:
        """Mark/unmark a row as loaned to serve.  Loaned rows stay in
        the placement mask — batch is kept off them by availability
        (force-subtracted to zero), not by masking, so the drain/restore
        epilogue is a plain add_back."""
        with self._lock:
            if 0 <= row < self._capacity and \
                    bool(self.loaned[row]) != flag:
                self.loaned[row] = flag
                self._mark(row)

    def is_loaned(self, row: int) -> bool:
        with self._lock:
            return bool(self.loaned[row]) if 0 <= row < self._capacity \
                else False

    def loaned_rows(self) -> list[int]:
        with self._lock:
            return [int(r) for r in
                    np.flatnonzero(self.node_mask & self.loaned)]

    def _alloc_row(self) -> int:
        free = np.flatnonzero(~self.node_mask)
        # prefer rows never used / lowest index: deterministic traversal order
        if free.size == 0:
            if self._capacity >= MAX_NODES:
                raise RuntimeError(f"cluster exceeds MAX_NODES={MAX_NODES}")
            self._grow()
            free = np.flatnonzero(~self.node_mask)
        return int(free[0])

    def _grow(self):
        cap = min(self._capacity * 2, MAX_NODES)
        for name in ("totals", "avail"):
            arr = getattr(self, name)
            new = np.zeros((cap, self._r_slots), dtype=np.int32)
            new[:self._capacity] = arr
            setattr(self, name, new)
        mask = np.zeros(cap, dtype=bool)
        mask[:self._capacity] = self.node_mask
        self.node_mask = mask
        drain = np.zeros(cap, dtype=bool)
        drain[:self._capacity] = self.draining
        self.draining = drain
        sus = np.zeros(cap, dtype=bool)
        sus[:self._capacity] = self.suspect
        self.suspect = sus
        loan = np.zeros(cap, dtype=bool)
        loan[:self._capacity] = self.loaned
        self.loaned = loan
        self._capacity = cap
        self._mark_struct()

    def _col(self, name: str) -> int:
        col = self.resource_index.get_or_add(name)
        grew = False
        while col >= self._r_slots:
            new = np.zeros((self._capacity, self._r_slots * 2), dtype=np.int32)
            new[:, :self._r_slots] = self.totals
            self.totals = new
            new_a = np.zeros_like(new)
            new_a[:, :self._r_slots] = self.avail
            self.avail = new_a
            self._r_slots *= 2
            grew = True
        if grew:
            self._mark_struct()
        return col

    def _dense_req(self, req: ResourceRequest) -> np.ndarray:
        """Dense cu vector, growing the resource slots to cover the request
        (ResourceRequest.dense interns names but cannot grow our arrays).
        Caller must hold self._lock (array growth replaces the arrays).

        The vector of each scheduling class is interned once per
        (request, width) and shared read-only across beats — heartbeats
        stop re-densifying every class every time."""
        vec = self._req_cache.get((req.key(), self._r_slots))
        if vec is None:
            for name in req.cu():
                self._col(name)          # may grow width (changes the key)
            vec = req.dense(self.resource_index, self._r_slots)
            vec.setflags(write=False)
            if len(self._req_cache) >= _REQ_CACHE_CAP:
                self._req_cache.clear()
            self._req_cache[(req.key(), self._r_slots)] = vec
        return vec

    def intern_request(self, req: ResourceRequest) -> np.ndarray:
        """Public, lock-acquiring name interning + densification — the safe
        entry point for external callers (array growth under _lock)."""
        with self._lock:
            return self._dense_req(req)

    # -- sync from heartbeats (ray_syncer analogue, SURVEY §2.1) ------------
    def update_node_available(self, node_id: NodeID,
                              available_cu: dict[str, int]) -> None:
        with self._lock:
            row = self._row_of.get(node_id)
            if row is None:
                return
            for name, cu in available_cu.items():
                self.avail[row, self._col(name)] = cu
            self._mark(row)

    # -- allocation (used by the dispatch path) -----------------------------
    def subtract(self, row: int, req: ResourceRequest) -> bool:
        with self._lock:
            vec = self._dense_req(req)
            if (self.avail[row] < vec).any():
                return False
            self.avail[row] -= vec
            self._mark(row)
            return True

    def force_subtract(self, row: int, req: ResourceRequest) -> None:
        """Debit even into negative availability (bounded oversubscription
        on worker-unblock; the matching add_back rebalances)."""
        with self._lock:
            self.avail[row] -= self._dense_req(req)
            self._mark(row)

    def add_back(self, row: int, req: ResourceRequest) -> None:
        with self._lock:
            vec = self._dense_req(req)
            self.avail[row] = np.minimum(self.totals[row],
                                         self.avail[row] + vec)
            self._mark(row)
            self._freed.notify_all()

    def wait_subtract(self, row: int, req: ResourceRequest,
                      timeout: float) -> bool:
        """Blocking subtract: parks on the release condition (no polling)
        until the resources fit or ``timeout`` elapses.  Returns whether
        the debit happened."""
        deadline = time.monotonic() + timeout
        with self._lock:
            while True:
                vec = self._dense_req(req)
                if (self.avail[row] >= vec).all():
                    self.avail[row] -= vec
                    self._mark(row)
                    return True
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._freed.wait(remaining)

    # -- bundle (placement-group) resource shaping --------------------------
    def add_shaped_resources(self, row: int, shaped_cu: dict[str, int]
                             ) -> None:
        """Create/extend pg-shaped resource columns on a node (reference:
        committed bundles surface as ``CPU_group_{pgid}``-style custom
        resources that pg tasks then request — SURVEY §3.5)."""
        with self._lock:
            for name, cu in shaped_cu.items():
                col = self._col(name)
                self.totals[row, col] += cu
                self.avail[row, col] += cu
            self._mark(row)

    def remove_shaped_resources(self, row: int, shaped_cu: dict[str, int]
                                ) -> None:
        with self._lock:
            for name, cu in shaped_cu.items():
                col = self._col(name)
                self.totals[row, col] = max(0, self.totals[row, col] - cu)
                self.avail[row, col] = max(0, self.avail[row, col] - cu)
            self._mark(row)

    # -- views --------------------------------------------------------------
    # a frozen array nobody else holds has exactly this many refs at the
    # getrefcount call: the generation tuple + getrefcount's argument
    _FROZEN_FREE_REFS = 2

    def _recycle_frozen_locked(self) -> tuple | None:
        """Bring the RETIRED frozen generation current by patching only
        the rows dirtied since it was built, instead of re-copying every
        node shard's rows because one row moved.  Returns the patched
        generation, or None when only a full rebuild is sound:

        - no retired generation yet, or shapes grew under it
          (_struct_version), or the dirty journal was truncated past it
          (_log_floor) so "which rows?" cannot be answered;
        - some consumer still holds one of its arrays (refcount probe) —
          patching in place would mutate a view handed out as immutable.

        Caller holds _lock (getrefcount is exact under the GIL)."""
        cand = self._frozen_prev
        if cand is None:
            return None
        v0 = cand[0]
        if v0 < self._struct_version or v0 < self._log_floor or \
                cand[1].shape != self.totals.shape:
            return None
        for i in range(1, 5):
            if sys.getrefcount(cand[i]) > self._FROZEN_FREE_REFS:
                return None
        rows = sorted({r for (ver, r) in self._dirty_log if ver > v0})
        _v, totals, avail, raw_mask, place_mask = cand
        for arr in (totals, avail, raw_mask, place_mask):
            arr.setflags(write=True)
        if rows:
            totals[rows] = self.totals[rows]
            avail[rows] = self.avail[rows]
            raw_mask[rows] = self.node_mask[rows]
            place_mask[rows] = self.node_mask[rows] & \
                ~self.draining[rows]
        for arr in (totals, avail, raw_mask, place_mask):
            arr.setflags(write=False)
        self.frozen_stats["patched"] += 1
        self.frozen_stats["rows_patched"] += len(rows)
        return (self.version, totals, avail, raw_mask, place_mask)

    def _frozen_locked(self) -> tuple:
        """Epoch-memoized read-only copies of the state arrays.  One set
        of copies per epoch, shared by snapshot()/arrays()/delta_view():
        unchanged beats stop re-copying three arrays per heartbeat, and
        dirty beats recycle the retired generation row-by-row
        (_recycle_frozen_locked) rather than rebuilding every view.
        Caller holds _lock."""
        if self._frozen is not None and self._frozen[0] == self.version:
            return self._frozen
        gen = self._recycle_frozen_locked()
        if gen is None:
            totals = self.totals.copy()
            avail = self.avail.copy()
            raw_mask = self.node_mask.copy()
            place_mask = self.node_mask & ~self.draining
            for arr in (totals, avail, raw_mask, place_mask):
                arr.setflags(write=False)
            gen = (self.version, totals, avail, raw_mask, place_mask)
            self.frozen_stats["full"] += 1
        self._frozen_prev = self._frozen
        self._frozen = gen
        return gen

    def snapshot(self) -> ClusterState:
        """Copy-on-read snapshot for a scheduling round (pure-function
        discipline: policies never see live mutable state — SURVEY §4
        'every scheduling decision is testable without real distribution')."""
        with self._lock:
            # DRAINING rows are infeasible for every placement consumer
            # (raylet rounds, pg bundles, autoscaler demand, trainer fit).
            # Policies decrement state.avail in place, so each caller gets
            # its own writable avail; totals/mask are shared frozen views.
            _, totals, avail, _raw, place = self._frozen_locked()
            return ClusterState(totals, avail.copy(), place)

    def arrays(self) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Read-only epoch-frozen (totals, avail, node_mask) for metric /
        autoscaler reads — memoized by the epoch counter."""
        with self._lock:
            _, totals, avail, raw, _place = self._frozen_locked()
            return totals, avail, raw

    def delta_view(self, since_version: int) -> tuple:
        """Atomic "what changed since epoch V" view for device-resident
        mirrors (the delta-scheduling heartbeat).

        Returns ``(version, totals, avail, place_mask, dirty_rows)``.
        The arrays are the shared read-only epoch copies (never mutate);
        ``place_mask = node_mask & ~draining`` — the same placement mask
        ``snapshot()`` hands every consumer.  ``dirty_rows`` is the set
        of rows mutated in ``(since_version, version]``; ``None`` means
        the journal cannot answer (first sync, journal truncated past
        ``since_version``, or a capacity/width growth moved array shapes)
        and the caller must re-upload everything."""
        with self._lock:
            v, totals, avail, _raw, place = self._frozen_locked()
            rows: set[int] | None
            if since_version >= v:
                rows = set()
            elif since_version < self._struct_version or \
                    since_version < self._log_floor:
                rows = None
            else:
                rows = {r for (ver, r) in self._dirty_log
                        if ver > since_version}
            return v, totals, avail, place, rows

    def row_of(self, node_id: NodeID) -> int | None:
        with self._lock:
            return self._row_of.get(node_id)

    def id_of(self, row: int) -> NodeID | None:
        with self._lock:
            return self._id_of.get(row)

    def labels_of(self, row: int) -> dict[str, str]:
        with self._lock:
            return dict(self._labels.get(row, {}))

    def num_nodes(self) -> int:
        with self._lock:
            return len(self._row_of)

    def label_mask(self, label_selector: dict[str, str]) -> np.ndarray:
        """(capacity,) bool mask of nodes matching all label k=v pairs."""
        with self._lock:
            mask = self.node_mask & ~self.draining
            for row in range(self._capacity):
                if not mask[row]:
                    continue
                labels = self._labels.get(row, {})
                if any(labels.get(k) != v
                       for k, v in label_selector.items()):
                    mask[row] = False
            return mask
