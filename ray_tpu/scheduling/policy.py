"""The SchedulingPolicy plugin boundary and its stock policies.

Reference parity: ``ISchedulingPolicy::Schedule(resource_request,
SchedulingOptions)`` with implementations ``HybridSchedulingPolicy``,
``SpreadSchedulingPolicy``, ``RandomSchedulingPolicy``,
``NodeAffinitySchedulingPolicy``, ``NodeLabelSchedulingPolicy``, composed by
``CompositeSchedulingPolicy`` (``src/ray/raylet/scheduling/policy/*``).
[SURVEY.md §1 layer 5; mount empty.]  BASELINE.json gates the TPU backend
behind exactly this boundary: the hybrid policy here can answer from the CPU
oracle or defer batches to the device kernel — callers cannot tell which.

Policies are pure functions of (ClusterState snapshot, request, options):
no hidden state except the documented RNG/round-robin cursors, so parity is a
property test (SURVEY §4 closing note).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass

import numpy as np

from .contract import (AVAIL_SHIFT, INFEASIBLE_KEY, compute_keys,
                       threshold_fp)
from .oracle import ClusterState


class SchedulingType(enum.Enum):
    HYBRID = 0
    SPREAD = 1
    RANDOM = 2
    NODE_AFFINITY = 3
    NODE_LABEL = 4


@dataclass
class SchedulingOptions:
    """Mirror of the reference's SchedulingOptions variants."""

    scheduling_type: SchedulingType = SchedulingType.HYBRID
    spread_threshold: float | None = None      # None => config default
    avoid_local_node: bool = False
    local_node_row: int = 0                    # row of the scheduling raylet
    require_node_available: bool = False
    # NODE_AFFINITY
    node_row: int = -1
    soft: bool = False
    # label constraints resolved by the caller into a node mask
    node_mask: np.ndarray | None = None


class ISchedulingPolicy:
    def schedule(self, state: ClusterState, req: np.ndarray,
                 options: SchedulingOptions) -> int:
        """Return node row or -1. Must not mutate ``state`` unless the
        placement consumes resources (available-bucket placements do)."""
        raise NotImplementedError


class HybridSchedulingPolicy(ISchedulingPolicy):
    """The default policy — contract.py semantics (SURVEY §2.5).

    Top-k sampling (reference ``scheduler_top_k_fraction`` /
    ``scheduler_top_k_absolute``): with fraction > 0 the policy samples
    uniformly among the k best-keyed feasible nodes instead of always
    taking the minimum, trading determinism for contention spread.  The
    stream is a pinned Philox counter (one draw per decision) so runs
    replay bit-for-bit.  fraction = 0 (the default) is the
    argmin/bit-exact-parity configuration; the device batch path requires
    it (sampling rounds route through this host policy)."""

    def __init__(self, seed: int = 0):
        self._rng = np.random.Generator(np.random.Philox(seed))

    def schedule(self, state, req, options):
        from ..common.config import get_config
        thr = threshold_fp(options.spread_threshold)
        mask = state.node_mask
        if options.node_mask is not None:
            mask = mask & options.node_mask
        if options.avoid_local_node and \
                0 <= options.local_node_row < mask.shape[0]:
            mask = mask.copy()
            mask[options.local_node_row] = False
        keys = compute_keys(state.totals, state.avail, req, thr, mask)
        cfg = get_config()
        if cfg.scheduler_top_k_fraction > 0:
            node = self._sample_top_k(keys, cfg)
        else:
            node = int(np.argmin(keys))
        if node < 0 or keys[node] == INFEASIBLE_KEY:
            return -1
        available = (keys[node] >> AVAIL_SHIFT) == 0
        if options.require_node_available and not available:
            return -1
        if available:
            state.avail[node] -= np.asarray(req, dtype=np.int32)
        return node

    def _sample_top_k(self, keys: np.ndarray, cfg) -> int:
        feasible = np.flatnonzero(keys != INFEASIBLE_KEY)
        if feasible.size == 0:
            return -1
        k = max(int(cfg.scheduler_top_k_absolute),
                int(np.ceil(cfg.scheduler_top_k_fraction * feasible.size)))
        k = min(k, feasible.size)
        # the k best by packed key (ties broken by row index, like argmin)
        order = feasible[np.argsort(keys[feasible], kind="stable")[:k]]
        return int(self._rng.choice(order))


class SpreadSchedulingPolicy(ISchedulingPolicy):
    """Round-robin over feasible+available nodes (reference
    ``SpreadSchedulingPolicy``: best-effort even spreading with a rotating
    start cursor)."""

    def __init__(self):
        self._cursor = 0

    def schedule(self, state, req, options):
        thr = threshold_fp(options.spread_threshold)
        mask = state.node_mask if options.node_mask is None \
            else state.node_mask & options.node_mask
        keys = compute_keys(state.totals, state.avail, req, thr, mask)
        n = state.num_nodes
        order = (np.arange(n) + self._cursor) % n
        feasible = keys != INFEASIBLE_KEY
        available = feasible & ((keys >> AVAIL_SHIFT) == 0)
        for pool in (available, feasible):
            cand = order[pool[order]]
            if cand.size:
                node = int(cand[0])
                self._cursor = (node + 1) % n
                if available[node]:
                    state.avail[node] -= np.asarray(req, dtype=np.int32)
                return node
        return -1


class RandomSchedulingPolicy(ISchedulingPolicy):
    """Uniform over feasible+available nodes, pinned threefry stream so runs
    replay deterministically (SURVEY §7 hard part 2)."""

    def __init__(self, seed: int = 0):
        self._rng = np.random.Generator(np.random.Philox(seed))

    def schedule(self, state, req, options):
        thr = threshold_fp(options.spread_threshold)
        mask = state.node_mask if options.node_mask is None \
            else state.node_mask & options.node_mask
        keys = compute_keys(state.totals, state.avail, req, thr, mask)
        available = (keys != INFEASIBLE_KEY) & ((keys >> AVAIL_SHIFT) == 0)
        cand = np.flatnonzero(available)
        if cand.size == 0:
            cand = np.flatnonzero(keys != INFEASIBLE_KEY)
            if cand.size == 0:
                return -1
            return int(self._rng.choice(cand))
        node = int(self._rng.choice(cand))
        state.avail[node] -= np.asarray(req, dtype=np.int32)
        return node


class NodeAffinitySchedulingPolicy(ISchedulingPolicy):
    """Pin to a node; hard affinity fails if the node can't take it, soft
    affinity falls back to hybrid (reference
    ``NodeAffinitySchedulingPolicy``)."""

    def __init__(self):
        self._hybrid = HybridSchedulingPolicy()

    def schedule(self, state, req, options):
        row = options.node_row
        ok = (0 <= row < state.num_nodes) and bool(state.node_mask[row])
        if ok:
            thr = threshold_fp(options.spread_threshold)
            keys = compute_keys(state.totals, state.avail, req, thr,
                                state.node_mask)
            if keys[row] != INFEASIBLE_KEY:
                if (keys[row] >> AVAIL_SHIFT) == 0:
                    state.avail[row] -= np.asarray(req, dtype=np.int32)
                return row
        if options.soft:
            fallback = SchedulingOptions(
                scheduling_type=SchedulingType.HYBRID,
                spread_threshold=options.spread_threshold,
                node_mask=options.node_mask)
            return self._hybrid.schedule(state, req, fallback)
        return -1


class NodeLabelSchedulingPolicy(ISchedulingPolicy):
    """Restrict to nodes matching a label selector (resolved by the
    caller into ``options.node_mask``), hybrid within the match set;
    hard selectors with no matching node park (-1), soft ones fall back
    to the unrestricted hybrid (reference
    ``NodeLabelSchedulingPolicy`` hard/soft label constraints)."""

    def __init__(self):
        self._hybrid = HybridSchedulingPolicy()

    def schedule(self, state, req, options):
        node = self._hybrid.schedule(state, req, options)
        if node >= 0 or not options.soft:
            return node
        fallback = SchedulingOptions(
            scheduling_type=SchedulingType.HYBRID,
            spread_threshold=options.spread_threshold)
        return self._hybrid.schedule(state, req, fallback)


class CompositeSchedulingPolicy(ISchedulingPolicy):
    """Dispatch on options.scheduling_type (reference
    ``CompositeSchedulingPolicy``)."""

    def __init__(self, seed: int = 0):
        self._policies = {
            SchedulingType.HYBRID: HybridSchedulingPolicy(),
            SchedulingType.SPREAD: SpreadSchedulingPolicy(),
            SchedulingType.RANDOM: RandomSchedulingPolicy(seed),
            SchedulingType.NODE_AFFINITY: NodeAffinitySchedulingPolicy(),
            SchedulingType.NODE_LABEL: NodeLabelSchedulingPolicy(),
        }

    def schedule(self, state, req, options):
        return self._policies[options.scheduling_type].schedule(
            state, req, options)
