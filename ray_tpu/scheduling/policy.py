"""The SchedulingPolicy plugin boundary and its stock policies.

Reference parity: ``ISchedulingPolicy::Schedule(resource_request,
SchedulingOptions)`` with implementations ``HybridSchedulingPolicy``,
``SpreadSchedulingPolicy``, ``RandomSchedulingPolicy``,
``NodeAffinitySchedulingPolicy``, ``NodeLabelSchedulingPolicy``, composed by
``CompositeSchedulingPolicy`` (``src/ray/raylet/scheduling/policy/*``).
[SURVEY.md §1 layer 5; mount empty.]  BASELINE.json gates the TPU backend
behind exactly this boundary: the hybrid policy here can answer from the CPU
oracle or defer batches to the device kernel — callers cannot tell which.

Policies are pure functions of (ClusterState snapshot, request, options):
no hidden state except the documented RNG/round-robin cursors, so parity is a
property test (SURVEY §4 closing note).
"""

from __future__ import annotations

import enum
import time
from dataclasses import dataclass

import numpy as np

from .contract import (AVAIL_SHIFT, INFEASIBLE_KEY, compute_keys,
                       threshold_fp)
from .oracle import ClusterState


class SchedulingType(enum.Enum):
    HYBRID = 0
    SPREAD = 1
    RANDOM = 2
    NODE_AFFINITY = 3
    NODE_LABEL = 4


@dataclass
class SchedulingOptions:
    """Mirror of the reference's SchedulingOptions variants."""

    scheduling_type: SchedulingType = SchedulingType.HYBRID
    spread_threshold: float | None = None      # None => config default
    avoid_local_node: bool = False
    local_node_row: int = 0                    # row of the scheduling raylet
    require_node_available: bool = False
    # NODE_AFFINITY
    node_row: int = -1
    soft: bool = False
    # label constraints resolved by the caller into a node mask
    node_mask: np.ndarray | None = None


class ISchedulingPolicy:
    def schedule(self, state: ClusterState, req: np.ndarray,
                 options: SchedulingOptions) -> int:
        """Return node row or -1. Must not mutate ``state`` unless the
        placement consumes resources (available-bucket placements do)."""
        raise NotImplementedError


class HybridSchedulingPolicy(ISchedulingPolicy):
    """The default policy — contract.py semantics (SURVEY §2.5).

    Top-k sampling (reference ``scheduler_top_k_fraction`` /
    ``scheduler_top_k_absolute``): with fraction > 0 the policy samples
    uniformly among the k best-keyed feasible nodes instead of always
    taking the minimum, trading determinism for contention spread.  The
    stream is a pinned Philox counter (one draw per decision) so runs
    replay bit-for-bit.  fraction = 0 (the default) is the
    argmin/bit-exact-parity configuration; the device batch path requires
    it (sampling rounds route through this host policy)."""

    def __init__(self, seed: int = 0):
        self._rng = np.random.Generator(np.random.Philox(seed))

    def schedule(self, state, req, options):
        from ..common.config import get_config
        thr = threshold_fp(options.spread_threshold)
        mask = state.node_mask
        if options.node_mask is not None:
            mask = mask & options.node_mask
        if options.avoid_local_node and \
                0 <= options.local_node_row < mask.shape[0]:
            mask = mask.copy()
            mask[options.local_node_row] = False
        keys = compute_keys(state.totals, state.avail, req, thr, mask)
        cfg = get_config()
        if cfg.scheduler_top_k_fraction > 0:
            node = self._sample_top_k(keys, cfg)
        else:
            node = int(np.argmin(keys))
        if node < 0 or keys[node] == INFEASIBLE_KEY:
            return -1
        available = (keys[node] >> AVAIL_SHIFT) == 0
        if options.require_node_available and not available:
            return -1
        if available:
            state.avail[node] -= np.asarray(req, dtype=np.int32)
        return node

    def _sample_top_k(self, keys: np.ndarray, cfg) -> int:
        feasible = np.flatnonzero(keys != INFEASIBLE_KEY)
        if feasible.size == 0:
            return -1
        k = max(int(cfg.scheduler_top_k_absolute),
                int(np.ceil(cfg.scheduler_top_k_fraction * feasible.size)))
        k = min(k, feasible.size)
        # the k best by packed key (ties broken by row index, like argmin)
        order = feasible[np.argsort(keys[feasible], kind="stable")[:k]]
        return int(self._rng.choice(order))


class SpreadSchedulingPolicy(ISchedulingPolicy):
    """Round-robin over feasible+available nodes (reference
    ``SpreadSchedulingPolicy``: best-effort even spreading with a rotating
    start cursor)."""

    def __init__(self):
        self._cursor = 0

    def schedule(self, state, req, options):
        thr = threshold_fp(options.spread_threshold)
        mask = state.node_mask if options.node_mask is None \
            else state.node_mask & options.node_mask
        keys = compute_keys(state.totals, state.avail, req, thr, mask)
        n = state.num_nodes
        order = (np.arange(n) + self._cursor) % n
        feasible = keys != INFEASIBLE_KEY
        available = feasible & ((keys >> AVAIL_SHIFT) == 0)
        for pool in (available, feasible):
            cand = order[pool[order]]
            if cand.size:
                node = int(cand[0])
                self._cursor = (node + 1) % n
                if available[node]:
                    state.avail[node] -= np.asarray(req, dtype=np.int32)
                return node
        return -1


class RandomSchedulingPolicy(ISchedulingPolicy):
    """Uniform over feasible+available nodes, pinned threefry stream so runs
    replay deterministically (SURVEY §7 hard part 2)."""

    def __init__(self, seed: int = 0):
        self._rng = np.random.Generator(np.random.Philox(seed))

    def schedule(self, state, req, options):
        thr = threshold_fp(options.spread_threshold)
        mask = state.node_mask if options.node_mask is None \
            else state.node_mask & options.node_mask
        keys = compute_keys(state.totals, state.avail, req, thr, mask)
        available = (keys != INFEASIBLE_KEY) & ((keys >> AVAIL_SHIFT) == 0)
        cand = np.flatnonzero(available)
        if cand.size == 0:
            cand = np.flatnonzero(keys != INFEASIBLE_KEY)
            if cand.size == 0:
                return -1
            return int(self._rng.choice(cand))
        node = int(self._rng.choice(cand))
        state.avail[node] -= np.asarray(req, dtype=np.int32)
        return node


class NodeAffinitySchedulingPolicy(ISchedulingPolicy):
    """Pin to a node; hard affinity fails if the node can't take it, soft
    affinity falls back to hybrid (reference
    ``NodeAffinitySchedulingPolicy``)."""

    def __init__(self):
        self._hybrid = HybridSchedulingPolicy()

    def schedule(self, state, req, options):
        row = options.node_row
        ok = (0 <= row < state.num_nodes) and bool(state.node_mask[row])
        if ok:
            thr = threshold_fp(options.spread_threshold)
            keys = compute_keys(state.totals, state.avail, req, thr,
                                state.node_mask)
            if keys[row] != INFEASIBLE_KEY:
                if (keys[row] >> AVAIL_SHIFT) == 0:
                    state.avail[row] -= np.asarray(req, dtype=np.int32)
                return row
        if options.soft:
            fallback = SchedulingOptions(
                scheduling_type=SchedulingType.HYBRID,
                spread_threshold=options.spread_threshold,
                node_mask=options.node_mask)
            return self._hybrid.schedule(state, req, fallback)
        return -1


class NodeLabelSchedulingPolicy(ISchedulingPolicy):
    """Restrict to nodes matching a label selector (resolved by the
    caller into ``options.node_mask``), hybrid within the match set;
    hard selectors with no matching node park (-1), soft ones fall back
    to the unrestricted hybrid (reference
    ``NodeLabelSchedulingPolicy`` hard/soft label constraints)."""

    def __init__(self):
        self._hybrid = HybridSchedulingPolicy()

    def schedule(self, state, req, options):
        node = self._hybrid.schedule(state, req, options)
        if node >= 0 or not options.soft:
            return node
        fallback = SchedulingOptions(
            scheduling_type=SchedulingType.HYBRID,
            spread_threshold=options.spread_threshold)
        return self._hybrid.schedule(state, req, fallback)


def _bucket(n: int, floor: int = 8) -> int:
    """Smallest power of two >= max(n, floor) — XLA compilation bucketing
    (same discipline as the raylet's device batch path)."""
    n = max(int(n), floor)
    return 1 << (n - 1).bit_length()


class DeltaScheduler:
    """Device-resident delta-scheduling heartbeat engine.

    Keeps three residents in HBM between beats: a mirror of the CRM's
    dense state (totals/avail/placement mask), the interned scheduling
    class request matrix, and a carried (classes x nodes) packed-key
    tensor bit-identical to ``contract.compute_keys`` on the mirror.
    Each ``beat``:

    1. asks the CRM what changed since the last synced epoch
       (``ClusterResourceManager.delta_view``), stages ONLY the dirty
       rows host->HBM through one of two staging slots (double
       buffering: beat N+1's upload enqueues while beat N's readback is
       still in flight — dispatch is async, the host blocks only on the
       consumed counts buffer), and re-scores only the touched key
       columns (``ops.hybrid_kernel.apply_dirty_rows``);
    2. falls back to a full re-upload + ``full_rescore`` when the dirty
       fraction crosses ``scheduler_delta_max_dirty_fraction``, the
       journal was truncated, array shapes grew, or the spread
       threshold changed;
    3. runs the fused water-fill + per-class argmin
       (``ops.hybrid_kernel.fused_beat``) with this beat's ephemeral
       avail overrides (planned-load debits) and soft mask (suspect
       avoidance) — ONE readback per beat, not one per class.  The
       packed buffer carries the water-fill counts AND the
       per-(class, node) lease budgets the kernel priced off its own
       post-beat avail (``contract.compute_budgets`` twin); the lease
       plane reads them via ``last_budgets``/``budget_row_host``
       without any extra device sync.

    Placements are advisory exactly like the snapshot path: the CRM
    stays authoritative, commits happen through ``subtract`` at
    dispatch, which marks the rows dirty for the next beat.  Counts are
    bit-identical to ``schedule_grouped`` on a fresh snapshot — the
    randomized delta-sequence oracle test holds delta path == full
    rescore == CPU oracle.
    """

    def __init__(self, crm):
        self._crm = crm
        self._version = -2          # pre-first-sync sentinel (< any epoch)
        self._thr: int | None = None
        # device residents
        self._totals = None
        self._avail = None
        self._mask = None
        self._keys = None
        self._reqs = None
        self._ones = None           # resident all-true extra mask
        self._n = 0                 # padded node axis
        self._r = 0                 # padded resource axis
        self._cap_c = 0             # padded class axis
        self._n_real = 0
        self._r_real = 0
        # class slot registry (+ host copies to rebuild across resyncs)
        self._slot_of: dict[bytes, int] = {}
        self._class_host: dict[int, np.ndarray] = {}
        self._free_slots: list[int] = []
        self._next_slot = 0
        # double-buffered staging: the previous beat's upload stays
        # referenced until its transfer can no longer be in flight
        self._stage: list = [None, None]
        self._parity = 0
        self._empty_ov = None
        self._last_amin = None
        # beat-emitted lease budgets: host (C_real, n_real) slice of the
        # packed readback, refreshed every beat; seq lets the publisher
        # tell "new beat" from "same beat re-read"
        self._budgets_host: np.ndarray | None = None
        self._budget_seq = 0
        self.stats = {"beats": 0, "delta_beats": 0, "full_rescores": 0,
                      "clean_beats": 0, "rows_uploaded": 0,
                      "classes_installed": 0}
        # opt-in phase profiling (bench.py): inserts device syncs after
        # every phase, so it DEFEATS the double-buffered overlap — never
        # enable on the live dispatch path
        self.profile = False
        self.phase_ms = {"densify": 0.0, "h2d": 0.0, "score": 0.0,
                         "argmin": 0.0, "readback": 0.0}

    # -- public surface -----------------------------------------------------
    def beat(self, group_reqs, group_counts, overrides=None,
             extra_mask=None, require_available: bool = False,
             spread_threshold: float | None = None) -> np.ndarray:
        """Sync the mirror, schedule G classes, return (G, n+1) int32
        counts (column n = infeasible/queued-nowhere), matching
        ``hybrid_kernel.schedule_grouped`` on a fresh CRM snapshot.

        ``overrides``: {row: int32 avail vector} applied for this beat
        only (the raylet's planned-load debits).  ``extra_mask``: host
        bool (n,) soft mask ANDed into the placement mask for this beat
        (suspect avoidance) — the carried key tensor ignores it.
        """
        from ..common.config import get_config

        thr = int(threshold_fp(spread_threshold))
        v, totals, avail, place_mask, rows = \
            self._crm.delta_view(self._version)
        n_real, r_real = totals.shape
        cfg = get_config()
        resync = (rows is None or self._totals is None
                  or thr != self._thr or n_real != self._n_real
                  or r_real != self._r_real)
        if not resync and rows and len(rows) > \
                cfg.scheduler_delta_max_dirty_fraction * n_real:
            # the fallback knob: 0.0 disables the delta path entirely
            resync = True
        if resync:
            self._full_sync(totals, avail, place_mask, thr)
            self.stats["full_rescores"] += 1
        elif rows:
            self._delta_sync(sorted(rows), totals, avail, place_mask, thr)
            self.stats["delta_beats"] += 1
            self.stats["rows_uploaded"] += len(rows)
        else:
            self.stats["clean_beats"] += 1
        self._version = v
        self.stats["beats"] += 1

        t0 = time.perf_counter() if self.profile else 0.0
        group_reqs = np.ascontiguousarray(
            np.asarray(group_reqs, np.int32))
        g = group_reqs.shape[0]
        if group_reqs.shape[1] != self._r_real:
            # caller densified at an older width; columns only ever
            # append, so zero-padding to the mirror's width is exact
            norm = np.zeros((g, self._r_real), np.int32)
            w = min(self._r_real, group_reqs.shape[1])
            norm[:, :w] = group_reqs[:, :w]
            group_reqs = norm
        slots = self._ensure_classes(group_reqs, thr)
        gp = _bucket(g)
        slots_p = np.full((gp,), self._cap_c, np.int32)
        slots_p[:g] = slots
        counts_p = np.zeros((gp,), np.int32)
        counts_p[:g] = np.asarray(group_counts, np.int32)

        ov = self._pack_overrides(overrides)
        if extra_mask is None:
            em = self._ones
        else:
            emp = np.zeros((self._n,), bool)
            emp[:n_real] = np.asarray(extra_mask, bool)[:n_real]
            em = self._put_extra_mask(emp)
        if self.profile:
            self.phase_ms["densify"] += (time.perf_counter() - t0) * 1e3
            t0 = time.perf_counter()

        counts_d, amin_d = self._fused_call(
            slots_p, counts_p, em, ov, thr, require_available)
        self._last_amin = amin_d
        if self.profile:
            counts_d.block_until_ready()    # rtlint: disable=W6
            self.phase_ms["argmin"] += (time.perf_counter() - t0) * 1e3
            t0 = time.perf_counter()
        # the one sanctioned host<-device readback of the beat: rows
        # [:gp] are the water-fill counts, rows [gp:] the lease budgets
        packed = np.asarray(counts_d)
        counts = packed[:gp]
        self._budgets_host = packed[gp:, :n_real]
        self._budget_seq += 1
        if self.profile:
            self.phase_ms["readback"] += (time.perf_counter() - t0) * 1e3
        return np.concatenate(
            [counts[:g, :n_real], counts[:g, -1:]], axis=1)

    def hit_rate(self) -> float:
        """Fraction of beats served without a full re-upload/rescore."""
        b = self.stats["beats"]
        return 0.0 if not b else 1.0 - self.stats["full_rescores"] / b

    def retire_class(self, req_vec) -> bool:
        """Forget an interned scheduling class, freeing its slot (the
        next new class reuses it and rewrites the key row)."""
        key = np.ascontiguousarray(
            np.asarray(req_vec, np.int32)).tobytes()
        slot = self._slot_of.pop(key, None)
        if slot is None:
            return False
        self._class_host.pop(slot, None)
        self._free_slots.append(slot)
        return True

    def keys_row_host(self, req_vec) -> np.ndarray:
        """Carried key row of one interned class vs the real nodes —
        verification surface for the parity tests (deliberate
        readback)."""
        key = np.ascontiguousarray(
            np.asarray(req_vec, np.int32)).tobytes()
        row = np.asarray(self._keys[self._slot_of[key]])
        return row[:self._n_real].astype(np.int64)

    def peek_argmin(self, req_vec) -> int:
        """Best node row for one class per the carried key tensor (the
        lease-grant preview; deliberate readback)."""
        key = np.ascontiguousarray(
            np.asarray(req_vec, np.int32)).tobytes()
        return int(np.asarray(self._last_amin)[self._slot_of[key]])

    # -- beat-emitted lease budgets (host copies off the fused readback) ----
    @property
    def budget_seq(self) -> int:
        """Monotonic count of beats whose budgets have landed."""
        return self._budget_seq

    def last_budgets(self) -> np.ndarray | None:
        """(C, n_real) int32 budgets from the last beat's readback, row
        index == class slot; None before the first beat.  NOT a device
        sync — this is the host slice the beat already fetched."""
        return self._budgets_host

    def class_vectors(self) -> dict[int, np.ndarray]:
        """{slot: interned dense request vector} for every resident
        class — the publisher's map from budget rows back to lease
        class keys."""
        return dict(self._class_host)

    def budget_row_host(self, req_vec) -> np.ndarray | None:
        """Beat-emitted lease budget of one interned class vs the real
        nodes, or None if the class isn't resident / no beat has run."""
        if self._budgets_host is None:
            return None
        key = np.ascontiguousarray(
            np.asarray(req_vec, np.int32)).tobytes()
        slot = self._slot_of.get(key)
        if slot is None or slot >= self._budgets_host.shape[0]:
            return None
        return self._budgets_host[slot]

    # -- device-layout hooks (the mesh-sharded engine overrides these) ------
    def _put_extra_mask(self, emp):
        """Device placement of a padded per-beat soft mask."""
        import jax
        return jax.device_put(emp)

    def _fused_call(self, slots_p, counts_p, em, ov, thr,
                    require_available):
        """The fused schedule->argmin device call; returns
        (counts_device (G, n+1), amin_device (C,))."""
        import jax

        from ..ops import hybrid_kernel as hk
        return hk.fused_beat(
            self._totals, self._avail, self._mask, self._keys, self._reqs,
            jax.device_put(slots_p), jax.device_put(counts_p), em,
            ov[0], ov[1], thr, require_available=require_available)

    def _put_state(self, ht, ha, hm):
        """Place the padded mirror arrays (+ the resident all-true
        mask); called by _full_sync after shape bookkeeping."""
        import jax
        self._totals = jax.device_put(ht)
        self._avail = jax.device_put(ha)
        self._mask = jax.device_put(hm)
        self._ones = jax.device_put(np.ones(hm.shape, bool))

    def _put_reqs(self, hr):
        import jax
        self._reqs = jax.device_put(hr)

    def _full_rescore_call(self, thr):
        from ..ops import hybrid_kernel as hk
        return hk.full_rescore(self._totals, self._avail, self._mask,
                               self._reqs, thr)

    def _install_classes(self, idx, vecs, thr):
        """Install freshly interned class rows (host idx/vec buffers)
        into the resident request matrix + key tensor."""
        import jax

        from ..ops import hybrid_kernel as hk
        self._reqs, self._keys = hk.apply_dirty_classes(
            self._totals, self._avail, self._mask, self._keys,
            self._reqs, jax.device_put(idx), jax.device_put(vecs), thr)

    def _node_pad(self, n_real: int) -> int:
        """Padded node-axis length (power-of-2 bucket, floor 64)."""
        return _bucket(n_real, 64)

    # -- sync internals -----------------------------------------------------
    def _full_sync(self, totals, avail, mask, thr):
        import jax

        n_real, r_real = totals.shape
        n = self._node_pad(n_real)
        r = _bucket(r_real)
        if r_real != self._r_real and self._slot_of:
            # width grew: re-key the registry at the new width (dense
            # vectors only ever append columns, so zero-padding is exact)
            rekeyed = {}
            for slot, vec in list(self._class_host.items()):
                nv = np.zeros((r_real,), np.int32)
                nv[:vec.shape[0]] = vec
                self._class_host[slot] = nv
                rekeyed[nv.tobytes()] = slot
            self._slot_of = rekeyed
        ht = np.zeros((n, r), np.int32)
        ht[:n_real, :r_real] = totals
        ha = np.zeros((n, r), np.int32)
        ha[:n_real, :r_real] = avail
        hm = np.zeros((n,), bool)
        hm[:n_real] = mask
        t0 = time.perf_counter() if self.profile else 0.0
        self._n, self._r = n, r
        self._n_real, self._r_real = n_real, r_real
        self._put_state(ht, ha, hm)
        self._empty_ov = None
        if self.profile:
            jax.block_until_ready(self._avail)  # rtlint: disable=W6
            self.phase_ms["h2d"] += (time.perf_counter() - t0) * 1e3
            t0 = time.perf_counter()
        self._rebuild_class_plane(thr, rescore=False)
        self._keys = self._full_rescore_call(thr)
        if self.profile:
            jax.block_until_ready(self._keys)   # rtlint: disable=W6
            self.phase_ms["score"] += (time.perf_counter() - t0) * 1e3
        self._thr = thr

    def _delta_sync(self, rows, totals, avail, mask, thr):
        import jax

        from ..ops import hybrid_kernel as hk
        t0 = time.perf_counter() if self.profile else 0.0
        b = _bucket(len(rows))
        idx = np.full((b,), self._n, np.int32)   # padding idx -> dropped
        idx[:len(rows)] = rows
        rt = np.zeros((b, self._r), np.int32)
        ra = np.zeros((b, self._r), np.int32)
        rm = np.zeros((b,), bool)
        rt[:len(rows), :self._r_real] = totals[rows]
        ra[:len(rows), :self._r_real] = avail[rows]
        rm[:len(rows)] = mask[rows]
        # double-buffered staging: enqueue into the free slot; no host
        # block here — the transfer overlaps the previous beat's compute
        staged = jax.device_put((idx, rt, ra, rm))
        self._stage[self._parity] = staged
        self._parity ^= 1
        if self.profile:
            jax.block_until_ready(staged)       # rtlint: disable=W6
            self.phase_ms["h2d"] += (time.perf_counter() - t0) * 1e3
            t0 = time.perf_counter()
        self._totals, self._avail, self._mask, self._keys = \
            hk.apply_dirty_rows(self._totals, self._avail, self._mask,
                                self._keys, self._reqs, *staged, thr)
        if self.profile:
            jax.block_until_ready(self._keys)   # rtlint: disable=W6
            self.phase_ms["score"] += (time.perf_counter() - t0) * 1e3

    def _rebuild_class_plane(self, thr, rescore=True):
        cap = _bucket(max(self._next_slot, 1))
        hr = np.zeros((cap, self._r), np.int32)
        for slot, vec in self._class_host.items():
            hr[slot, :vec.shape[0]] = vec
        self._cap_c = cap
        self._put_reqs(hr)
        if rescore:
            self._keys = self._full_rescore_call(thr)

    def _ensure_classes(self, group_reqs, thr) -> np.ndarray:
        slots = np.empty((group_reqs.shape[0],), np.int32)
        fresh: list[tuple[int, np.ndarray]] = []
        for i, vec in enumerate(group_reqs):
            key = vec.tobytes()
            slot = self._slot_of.get(key)
            if slot is None:
                slot = self._free_slots.pop() if self._free_slots \
                    else self._next_slot
                if slot == self._next_slot:
                    self._next_slot += 1
                self._slot_of[key] = slot
                self._class_host[slot] = vec.copy()
                fresh.append((slot, vec))
            slots[i] = slot
        if fresh:
            self.stats["classes_installed"] += len(fresh)
            if max(s for s, _ in fresh) >= self._cap_c:
                self._rebuild_class_plane(thr)   # class axis grew
            else:
                b = _bucket(len(fresh))
                idx = np.full((b,), self._cap_c, np.int32)
                vecs = np.zeros((b, self._r), np.int32)
                for j, (slot, vec) in enumerate(fresh):
                    idx[j] = slot
                    vecs[j, :vec.shape[0]] = vec
                self._install_classes(idx, vecs, thr)
        return slots

    def _pack_overrides(self, overrides):
        import jax
        if not overrides:
            if self._empty_ov is None:
                idx = np.full((8,), self._n, np.int32)
                av = np.zeros((8, self._r), np.int32)
                self._empty_ov = jax.device_put((idx, av))
            return self._empty_ov
        b = _bucket(len(overrides))
        idx = np.full((b,), self._n, np.int32)
        av = np.zeros((b, self._r), np.int32)
        for j, (row, vec) in enumerate(sorted(overrides.items())):
            idx[j] = row
            av[j, :len(vec)] = np.asarray(vec, np.int32)
        return jax.device_put((idx, av))


class CompositeSchedulingPolicy(ISchedulingPolicy):
    """Dispatch on options.scheduling_type (reference
    ``CompositeSchedulingPolicy``)."""

    def __init__(self, seed: int = 0):
        self._policies = {
            SchedulingType.HYBRID: HybridSchedulingPolicy(),
            SchedulingType.SPREAD: SpreadSchedulingPolicy(),
            SchedulingType.RANDOM: RandomSchedulingPolicy(seed),
            SchedulingType.NODE_AFFINITY: NodeAffinitySchedulingPolicy(),
            SchedulingType.NODE_LABEL: NodeLabelSchedulingPolicy(),
        }

    def schedule(self, state, req, options):
        return self._policies[options.scheduling_type].schedule(
            state, req, options)
