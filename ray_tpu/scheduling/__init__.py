from .bundles import PlacementStrategy, schedule_bundles
from .cluster_resources import ClusterResourceManager
from .contract import (AVAIL_SHIFT, INFEASIBLE_KEY, MAX_NODES, SCALE,
                       compute_keys, compute_keys_batch, threshold_fp,
                       unpack_key)
from .oracle import (ClusterState, expand_group_counts, group_requests,
                     schedule_grouped_oracle, schedule_one, schedule_tasks)
from .policy import (CompositeSchedulingPolicy, DeltaScheduler,
                     HybridSchedulingPolicy, ISchedulingPolicy,
                     NodeAffinitySchedulingPolicy, RandomSchedulingPolicy,
                     SchedulingOptions, SchedulingType,
                     SpreadSchedulingPolicy)
from .sharded_delta import ShardedDeltaScheduler, make_delta_scheduler

__all__ = [
    "PlacementStrategy", "schedule_bundles",
    "ClusterResourceManager", "ClusterState", "CompositeSchedulingPolicy",
    "DeltaScheduler",
    "HybridSchedulingPolicy", "ISchedulingPolicy", "INFEASIBLE_KEY",
    "MAX_NODES", "NodeAffinitySchedulingPolicy", "RandomSchedulingPolicy",
    "SCALE", "AVAIL_SHIFT", "SchedulingOptions", "SchedulingType",
    "ShardedDeltaScheduler", "make_delta_scheduler",
    "SpreadSchedulingPolicy", "compute_keys", "compute_keys_batch",
    "expand_group_counts",
    "group_requests", "schedule_grouped_oracle", "schedule_one",
    "schedule_tasks", "threshold_fp", "unpack_key",
]
