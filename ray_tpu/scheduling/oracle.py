"""CPU reference scheduler — the bit-for-bit parity anchor.

Implements the contract in ``contract.py`` with a straightforward
task-at-a-time loop, exactly the way the reference's raylet invokes
``HybridSchedulingPolicy::Schedule`` once per task from
``ClusterTaskManager::ScheduleAndDispatchTasks`` (SURVEY.md §3.2 hot loop).
The TPU kernel (ray_tpu/ops/hybrid_kernel.py) must reproduce this loop's
placements exactly; tests/test_parity.py asserts it property-style.

Nothing here is performance-relevant — clarity and obvious correctness win.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from .contract import (AVAIL_SHIFT, INFEASIBLE_KEY, compute_keys,
                       threshold_fp)


@dataclass
class ClusterState:
    """Dense mirror of per-node resource state.

    Rows are traversal order (the deterministic tie-break order of the
    contract).  The ClusterResourceManager owns the NodeID <-> row mapping.
    """

    totals: np.ndarray            # (N, R) int32 cu
    avail: np.ndarray             # (N, R) int32 cu
    node_mask: np.ndarray = field(default=None)  # (N,) bool; False = dead/pad

    def __post_init__(self):
        self.totals = np.asarray(self.totals, dtype=np.int32)
        self.avail = np.asarray(self.avail, dtype=np.int32)
        if self.node_mask is None:
            self.node_mask = np.ones(self.totals.shape[0], dtype=bool)

    def copy(self) -> "ClusterState":
        return ClusterState(self.totals.copy(), self.avail.copy(),
                            self.node_mask.copy())

    @property
    def num_nodes(self) -> int:
        return self.totals.shape[0]


def _schedule_one_info(state: ClusterState, req: np.ndarray,
                       thr_fp: int, extra_mask: np.ndarray | None,
                       commit: bool, require_available: bool
                       ) -> tuple[int, bool]:
    """(node, consumed): core of schedule_one; consumed=False means the
    placement did not change state (queued or infeasible) — a fixed point
    for identical follow-up requests."""
    mask = state.node_mask if extra_mask is None \
        else (state.node_mask & extra_mask)
    keys = compute_keys(state.totals, state.avail, req, thr_fp, mask)
    node = int(np.argmin(keys))
    if keys[node] == INFEASIBLE_KEY:
        return -1, False
    if (keys[node] >> AVAIL_SHIFT) != 0:             # best is unavailable
        return (-1, False) if require_available else (node, False)
    if commit:
        state.avail[node] -= np.asarray(req, dtype=np.int32)
    return node, commit and bool((np.asarray(req) > 0).any())


def schedule_one(state: ClusterState, req: np.ndarray,
                 thr_fp: int, extra_mask: np.ndarray | None = None,
                 commit: bool = True, require_available: bool = False) -> int:
    """Schedule a single request. Returns node row or -1 (infeasible).

    Decrements ``state.avail`` iff the chosen node is available and
    ``commit`` — feasible-but-unavailable placements queue without consuming
    (contract; reference behavior per SURVEY §2.5 item 4), unless
    ``require_available``, in which case they return -1.
    """
    return _schedule_one_info(state, req, thr_fp, extra_mask, commit,
                              require_available)[0]


def schedule_tasks(state: ClusterState, reqs: np.ndarray,
                   spread_threshold: float | None = None,
                   masks: np.ndarray | None = None) -> np.ndarray:
    """Sequential greedy over a task batch (mutates ``state.avail``).

    reqs: (T, R) int32 cu.  masks: optional (T, N) bool per-task feasibility
    restriction.  Returns (T,) int32 node rows (-1 = infeasible).
    """
    thr = threshold_fp(spread_threshold)
    out = np.empty(reqs.shape[0], dtype=np.int32)
    for t in range(reqs.shape[0]):
        m = masks[t] if masks is not None else None
        out[t] = schedule_one(state, reqs[t], thr, m)
    return out


def group_requests(reqs: np.ndarray, masks: np.ndarray | None = None
                   ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Partition a task batch into scheduling classes.

    Returns (group_reqs (G, R), group_counts (G,), task_group (T,)) with
    groups ordered by first appearance — the contract's batch order.  Tasks
    are one class iff request vectors AND masks match.
    """
    seen: dict[bytes, int] = {}
    group_reqs: list[np.ndarray] = []
    counts: list[int] = []
    task_group = np.empty(reqs.shape[0], dtype=np.int32)
    for t in range(reqs.shape[0]):
        key = reqs[t].tobytes()
        if masks is not None:
            key += masks[t].tobytes()
        g = seen.get(key)
        if g is None:
            g = len(group_reqs)
            seen[key] = g
            group_reqs.append(reqs[t])
            counts.append(0)
        counts[g] += 1
        task_group[t] = g
    return (np.stack(group_reqs).astype(np.int32),
            np.asarray(counts, dtype=np.int32), task_group)


def schedule_grouped_oracle(state: ClusterState, group_reqs: np.ndarray,
                            group_counts: np.ndarray,
                            spread_threshold: float | None = None,
                            group_masks: np.ndarray | None = None,
                            require_available: bool = False) -> np.ndarray:
    """Grouped batch semantics via the sequential loop (mutates state).

    Returns per-(group, node) placement counts (G, N) int32; column index N
    (one past the last node) counts infeasible tasks.  This is the function
    the TPU water-fill kernel must match bit-for-bit.

    ``require_available``: feasible-but-unavailable nodes count as column N
    instead of queueing — the autoscaler's fit-onto-existing-nodes semantics
    (a demand that doesn't fit now must trigger a launch, not wait).
    """
    thr = threshold_fp(spread_threshold)
    G, N = group_reqs.shape[0], state.num_nodes
    counts = np.zeros((G, N + 1), dtype=np.int32)
    for g in range(G):
        m = group_masks[g] if group_masks is not None else None
        remaining = int(group_counts[g])
        while remaining > 0:
            node, consumed = _schedule_one_info(
                state, group_reqs[g], thr, m, True, require_available)
            if consumed:
                counts[g, node] += 1
                remaining -= 1
                continue
            # fixed point: state unchanged => every remaining request of
            # this class lands identically (empty request, queue on the
            # same feasible node, or infeasible) — bit-exact short-cut
            counts[g, node if node >= 0 else N] += remaining
            break
    return counts


def expand_group_counts(counts: np.ndarray, task_group: np.ndarray
                        ) -> np.ndarray:
    """Turn (G, N+1) placement counts into per-task node rows.

    Within a scheduling class, placements are handed out in *key order*
    (cheapest slots first), which for the sequential loop means: the order in
    which the greedy loop produced them.  Reconstructing that order from
    counts alone is not possible — but any within-class assignment of tasks
    to the counted slots is equivalent (tasks in a class are identical), so
    we hand slots out node-row-ascending.  Returns (T,) int32, -1 infeasible.
    """
    G, n_plus_1 = counts.shape
    out = np.empty(task_group.shape[0], dtype=np.int32)
    cursors = [np.repeat(np.arange(n_plus_1), counts[g]) for g in range(G)]
    pos = np.zeros(G, dtype=np.int64)
    for t, g in enumerate(task_group):
        out[t] = cursors[g][pos[g]]
        pos[g] += 1
    out[out == n_plus_1 - 1] = -1
    return out
