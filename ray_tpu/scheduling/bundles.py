"""Bundle (placement-group) scheduling — CPU reference oracle.

Reference parity: upstream Ray's gang scheduler places a placement group's
bundles atomically via ``BundleSchedulingPolicy`` variants —
``BundlePackSchedulingPolicy``, ``BundleSpreadSchedulingPolicy``,
``BundleStrictPackSchedulingPolicy``, ``BundleStrictSpreadSchedulingPolicy``
(``src/ray/raylet/scheduling/policy/bundle_scheduling_policy.cc``, invoked
from ``GcsPlacementGroupScheduler::ScheduleUnplacedBundles``).  [SURVEY.md
§3.5 / §2.1 scheduling row; reference mount empty — semantics re-derived
from the survey's behavioral description: "STRICT_SPREAD: <=1 bundle/node;
STRICT_PACK: all on one; PACK/SPREAD: soft scoring".]

The contract (shared with the device kernel in ray_tpu/ops/bundle_kernel.py)
------------------------------------------------------------------------
Bundles are placed in index order on a snapshot of ``avail``; placement is
all-or-nothing (the caller then runs 2-phase prepare/commit against the
chosen nodes).  Reservation CONSUMES resources, so a bundle may only land on
an *available* node (unlike task scheduling's feasible-queue fallback).

* STRICT_PACK   — one node must hold the elementwise sum of all bundles;
                  chosen by the hybrid key of the summed request.
* STRICT_SPREAD — each bundle goes to a distinct node; bundle b's key is the
                  hybrid key masked to nodes without earlier bundles.
* PACK (soft)   — bundle b first tries nodes already holding one of this
                  group's bundles (min hybrid key among them); if none is
                  available it falls back to all nodes.
* SPREAD (soft) — mirror image: first tries nodes NOT yet holding one of
                  this group's bundles, falls back to reuse.

Soft preference is a two-pass masked argmin, NOT a key-bit: availability
must dominate preference, and the int32 key has no spare bits between the
availability bucket and the score field (contract.py layout).
"""

from __future__ import annotations

import enum

import numpy as np

from .contract import AVAIL_SHIFT, INFEASIBLE_KEY, compute_keys, threshold_fp
from .oracle import ClusterState


class PlacementStrategy(enum.Enum):
    PACK = 0
    SPREAD = 1
    STRICT_PACK = 2
    STRICT_SPREAD = 3


def _best_available(totals, avail, req, thr_fp, mask) -> int:
    """Row of the min-key AVAILABLE node under ``mask``, or -1."""
    keys = compute_keys(totals, avail, req, thr_fp, mask)
    node = int(np.argmin(keys))
    if keys[node] == INFEASIBLE_KEY or (keys[node] >> AVAIL_SHIFT) != 0:
        return -1
    return node


def schedule_bundles(state: ClusterState, bundle_reqs: np.ndarray,
                     strategy: PlacementStrategy,
                     spread_threshold: float | None = None,
                     node_mask: np.ndarray | None = None,
                     commit: bool = True) -> np.ndarray | None:
    """Atomically place a bundle set. Returns (B,) node rows or None.

    bundle_reqs: (B, R) int32 cu.  On success with ``commit`` the chosen
    reservations are subtracted from ``state.avail``; on failure ``state``
    is untouched (all-or-nothing, the PG stays pending).
    """
    bundle_reqs = np.asarray(bundle_reqs, dtype=np.int32)
    thr = threshold_fp(spread_threshold)
    mask = state.node_mask if node_mask is None \
        else state.node_mask & node_mask
    B = bundle_reqs.shape[0]
    avail = state.avail.copy()
    rows = np.empty(B, dtype=np.int32)

    if strategy is PlacementStrategy.STRICT_PACK:
        total = bundle_reqs.sum(axis=0, dtype=np.int64)
        if (total > np.iinfo(np.int32).max).any():
            return None
        node = _best_available(state.totals, avail, total.astype(np.int32),
                               thr, mask)
        if node < 0:
            return None
        rows[:] = node
        avail[node] -= total.astype(np.int32)
    else:
        used = np.zeros(state.num_nodes, dtype=bool)
        for b in range(B):
            req = bundle_reqs[b]
            if strategy is PlacementStrategy.STRICT_SPREAD:
                node = _best_available(state.totals, avail, req, thr,
                                       mask & ~used)
            elif strategy is PlacementStrategy.PACK:
                node = _best_available(state.totals, avail, req, thr,
                                       mask & used)
                if node < 0:
                    node = _best_available(state.totals, avail, req, thr,
                                           mask)
            else:  # SPREAD
                node = _best_available(state.totals, avail, req, thr,
                                       mask & ~used)
                if node < 0:
                    node = _best_available(state.totals, avail, req, thr,
                                           mask)
            if node < 0:
                return None
            rows[b] = node
            used[node] = True
            avail[node] -= req

    if commit:
        state.avail = avail
    return rows
