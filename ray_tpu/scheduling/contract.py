"""The scheduling contract: exact integer semantics shared by the CPU oracle
and the TPU kernel.

Reference parity: this encodes the semantics of upstream Ray's
``HybridSchedulingPolicy`` (``src/ray/raylet/scheduling/policy/
hybrid_scheduling_policy.cc``) and ``LeastResourceScorer``
(``src/ray/raylet/scheduling/policy/scorer.h``), per SURVEY.md §2.5
[reference mount empty — semantics re-derived from the survey's behavioral
description, not copied from source].  BASELINE.json's north star requires the
TPU backend to match the CPU policy bit-for-bit; everything in this module is
therefore *pure integer arithmetic* with explicitly documented widths.

Semantics (the contract)
------------------------
For a request ``r`` (dense int32 cu vector) against node ``n`` with totals
``T_n`` and availables ``A_n``:

* feasible(n)   = all(T_n[i] >= r[i] for r[i] > 0)
* available(n)  = all(A_n[i] >= r[i] for r[i] > 0)
* score(n)      = max over {i : r[i] > 0} of ((T_n[i] - A_n[i] + r[i]) * SCALE)
                  // T_n[i]              -- critical-resource utilization,
                  integer floor division, SCALE = 2**12.  Empty request => 0.
* eff(n)        = 0 if (available(n) and score(n) < threshold_fp) else score(n)
                  -- the hybrid pack/spread bucketing: below-threshold
                  available nodes tie at 0 and fall to traversal order
                  (packing); above it they rank by score (spreading).
* key(n)        = (not available(n)) << 27 | eff(n) << 13 | traversal_index(n)
                  if feasible(n) else INFEASIBLE_KEY
* decision      = argmin over nodes of key(n); INFEASIBLE_KEY everywhere
                  => infeasible (queue until the cluster changes).

A placement on an *available* node decrements its availables by ``r``; a
placement on a feasible-but-unavailable node queues (no decrement) — matching
the reference's "best feasible node" fallback (SURVEY §2.5 item 4).

Batch semantics: one scheduling round partitions the pending queue by
scheduling class (identical (resources, strategy)) and processes classes in
first-appearance order, tasks within a class in queue order.  This is faithful
to the reference, whose ``ClusterTaskManager`` keys its schedule queue by
``SchedulingClass`` and drains it class-by-class (SURVEY §3.2).

Width audit (why int32 suffices end to end, incl. on TPU):
    T, A, r      <= MAX_TOTAL_CU = 2**17
    q = used + r <= 2 * 2**17 = 2**18
    q * SCALE    <= 2**30 < 2**31 - 1          (the score numerator)
    (L+1) * T    <= (2*SCALE + 1) * 2**17 < 2**31   (water-fill inversion;
                    L is capped by the largest permitted threshold
                    2*SCALE + 1 = the autoscaler first-fit threshold)
    key          <  2**28
"""

from __future__ import annotations

import numpy as np

from ..common.config import get_config

SCORE_SCALE_BITS = 12
SCALE = 1 << SCORE_SCALE_BITS          # 4096
NODE_BITS = 13
MAX_NODES = 1 << NODE_BITS             # 8192
SCORE_SHIFT = NODE_BITS
AVAIL_SHIFT = NODE_BITS + 14           # eff(n) <= 2*SCALE < 2**14
INFEASIBLE_KEY = np.int32(2**31 - 1)
MAX_SCORE = 2 * SCALE                  # score of a node at 2x utilization
# Per-(class, node) lease-budget ceiling: the fused beat emits water-fill
# headroom as lease budgets (see compute_budgets); the cap bounds what a
# single grant can hand a raylet and keeps the packed budget tensor well
# inside int32 (avail <= MAX_TOTAL_CU = 2**17, req >= 1 cu).
BUDGET_CAP = 1 << 15


def threshold_fp(spread_threshold: float | None = None) -> int:
    """Spread threshold in score fixed point."""
    t = (get_config().scheduler_spread_threshold
         if spread_threshold is None else spread_threshold)
    return int(round(t * SCALE))


def compute_keys(totals: np.ndarray, avail: np.ndarray, req: np.ndarray,
                 thr_fp: int, node_mask: np.ndarray | None = None
                 ) -> np.ndarray:
    """Packed int32 keys for one request against all nodes (numpy, exact).

    totals/avail: (N, R) int32 cu.  req: (R,) int32 cu.
    node_mask: optional (N,) bool — False rows are treated as infeasible
    (affinity/label constraints, dead nodes, padding rows).
    Returns (N,) int32.
    """
    totals = np.asarray(totals, dtype=np.int64)
    avail = np.asarray(avail, dtype=np.int64)
    req = np.asarray(req, dtype=np.int64)
    n = totals.shape[0]
    req_pos = req > 0

    if not req_pos.any():
        feasible = np.ones(n, dtype=bool)
        available = np.ones(n, dtype=bool)
        score = np.zeros(n, dtype=np.int64)
    else:
        t = totals[:, req_pos]
        a = avail[:, req_pos]
        r = req[req_pos]
        feasible = (t >= r).all(axis=1)
        available = (a >= r).all(axis=1)
        denom = np.where(t > 0, t, 1)
        q = t - a + r
        score = ((q * SCALE) // denom).max(axis=1)

    eff = np.where(available & (score < thr_fp), 0, score)
    key = ((~available).astype(np.int64) << AVAIL_SHIFT) \
        | (eff << SCORE_SHIFT) | np.arange(n, dtype=np.int64)
    key = np.where(feasible, key, np.int64(INFEASIBLE_KEY))
    if node_mask is not None:
        key = np.where(node_mask, key, np.int64(INFEASIBLE_KEY))
    return key.astype(np.int32)


def compute_keys_batch(totals: np.ndarray, avail: np.ndarray,
                       reqs: np.ndarray, thr_fp: int,
                       node_mask: np.ndarray | None = None) -> np.ndarray:
    """Packed keys for a batch of class requests: (C, N) int32.

    The host oracle twin of ``ops.hybrid_kernel.full_rescore`` — the
    carried key tensor a ``DeltaScheduler`` keeps device-resident
    between beats must equal this on the mirrored state, row for row
    (the delta-sequence parity gate).
    """
    reqs = np.asarray(reqs, dtype=np.int64)
    return np.stack([compute_keys(totals, avail, r, thr_fp, node_mask)
                     for r in reqs])


def compute_budgets(totals: np.ndarray, avail: np.ndarray, reqs: np.ndarray,
                    node_mask: np.ndarray | None = None,
                    cap: int = BUDGET_CAP) -> np.ndarray:
    """Per-(class, node) lease budgets from a post-water-fill state.

    The host oracle twin of the budget tensor the fused beat emits
    (``ops.hybrid_kernel.fused_beat`` / ``ShardPlane.fused_beat``): for
    each class ``c`` and node ``n``, how many MORE tasks of ``c`` node
    ``n`` could admit against the availables the beat left behind.

    * feasible(c, n) = all(T_n[i] >= r_c[i] for r_c[i] > 0) and mask(n)
    * fill(c, n)     = min over {i : r_c[i] > 0} of max(A_n[i], 0) // r_c[i]
                       (``cap`` when the class requests nothing — the
                       "zero" lease class is admission-unbounded)
    * budget(c, n)   = clip(fill, 0, cap) if feasible else 0

    ``avail`` is clamped to >= 0 *before* the floor division on both the
    host and device twins — numpy and XLA agree on non-negative ``//``
    but not on negative operands, and overcommitted rows owe 0 headroom
    anyway.  totals/avail: (N, R) int32 cu; reqs: (C, R); returns (C, N)
    int32.
    """
    totals = np.asarray(totals, dtype=np.int64)
    avail = np.maximum(np.asarray(avail, dtype=np.int64), 0)
    reqs = np.atleast_2d(np.asarray(reqs, dtype=np.int64))
    n = totals.shape[0]
    mask = (np.ones(n, dtype=bool) if node_mask is None
            else np.asarray(node_mask, dtype=bool))
    out = np.zeros((reqs.shape[0], n), dtype=np.int32)
    for c, r in enumerate(reqs):
        pos = r > 0
        if not pos.any():
            out[c] = np.where(mask, np.int32(cap), np.int32(0))
            continue
        feas = (totals[:, pos] >= r[pos]).all(axis=1) & mask
        fill = (avail[:, pos] // r[pos]).min(axis=1)
        out[c] = np.where(feas, np.clip(fill, 0, cap), 0).astype(np.int32)
    return out


def unpack_key(key: int) -> tuple[int, int, int]:
    """(unavailable_bucket, eff_score, traversal_index) for debugging."""
    return (int(key) >> AVAIL_SHIFT,
            (int(key) >> SCORE_SHIFT) & ((1 << 14) - 1),
            int(key) & (MAX_NODES - 1))
