"""Simulated serve plane: sharded routers, gossiped load digests and
elastic serve<->batch capacity loaning over the simulated cluster.

The live request plane (``serve/router.py`` + ``serve/gossip.py`` +
``serve/loaning.py``) runs on threads and real actors — a shape the
synchronous single-threaded ``SimTransport`` cannot host.  The
simulator therefore models the SAME control decisions as discrete
events on the virtual clock:

* **Sharded routers.**  Each shard serializes its routing work — one
  admission + placement decision costs ``route_overhead_s`` of shard
  time, exactly the per-request critical section the live
  ``RequestRouter`` holds under its condition variable.  Shard count is
  therefore the request-plane throughput lever, which is what the
  diurnal bench measures (1 shard vs N at identical load).
* **Gossiped load.**  Shards route power-of-two-choices on a digest of
  per-replica load that refreshes only when that replica's node
  heartbeats (``SimHead._h_heartbeat`` -> :meth:`on_heartbeat`), plus
  the shard's own dispatches since the last fold — the same
  bounded-staleness contract as ``serve/gossip.py``.  Staleness is safe
  here for the same reason as in the live plane: replica concurrency
  caps are enforced replica-side, so a stale digest over-QUEUES a
  replica, it never over-RUNS the cap.
* **Capacity loaning.**  When serve backlog crosses the bar the plane
  borrows an idle batch node (it vanishes from ``SimHead._pick_node``
  via the ``reserved`` set), warms it in ``warmup_s`` — far below
  ``boot_delay_s``, the cold-start reference — and reclaims it with
  drain semantics when batch pressure returns or the peak passes:
  stop routing, let inflight finish, hand the row back.  A loaned node
  SIGKILLed mid-anything books the loss exactly once (the loan record
  is popped) and its accepted requests re-dispatch to other replicas.

Determinism contract: same as the rest of the simulator — virtual
clock, all randomness from one Philox stream keyed ``[seed,
0x5E12FE]``, no iteration over unordered sets (``reserved`` is
membership-only), bounded trace recording (aggregate windows + loan
lifecycle events, never per-request events).
"""

from __future__ import annotations

import math
from collections import deque
from dataclasses import dataclass

__all__ = ["SimServePlane", "SimServeParams"]

# latency histogram bucket upper edges (seconds); quantiles are read as
# the upper edge of the covering bucket — deterministic and O(1) memory
_LAT_EDGES = (0.05, 0.1, 0.2, 0.35, 0.5, 0.75, 1.0, 1.5, 2.5, 5.0, 10.0)


@dataclass
class SimServeParams:
    """Shape knobs for the simulated serve plane."""

    num_shards: int = 4
    replica_cap: int = 4            # max concurrent requests per replica
    replica_queue: int = 8          # replica mailbox bound (then bounce)
    service_s: tuple = (0.06, 0.14)     # uniform service time draw
    route_overhead_s: float = 0.002     # serialized shard work/request
    shard_queue: int = 512          # TOTAL admission bound, split across
                                    # shards like the live router's
                                    # _enqueue_locked (shed past it)
    arrival_tick_s: float = 0.1     # Poisson arrival batching quantum
    window_s: float = 15.0          # aggregate trace window
    warmup_s: float = 0.5           # loaned-node warm-up (<< boot_delay_s)
    loan_max: int = 4
    loan_backlog: int = 24          # queued requests that trigger a loan
    loan_reclaim_idle_s: float = 20.0
    tick_s: float = 2.5             # loan state machine period
    sessions: int = 64              # distinct sticky session keys


class _Replica:
    __slots__ = ("nid", "cap", "inflight", "queue", "loaned", "alive",
                 "route_ok", "epoch", "version")

    def __init__(self, nid: str, cap: int, loaned: bool = False):
        self.nid = nid
        self.cap = cap
        self.inflight: dict[int, float] = {}    # rid -> arrival t
        self.queue: deque = deque()             # (rid, arrival t)
        self.loaned = loaned
        self.alive = True
        self.route_ok = True
        self.epoch = 0          # bumped on death: stale completions no-op
        self.version = "v1"     # model version tag (rollout plane re-tags)

    def load(self) -> int:
        return len(self.inflight) + len(self.queue)


class _Shard:
    __slots__ = ("idx", "queue", "routing", "own")

    def __init__(self, idx: int):
        self.idx = idx
        self.queue: deque = deque()     # accepted (rid, arrival t)
        self.routing = False            # serialized: one decision at a time
        self.own: dict[str, int] = {}   # nid -> dispatches since last fold


class SimServePlane:
    """The serve overlay a ``serve_diurnal`` campaign installs on a
    :class:`SimCluster` (as ``cluster.serve_plane``)."""

    def __init__(self, cluster, seed: int = 0,
                 duration: float = 200.0,
                 num_replicas: int | None = None,
                 params: SimServeParams | None = None,
                 base_rps: float | None = None,
                 peak_rps: float | None = None):
        import numpy as np

        self.cluster = cluster
        self.p = params or SimServeParams()
        self.rng = np.random.Generator(np.random.Philox(
            key=[int(seed) & (2 ** 64 - 1), 0x5E12FE]))
        n = num_replicas if num_replicas is not None else \
            max(2, len(cluster.nodes) // 16)
        # base pool: the first n node ids, deterministically — these rows
        # are reserved (SimHead._pick_node and the autoscaler skip them)
        base = sorted(cluster.nodes)[:n]
        self.reserved: set[str] = set(base)
        self.replicas: dict[str, _Replica] = {
            nid: _Replica(nid, self.p.replica_cap) for nid in base}
        self.shards = [_Shard(i) for i in range(self.p.num_shards)]
        self._admit = max(8, self.p.shard_queue // self.p.num_shards)
        self.digest: dict[str, int] = {nid: 0 for nid in base}
        self.loans: dict[str, dict] = {}    # nid -> {state, t0, t_drain}
        # reverse direction (Aryl: train borrows serve capacity at the
        # diurnal trough) — driven entirely by sim/train.py, so on
        # campaigns without a train plane ``lent`` stays empty and every
        # branch below is dead (replay hashes of serve-only runs are
        # untouched)
        self.lent: dict[str, dict] = {}     # nid -> {state, t0}
        self.lends_total = 0
        self.lends_returned = 0
        self.lends_lost = 0

        # diurnal curve: one full cycle over the arrival window, scaled
        # to the base pool's steady-state capacity
        mean_svc = (self.p.service_s[0] + self.p.service_s[1]) / 2.0
        cap_rps = n * self.p.replica_cap / mean_svc
        self.base_rps = base_rps if base_rps is not None else 0.45 * cap_rps
        self.peak_rps = peak_rps if peak_rps is not None else 1.45 * cap_rps
        self.arrival_end = duration * 0.85
        self.pool_capacity_rps = cap_rps

        self.started = False
        self.arrivals_done = False
        self._rid = 0
        self.accepted = 0
        self.completed = 0
        self.shed = 0
        self.redispatched = 0
        self.outstanding = 0        # accepted - completed, by counter
        self.in_route = 0           # popped from a shard, not yet placed
        self.loans_total = 0
        self.reclaims_total = 0
        self.loans_lost = 0
        self.peak_backlog = 0
        self._busy_t = 0.0
        self._reclaim_sum = 0.0
        self._reclaim_max = 0.0
        self._win = {"accepted": 0, "completed": 0, "shed": 0}
        self._hist = [0] * (len(_LAT_EDGES) + 1)
        # model-version plane (sim/rollout.py) — None on every campaign
        # except serve_rolling_update, so no hook below changes the
        # behavior (or replay hash) of existing serve runs
        self.rollout = None

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> None:
        clock, trace = self.cluster.clock, self.cluster.trace
        self.started = True
        trace.rec(clock.monotonic(), "serve_start",
                  replicas=len(self.replicas), shards=len(self.shards),
                  base_rps=round(self.base_rps, 3),
                  peak_rps=round(self.peak_rps, 3))
        clock.call_later(self.p.arrival_tick_s, self._arrivals)
        clock.call_later(self.p.tick_s, self._tick)
        clock.call_later(self.p.window_s, self._window)

    @property
    def terminal(self) -> bool:
        return self.started and self.arrivals_done and \
            self.outstanding == 0 and not self.loans and not self.lent

    # -- arrivals ------------------------------------------------------------
    def _rate(self, t: float) -> float:
        frac = min(1.0, t / max(self.arrival_end, 1e-9))
        return self.base_rps + (self.peak_rps - self.base_rps) * \
            0.5 * (1.0 - math.cos(2.0 * math.pi * frac))

    def _arrivals(self) -> None:
        if not self.cluster.running:
            return
        now = self.cluster.clock.monotonic()
        if now >= self.arrival_end:
            self.arrivals_done = True
            return
        n = int(self.rng.poisson(self._rate(now) * self.p.arrival_tick_s))
        for _ in range(n):
            session = int(self.rng.integers(self.p.sessions))
            # session stickiness: Knuth-hash rendezvous, same shape as
            # RouterGroup.shard_for — a session always lands one shard
            shard = self.shards[
                (session * 2654435761) % (1 << 32) % len(self.shards)]
            if len(shard.queue) >= self._admit:
                self.shed += 1
                self._win["shed"] += 1
                continue
            self._rid += 1
            self.accepted += 1
            self.outstanding += 1
            self._win["accepted"] += 1
            shard.queue.append((self._rid, now))
            if self.rollout is not None:
                self.rollout.note_arrival(self._rid, session, now)
            self._pump(shard)
        self.cluster.clock.call_later(self.p.arrival_tick_s,
                                      self._arrivals)

    # -- routing (serialized per shard) --------------------------------------
    def _pump(self, shard: _Shard) -> None:
        if shard.routing or not shard.queue:
            return
        shard.routing = True
        rid, t_arr = shard.queue.popleft()
        self.in_route += 1
        self.cluster.clock.call_later(
            self.p.route_overhead_s,
            lambda: self._dispatch(shard, rid, t_arr))

    def _viewed_load(self, shard: _Shard, nid: str) -> int:
        return self.digest.get(nid, 0) + shard.own.get(nid, 0)

    def _dispatch(self, shard: _Shard, rid: int, t_arr: float) -> None:
        shard.routing = False
        if not self.cluster.running:
            return
        live = [r for r in self.replicas.values()
                if r.alive and r.route_ok]
        if not live:
            # momentarily no routable replica (mass kill, loan warming):
            # park and retry — the request is accepted, never dropped
            self.in_route -= 1
            shard.queue.appendleft((rid, t_arr))
            self.cluster.clock.call_later(1.0, lambda: self._pump(shard))
            return
        if self.rollout is not None:
            # session-version pinning: candidates narrow to the pinned
            # version (never to empty — the pin migrates instead)
            live = self.rollout.filter_candidates(rid, live)
        if len(live) == 1:
            cands = [live[0]]
        else:
            i = int(self.rng.integers(len(live)))
            j = int(self.rng.integers(len(live)))
            a, b = live[i], live[j]
            if self._viewed_load(shard, a.nid) <= \
                    self._viewed_load(shard, b.nid):
                cands = [a, b]
            else:
                cands = [b, a]
        bound = self.p.replica_cap + self.p.replica_queue
        for rep in cands:
            # cap + mailbox enforced replica-side on ACTUAL load: a
            # stale digest can pick a full replica, but the replica
            # bounces it back to the shard instead of over-running
            if rep.load() < bound:
                shard.own[rep.nid] = shard.own.get(rep.nid, 0) + 1
                self.in_route -= 1
                self._deliver(rep, rid, t_arr)
                self._pump(shard)
                return
        # every candidate full: back-pressure into the shard queue —
        # a completion (or the tick backstop) pumps the shard again
        self.in_route -= 1
        shard.queue.appendleft((rid, t_arr))
        self.cluster.clock.call_later(0.05, lambda: self._pump(shard))

    def _deliver(self, rep: _Replica, rid: int, t_arr: float) -> None:
        if len(rep.inflight) < rep.cap:
            self._begin(rep, rid, t_arr)
        else:
            # cap enforced replica-side: over-queue, never over-run
            rep.queue.append((rid, t_arr))

    def _begin(self, rep: _Replica, rid: int, t_arr: float) -> None:
        rep.inflight[rid] = t_arr
        svc = float(self.rng.uniform(*self.p.service_s))
        epoch = rep.epoch
        self.cluster.clock.call_later(
            svc, lambda: self._complete(rep.nid, rid, epoch))

    def _complete(self, nid: str, rid: int, epoch: int) -> None:
        rep = self.replicas.get(nid)
        if rep is None or rep.epoch != epoch or rid not in rep.inflight:
            return      # replica died meanwhile; request re-dispatched
        t_arr = rep.inflight.pop(rid)
        now = self.cluster.clock.monotonic()
        lat = now - t_arr
        k = 0
        while k < len(_LAT_EDGES) and lat > _LAT_EDGES[k]:
            k += 1
        self._hist[k] += 1
        self.completed += 1
        self.outstanding -= 1
        self._win["completed"] += 1
        if self.rollout is not None:
            self.rollout.on_complete(rid, rep.version)
        if rep.queue:
            nrid, nt = rep.queue.popleft()
            self._begin(rep, nrid, nt)
        # a slot (or mailbox room) freed: shards with parked work retry
        for shard in self.shards:
            self._pump(shard)

    # -- gossip fold (piggybacked on node heartbeats) ------------------------
    def on_heartbeat(self, nid: str) -> None:
        rep = self.replicas.get(nid)
        if rep is None:
            return
        self.digest[nid] = rep.load()
        for shard in self.shards:
            shard.own.pop(nid, None)

    # -- failure plumbing ----------------------------------------------------
    def on_node_killed(self, nid: str) -> None:
        if nid in self.replicas:
            self._replica_dead(nid)
        elif nid in self.loans:
            # killed while still warming: no replica yet, book the loss
            self.loans.pop(nid)
            self.reserved.discard(nid)
            self.loans_lost += 1
            self.cluster.trace.rec(self.cluster.clock.monotonic(),
                                   "loan_lost", node=nid, phase="warming")
        elif nid in self.lent:
            # died while fully lent out (no replica on it): the lend
            # record pops HERE and only here — booked exactly once even
            # when the train plane also sees the kill
            lend = self.lent.pop(nid)
            self.reserved.discard(nid)
            self.lends_lost += 1
            self.cluster.trace.rec(self.cluster.clock.monotonic(),
                                   "reverse_lend_lost", node=nid,
                                   phase=lend["state"])

    def _replica_dead(self, nid: str) -> None:
        rep = self.replicas.pop(nid, None)
        if rep is None:
            return
        rep.alive = False
        rep.epoch += 1
        moved = list(rep.inflight.items()) + list(rep.queue)
        for rid, t_arr in moved:
            # accepted work survives its replica: back into a shard
            shard = self.shards[rid % len(self.shards)]
            shard.queue.append((rid, t_arr))
        self.redispatched += len(moved)
        for shard in self.shards:
            shard.own.pop(nid, None)
        self.digest.pop(nid, None)
        self.reserved.discard(nid)
        loan = self.loans.pop(nid, None)
        lend = self.lent.pop(nid, None)
        now = self.cluster.clock.monotonic()
        if loan is not None:
            self.loans_lost += 1    # popped record: booked exactly once
            self.cluster.trace.rec(now, "loan_lost", node=nid,
                                   phase=loan["state"],
                                   redispatched=len(moved))
        elif lend is not None:
            # died while draining toward the train plane: same
            # popped-record exactly-once contract as the forward loans
            self.lends_lost += 1
            self.cluster.trace.rec(now, "reverse_lend_lost", node=nid,
                                   phase=lend["state"],
                                   redispatched=len(moved))
        else:
            self.cluster.trace.rec(now, "serve_replica_dead", node=nid,
                                   redispatched=len(moved))
        for shard in self.shards:
            self._pump(shard)

    # -- the loan state machine ----------------------------------------------
    def _backlog(self) -> int:
        return sum(len(s.queue) for s in self.shards) + \
            sum(len(r.queue) for r in self.replicas.values())

    def _node_alive(self, nid: str) -> bool:
        node = self.cluster.nodes.get(nid)
        return node is not None and node.alive

    def _tick(self) -> None:
        if not self.cluster.running:
            return
        clock, trace = self.cluster.clock, self.cluster.trace
        now = clock.monotonic()
        # sweep: replicas/loans whose node died without a kill callback
        # (campaign drain faults make serve nodes exit cleanly)
        for nid in [n for n in self.replicas if not self._node_alive(n)]:
            self._replica_dead(nid)
        for nid in [n for n in self.loans
                    if n not in self.replicas and not self._node_alive(n)]:
            self.on_node_killed(nid)
        for nid in [n for n in self.lent
                    if n not in self.replicas and not self._node_alive(n)]:
            self.on_node_killed(nid)

        backlog = self._backlog()
        self.peak_backlog = max(self.peak_backlog, backlog)
        if backlog:
            for shard in self.shards:   # lost-wakeup backstop
                self._pump(shard)
        if backlog:
            self._busy_t = now
        head = self.cluster.head
        batch_pressure = head is not None and head.alive and \
            bool(head.pending)

        # advance draining loans: inflight drained -> row goes back
        for nid in [n for n, lo in self.loans.items()
                    if lo["state"] == "draining"]:
            rep = self.replicas.get(nid)
            if rep is not None and rep.load() == 0:
                reclaim_s = now - self.loans[nid]["t_drain"]
                self.replicas.pop(nid)
                self.digest.pop(nid, None)
                for shard in self.shards:
                    shard.own.pop(nid, None)
                self.reserved.discard(nid)      # batch can place again
                self.loans.pop(nid)
                self.reclaims_total += 1
                self._reclaim_sum += reclaim_s
                self._reclaim_max = max(self._reclaim_max, reclaim_s)
                trace.rec(now, "loan_reclaimed", node=nid,
                          reclaim_s=round(reclaim_s, 4),
                          cold_start_s=self.cluster.params.boot_delay_s)

        # start a reclaim: batch pressure pulls the newest loan back
        # immediately; otherwise idle loans drain after the peak passes
        idle = (backlog == 0 and
                now - self._busy_t >= self.p.loan_reclaim_idle_s)
        if batch_pressure or idle or self.arrivals_done and backlog == 0:
            for nid in [n for n in reversed(self.loans)
                        if self.loans[n]["state"] == "active"]:
                rep = self.replicas.get(nid)
                if rep is None:
                    continue
                if idle or batch_pressure or rep.load() == 0:
                    rep.route_ok = False
                    self.loans[nid]["state"] = "draining"
                    self.loans[nid]["t_drain"] = now
                    trace.rec(now, "loan_reclaim_started", node=nid,
                              reason="batch_pressure" if batch_pressure
                              else "idle")
                    break       # gentle: one reclaim per tick

        # take a new loan: backlog over the bar and room under the cap
        want_loan = (backlog >= self.p.loan_backlog and
                     len(self.loans) < self.p.loan_max and
                     not self.arrivals_done)
        if not want_loan and self.outstanding and not self.replicas \
                and not self.loans:
            # rescue: every replica died and nothing is warming —
            # accepted work must still finish, so borrow regardless
            want_loan = True
        if want_loan:
            nid = self._pick_idle_batch_node()
            if nid is not None:
                self.reserved.add(nid)      # off the batch market NOW
                self.loans[nid] = {"state": "warming", "t0": now,
                                   "t_drain": 0.0}
                self.loans_total += 1
                trace.rec(now, "loan_started", node=nid,
                          backlog=backlog,
                          warmup_s=self.p.warmup_s)
                clock.call_later(self.p.warmup_s,
                                 lambda: self._loan_ready(nid))
        clock.call_later(self.p.tick_s, self._tick)

    def _pick_idle_batch_node(self) -> str | None:
        head = self.cluster.head
        if head is None or not head.alive:
            return None
        for nid in head._node_order:
            row = head.nodes.get(nid)
            if row is None or row["state"] != "alive" or row["suspect"]:
                continue
            if row["running"] or nid in self.reserved:
                continue
            if not self._node_alive(nid):
                continue
            return nid
        return None

    def _loan_ready(self, nid: str) -> None:
        loan = self.loans.get(nid)
        if loan is None or loan["state"] != "warming":
            return      # lost or reclaimed while warming
        if not self._node_alive(nid):
            self.on_node_killed(nid)
            return
        loan["state"] = "active"
        self.replicas[nid] = _Replica(nid, self.p.replica_cap,
                                      loaned=True)
        self.digest[nid] = 0
        if self.rollout is not None:
            # graft-on-pull: a late-joining replica adopts the
            # phase-appropriate model version
            self.rollout.on_replica_added(nid)
        self.cluster.trace.rec(
            self.cluster.clock.monotonic(), "loan_active", node=nid,
            warmup_s=self.p.warmup_s,
            cold_start_s=self.cluster.params.boot_delay_s)
        for shard in self.shards:
            self._pump(shard)

    # -- reverse loaning: the train plane borrows a serve replica node -------
    # Same Aryl drain-reclaim semantics as the forward direction, with
    # the roles swapped: serve is the lender, train the borrower.  The
    # lender keeps the row in ``reserved`` for the whole lend (batch
    # never places on it) and books a mid-lend death exactly once by
    # popping the record.

    def can_lend(self) -> bool:
        """True when serve is at the trough: low backlog, no forward
        loans outstanding, and at least two routable base replicas
        would remain after lending one out."""
        routable = [r for r in self.replicas.values()
                    if r.alive and r.route_ok and not r.loaned]
        return (self.started and not self.arrivals_done and
                len(routable) > 2 and not self.loans and
                self._backlog() < max(1, self.p.loan_backlog // 4))

    def begin_lend(self) -> str | None:
        """Stop routing to one idle base replica and start draining it
        toward the train plane.  Returns its nid, or None."""
        if not self.can_lend():
            return None
        now = self.cluster.clock.monotonic()
        for nid in sorted(self.replicas, reverse=True):
            rep = self.replicas[nid]
            if not rep.alive or not rep.route_ok or rep.loaned or \
                    nid in self.lent:
                continue
            rep.route_ok = False
            self.lent[nid] = {"state": "draining", "t0": now}
            self.lends_total += 1
            self.cluster.trace.rec(now, "reverse_lend_started",
                                   node=nid, backlog=self._backlog())
            return nid
        return None

    def lend_ready(self, nid: str) -> bool:
        """True once the draining replica emptied and the row was
        handed over (replica popped; the train plane owns the node
        until :meth:`end_lend` or death)."""
        lend = self.lent.get(nid)
        if lend is None:
            return False
        if lend["state"] == "lent":
            return True
        rep = self.replicas.get(nid)
        if rep is None or not self._node_alive(nid):
            return False
        if rep.load() != 0:
            return False
        self.replicas.pop(nid)
        self.digest.pop(nid, None)
        for shard in self.shards:
            shard.own.pop(nid, None)
        lend["state"] = "lent"
        self.cluster.trace.rec(self.cluster.clock.monotonic(),
                               "reverse_lend_active", node=nid)
        return True

    def wants_back(self) -> bool:
        """Serve pressure: when True the train plane must return every
        borrowed replica at its next epoch boundary (drain-reclaim, the
        mirror of batch_pressure in the forward direction)."""
        return self.arrivals_done or \
            self._backlog() >= max(1, self.p.loan_backlog // 2)

    def end_lend(self, nid: str) -> None:
        """Train hands the node back alive: the replica is re-created
        and routing resumes.  A no-op if death already popped the
        record (loss was booked there)."""
        lend = self.lent.pop(nid, None)
        if lend is None:
            return
        now = self.cluster.clock.monotonic()
        if not self._node_alive(nid):
            self.reserved.discard(nid)
            self.lends_lost += 1
            self.cluster.trace.rec(now, "reverse_lend_lost", node=nid,
                                   phase=lend["state"])
            return
        self.lends_returned += 1
        rep = self.replicas.get(nid)
        if rep is not None:
            rep.route_ok = True     # returned before the drain finished
        else:
            self.replicas[nid] = _Replica(nid, self.p.replica_cap)
            self.digest[nid] = 0
            if self.rollout is not None:
                self.rollout.on_replica_added(nid)
        self.cluster.trace.rec(now, "reverse_lend_returned", node=nid)
        for shard in self.shards:
            self._pump(shard)

    # -- aggregate trace window ----------------------------------------------
    def _window(self) -> None:
        if not self.cluster.running:
            return
        clock = self.cluster.clock
        w = self._win
        if w["accepted"] or w["completed"] or w["shed"] or self.loans:
            self.cluster.trace.rec(
                clock.monotonic(), "serve_window",
                accepted=w["accepted"], completed=w["completed"],
                shed=w["shed"], backlog=self._backlog(),
                loans=len(self.loans))
        self._win = {"accepted": 0, "completed": 0, "shed": 0}
        if not self.terminal:
            clock.call_later(self.p.window_s, self._window)

    # -- invariants ----------------------------------------------------------
    def check(self, strict: bool = False, now: float | None = None,
              grace: float = 10.0) -> tuple[list[str], int]:
        """Serve-plane invariants, called from
        :func:`sim.invariants.check_invariants`: accepted requests are
        never lost (counter vs structural sum), loans conserve
        (``loans_total == active + reclaimed + lost`` — a SIGKILL
        mid-reclaim must book the loss exactly once, never zero or
        twice), loan drains converge, and — strictly, after quiesce —
        everything accepted completed and every loan was reclaimed or
        booked lost."""
        from .invariants import fmt_violation

        violations: list[str] = []
        checks = 0
        if now is None:
            now = self.cluster.clock.monotonic()
        checks += 1
        accounted = sum(len(s.queue) for s in self.shards) + \
            self.in_route + \
            sum(r.load() for r in self.replicas.values())
        if accounted != self.outstanding:
            violations.append(fmt_violation(
                "serve-accounting", now,
                f"{self.outstanding} outstanding by counter, "
                f"{accounted} accounted in queues"))
        checks += 1
        if self.accepted != self.completed + self.outstanding:
            violations.append(fmt_violation(
                "serve-conservation", now,
                f"accepted={self.accepted} != "
                f"completed={self.completed} + "
                f"outstanding={self.outstanding}"))
        checks += 1
        if self.loans_total != (len(self.loans) + self.reclaims_total +
                                self.loans_lost):
            violations.append(fmt_violation(
                "loan-conservation", now,
                f"loans_total={self.loans_total} != "
                f"active={len(self.loans)} + "
                f"reclaimed={self.reclaims_total} + "
                f"lost={self.loans_lost}"))
        checks += 1
        if self.lends_total != (len(self.lent) + self.lends_returned +
                                self.lends_lost):
            violations.append(fmt_violation(
                "loan-conservation", now,
                f"reverse lends_total={self.lends_total} != "
                f"lent={len(self.lent)} + "
                f"returned={self.lends_returned} + "
                f"lost={self.lends_lost}"))
        drain_cap = self.cluster.params.drain_deadline_s + grace
        for nid, loan in self.loans.items():
            if loan["state"] != "draining":
                continue
            checks += 1
            if now - loan["t_drain"] > drain_cap and \
                    self._node_alive(nid):
                violations.append(fmt_violation(
                    "loan-drain-stuck", now,
                    f"{nid} draining for "
                    f"{now - loan['t_drain']:.1f}s"))
        if strict:
            checks += 2
            if self.outstanding:
                violations.append(fmt_violation(
                    "serve-incomplete", now,
                    f"{self.outstanding} accepted requests never "
                    f"completed after quiesce"))
            if self.loans or self.lent:
                violations.append(fmt_violation(
                    "loans-outstanding", now,
                    f"{len(self.loans)} loans / {len(self.lent)} "
                    f"reverse lends neither reclaimed nor booked lost "
                    f"after quiesce"))
        return violations, checks

    # -- reporting -----------------------------------------------------------
    def _quantile(self, q: float) -> float:
        total = sum(self._hist)
        if not total:
            return 0.0
        target = q * total
        acc = 0
        for k, cnt in enumerate(self._hist):
            acc += cnt
            if acc >= target:
                return _LAT_EDGES[k] if k < len(_LAT_EDGES) else \
                    _LAT_EDGES[-1] * 2
        return _LAT_EDGES[-1] * 2

    def stats(self) -> dict:
        return {
            "shards": len(self.shards),
            "replicas": len(self.replicas),
            "pool_capacity_rps": round(self.pool_capacity_rps, 1),
            "accepted": self.accepted,
            "completed": self.completed,
            "shed": self.shed,
            "redispatched": self.redispatched,
            "outstanding": self.outstanding,
            "p50_s": self._quantile(0.50),
            "p99_s": self._quantile(0.99),
            "peak_backlog": self.peak_backlog,
            "loans_total": self.loans_total,
            "reclaims_total": self.reclaims_total,
            "loans_lost": self.loans_lost,
            "lends_total": self.lends_total,
            "lends_returned": self.lends_returned,
            "lends_lost": self.lends_lost,
            "mean_reclaim_s": round(
                self._reclaim_sum / self.reclaims_total, 4)
            if self.reclaims_total else 0.0,
            "max_reclaim_s": round(self._reclaim_max, 4),
            "cold_start_s": self.cluster.params.boot_delay_s,
        }
