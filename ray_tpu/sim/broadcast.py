"""Event-scheduled broadcast waves over the simulated cluster.

The socket relay protocol (``broadcast/relay.py``) runs sessions on
per-request threads and blocks server-side — a shape the synchronous
single-threaded ``SimTransport`` cannot host.  The simulator therefore
models the SAME protocol as discrete chunk-delivery events on the
virtual clock: per-parent serialized uplinks, relay-as-you-receive
(a chunk forwards the moment it lands), deterministic re-parenting
through the ancestor chain when a parent dies, retry-with-backoff when
every candidate is momentarily gone (head restart).  1k-relay-node
waves run in milliseconds of wall time and land in the campaign trace,
so replay hashes cover broadcast behavior bit-for-bit.

Uplink model: parent ``p`` serves one chunk in ``chunk_bytes /
uplink_mbps`` virtual seconds, chunks serialized per parent (children
share the uplink exactly like frames on one NIC) — the same shape the
socket path enforces with ``plane_uplink_mbps`` pacing.
"""

from __future__ import annotations

from ..broadcast.plan import balanced_plan

_HEAD = "head"
_RETRY_S = 5.0          # re-probe period while no parent candidate lives
_MAX_RETRIES = 200      # then the member is marked unreachable


class SimBroadcastWave:
    """One 1->N distribution: a balanced relay tree over ``members``
    rooted at ``root`` (default: the head)."""

    def __init__(self, cluster, wave_id: str, members: list[str],
                 root: str = _HEAD, size_mb: int = 1024,
                 chunk_mb: int = 8, fanout: int = 2,
                 uplink_mbps: float = 1000.0):
        self.cluster = cluster
        self.wave_id = wave_id
        self.members = [m for m in dict.fromkeys(members) if m != root]
        self.root = root
        self.size = int(size_mb) * (1 << 20)
        self.chunk = int(chunk_mb) * (1 << 20)
        self.nchunks = max(1, -(-self.size // self.chunk))
        self.uplink = float(uplink_mbps) * (1 << 20)    # bytes/s
        self.plan = balanced_plan(self.members, root, fanout)
        self.parent_of = dict(self.plan.parent)
        self.have = {root: self.nchunks}
        self.have.update({m: 0 for m in self.members})
        self.up_free = {root: 0.0}      # uplink next-free instant
        self.waiters: dict[str, list] = {}  # parent -> [(child, k)]
        self.retries: dict[str, int] = {}
        self.completed: list[str] = []
        self.unreachable: set[str] = set()
        self.reparents = 0
        self.chunks_delivered = 0
        self.t_start = 0.0
        self.t_done: float | None = None
        self._started = False

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> None:
        clock, trace = self.cluster.clock, self.cluster.trace
        self.t_start = clock.monotonic()
        self._started = True
        trace.rec(self.t_start, "bcast_start", wave=self.wave_id,
                  root=self.root, members=len(self.members),
                  chunks=self.nchunks, fanout=self.plan.relay_fanout())
        for m in self.members:
            self._request(m, 0)
        self._check_done()

    @property
    def terminal(self) -> bool:
        return self._started and \
            len(self.completed) + len(self._dead_members()) + \
            len(self.unreachable) >= len(self.members)

    @property
    def time_to_all(self) -> float | None:
        return None if self.t_done is None else \
            self.t_done - self.t_start

    def unreached_live(self) -> list[str]:
        """Live members without a full replica — the campaign's final
        strict check expects this empty after quiesce."""
        done = set(self.completed)
        return [m for m in self.members
                if m not in done and self._alive(m)]

    # -- failure plumbing ----------------------------------------------------
    def on_node_killed(self, nid: str) -> None:
        """A relay died: orphaned children re-parent through the
        ancestor chain and resume their missing chunks.  Waiters parked
        on the dead node are flushed here (no event would ever wake
        them); in-flight deliveries re-check liveness on landing."""
        if not self._started or self.t_done is not None:
            return
        stuck = self.waiters.pop(nid, [])
        for child, k in stuck:
            self._request(child, k)
        self._check_done()

    # -- internals -----------------------------------------------------------
    def _alive(self, nid: str) -> bool:
        if nid == _HEAD:
            head = self.cluster.head
            return head is not None and head.alive
        node = self.cluster.nodes.get(nid)
        return node is not None and node.alive

    def _dead_members(self) -> list[str]:
        return [m for m in self.members if not self._alive(m)]

    def _pick_parent(self, child: str) -> str | None:
        """Deterministic re-parent order: original ancestor chain
        (ending at the root), then sealed replicas oldest-first.  A
        candidate whose CURRENT parent chain runs through ``child`` is
        skipped (no cycles)."""
        for cand in (*self.plan.fallbacks(child), *self.completed):
            if cand == child or not self._alive(cand):
                continue
            node, hops = cand, 0
            while node is not None and hops <= len(self.members) + 1:
                if node == child:
                    break
                node = self.parent_of.get(node)
                hops += 1
            else:
                node = None
            if node == child:
                continue
            return cand
        return None

    def _request(self, child: str, k: int) -> None:
        """Child wants chunk ``k``: serve it from the current parent's
        uplink if the parent has it, park as a waiter if not yet, or
        re-parent if the parent is gone."""
        clock = self.cluster.clock
        if not self._alive(child) or self.t_done is not None:
            return
        parent = self.parent_of.get(child)
        if parent is None or not self._alive(parent):
            cand = self._pick_parent(child)
            if cand is None:
                n = self.retries.get(child, 0) + 1
                self.retries[child] = n
                if n > _MAX_RETRIES:
                    self.unreachable.add(child)
                    self.cluster.trace.rec(
                        clock.monotonic(), "bcast_unreachable",
                        wave=self.wave_id, node=child)
                    self._check_done()
                    return
                clock.call_later(_RETRY_S,
                                 lambda: self._request(child, k))
                return
            if cand != parent:
                self.reparents += 1
                self.cluster.trace.rec(
                    clock.monotonic(), "bcast_reparent",
                    wave=self.wave_id, node=child, parent=cand)
            self.parent_of[child] = cand
            parent = cand
        if self.have.get(parent, 0) > k:
            now = clock.monotonic()
            nbytes = min(self.chunk, self.size - k * self.chunk)
            dur = nbytes / self.uplink
            begin = max(now, self.up_free.get(parent, 0.0))
            self.up_free[parent] = begin + dur
            clock.call_later(
                begin + dur - now,
                lambda: self._deliver(child, k, parent))
        else:
            self.waiters.setdefault(parent, []).append((child, k))

    def _deliver(self, child: str, k: int, parent: str) -> None:
        if not self._started or self.t_done is not None or \
                not self._alive(child):
            return
        if not self._alive(parent):
            # the sender died mid-chunk: the bytes never finished —
            # refetch through a new parent, nothing is lost
            self._request(child, k)
            return
        if self.have[child] > k:
            return      # duplicate (re-requested during a gray window)
        self.have[child] = k + 1
        self.chunks_delivered += 1
        # relay-as-you-receive: children parked on this chunk go NOW
        still = []
        for gc, wk in self.waiters.pop(child, []):
            if wk < self.have[child]:
                self._request(gc, wk)
            else:
                still.append((gc, wk))
        if still:
            self.waiters.setdefault(child, []).extend(still)
        if self.have[child] >= self.nchunks:
            self.completed.append(child)
            self.cluster.trace.rec(
                self.cluster.clock.monotonic(), "bcast_node_complete",
                wave=self.wave_id, node=child)
            self._check_done()
        else:
            self._request(child, k + 1)

    def _check_done(self) -> None:
        if self.t_done is None and self.terminal:
            self.t_done = self.cluster.clock.monotonic()
            self.cluster.trace.rec(
                self.t_done, "bcast_complete", wave=self.wave_id,
                reached=len(self.completed),
                dead=len(self._dead_members()),
                unreachable=len(self.unreachable),
                reparents=self.reparents,
                chunks=self.chunks_delivered,
                seconds=round(self.t_done - self.t_start, 6))
