"""Coverage-guided adversarial campaign search over the simulator.

``simulate`` replays a hand-scripted archetype; ``hunt`` *searches*.
A fault schedule is a first-class, serializable genome — typed fault
ops over nodes/links/head/standby/loans/drains with virtual-clock
timestamps — and because a campaign is a pure function of
``(nodes, seed, campaign, faults, duration, schedule)``, any genome
replays bit-identically.  The hunt mutates genomes under one seeded
Philox stream (splice, retime, retarget, drop, duplicate, insert,
densify-around-prior-near-misses), keeps the ones that reach new
coverage, and on any invariant violation delta-debugs the failing
schedule with :func:`minimize.ddmin` down to a 1-minimal genome,
emitting a ``ray_tpu-hunt-finding/1`` artifact with the minimized
genome, its trace hash and a repro command
(``ray_tpu hunt --repro <artifact>``).

The coverage signal is cheap by construction: a :class:`RunCoverage`
sink attached to the trace observes every event (including past the
storage cap) but never feeds the replay hash, so instrumented and
uninstrumented runs share fingerprints.  Coverage keys are invariant-
check sites reached plus state-machine edges exercised — lease epoch
bumps, broadcast re-parent depth, loan/reclaim phases, standby
promotion gates, node life-cycle transitions.

Everything here draws from Philox streams keyed by the hunt seed: the
same ``(seed, budget, nodes)`` finds the same failures in the same
order.  No wall-clock reads — callers time the hunt themselves.
"""

from __future__ import annotations

import hashlib
import json
from dataclasses import asdict, dataclass, field, fields, replace

from .campaign import (CAMPAIGNS, build_schedule, knob_snapshot,
                       run_campaign)
from .cluster import SimParams
from .invariants import violation_names
from .minimize import ddmin

__all__ = ["Genome", "RunCoverage", "Mutator", "HuntFinding",
           "HuntResult", "hunt", "seed_genomes", "run_genome",
           "minimize_genome", "load_finding", "replay_finding",
           "FINDING_FORMAT"]

FINDING_FORMAT = "ray_tpu-hunt-finding/1"

# Philox lane for mutation draws, distinct from the campaign stream
_HUNT_KEY = 0x48554E54             # "HUNT"

_MUTATIONS = ("retime", "retarget", "drop", "duplicate", "insert",
              "splice", "densify")

# ops that carry a node-id / link-addr target (retarget candidates)
_NODE_OPS = ("kill_node", "drain")
_ADDR_OPS = ("gray_slow", "gray_heal")


# ---------------------------------------------------------------------------
# genome


@dataclass
class Genome:
    """One fault schedule plus the base args that derive its job load.

    ``ops`` is ``[(t, op, kwargs), ...]`` in virtual seconds — exactly
    the ``schedule`` override :func:`campaign.run_campaign` accepts.
    The base ``(nodes, seed, campaign, faults, duration)`` tuple pins
    the background job schedule (job draws precede fault draws on the
    campaign Philox stream), so a genome replays bit-identically
    regardless of how far its ops have mutated from the archetype."""

    nodes: int
    seed: int
    campaign: str
    faults: int
    duration: float
    ops: list = field(default_factory=list)
    parent: str | None = None       # key() of the mutated-from genome
    mutation: str | None = None     # "+"-joined mutation kinds applied

    def canonical(self) -> dict:
        # kwargs pass through JSON so in-memory tuples (partition
        # pair lists) and their round-tripped list forms are identical
        return {
            "nodes": self.nodes, "seed": self.seed,
            "campaign": self.campaign, "faults": self.faults,
            "duration": self.duration,
            "ops": [[round(float(t), 6), op,
                     json.loads(json.dumps(kw))]
                    for t, op, kw in self.ops],
        }

    def key(self) -> str:
        """Short content hash — corpus identity and artifact naming."""
        blob = json.dumps(self.canonical(), sort_keys=True,
                          separators=(",", ":")).encode()
        return hashlib.sha256(blob).hexdigest()[:12]

    def to_dict(self) -> dict:
        doc = self.canonical()
        if self.parent:
            doc["parent"] = self.parent
        if self.mutation:
            doc["mutation"] = self.mutation
        return doc

    @classmethod
    def from_dict(cls, doc: dict) -> "Genome":
        return cls(nodes=int(doc["nodes"]), seed=int(doc["seed"]),
                   campaign=doc["campaign"], faults=int(doc["faults"]),
                   duration=float(doc["duration"]),
                   ops=[(float(t), op, dict(kw))
                        for t, op, kw in doc["ops"]],
                   parent=doc.get("parent"),
                   mutation=doc.get("mutation"))


def seed_genomes(nodes: int, seed: int, faults: int, duration: float,
                 campaigns=None) -> list:
    """The hand-scripted archetypes as seed genomes: each campaign's
    deterministic fault schedule, lifted into an explicit ops list the
    mutator can splice across archetype boundaries."""
    import numpy as np

    out = []
    for campaign in (campaigns or CAMPAIGNS):
        rng = np.random.Generator(np.random.Philox(
            key=[int(seed) & (2 ** 64 - 1), 0xC0FFEE]))
        _jobs, sched = build_schedule(campaign, rng, nodes, faults,
                                      duration)
        # times rounded to the canonical 6dp at creation, so the
        # in-memory schedule and its JSON round-trip replay identically
        ops = [(round(float(t), 6), op, kw) for t, op, kw in sched]
        out.append(Genome(nodes=nodes, seed=seed, campaign=campaign,
                          faults=faults, duration=duration, ops=ops))
    return out


def run_genome(genome: Genome, params: SimParams | None = None,
               coverage=None, out: str | None = None):
    """One deterministic sim run of a genome; returns the
    :class:`campaign.CampaignResult`."""
    return run_campaign(genome.nodes, seed=genome.seed,
                        campaign=genome.campaign, faults=genome.faults,
                        duration=genome.duration, params=params,
                        schedule=genome.ops, coverage=coverage, out=out)


# ---------------------------------------------------------------------------
# coverage


def _bucket(n: int) -> int:
    """Log2 bucket, capped — depth-ish signals stay low-cardinality."""
    return min(max(int(n), 0).bit_length(), 8)


class RunCoverage:
    """Coverage sink for one run, attached via ``Trace.cov``.

    ``keys`` is the run's coverage set: invariant-check sites reached,
    fault ops actually applied, state-machine edges exercised (lease
    epoch bumps bucketed log2, broadcast re-parent volume, loan and
    reclaim phases, promotion/restore gates, node life-cycle).
    ``hot_times`` collects virtual timestamps where something
    interesting happened — mid-run violations, node deaths, standby
    promotions — the mutator's densify target list."""

    _EDGE_KINDS = frozenset((
        "node_dead", "node_removed", "drain_start", "quarantine",
        "unquarantine", "reconstruct", "scale_up", "head_restore",
        "standby_promote", "lease_requeued", "loan_started",
        "loan_reclaim_started", "loan_reclaimed", "loan_lost",
        "serve_replica_dead", "bcast_start", "bcast_complete",
    ))
    _HOT_KINDS = frozenset(("node_dead", "standby_promote"))
    _HOT_CAP = 64

    def __init__(self):
        self.keys: set = set()
        self.hot_times: list = []
        self._reparents = 0

    def note(self, ev: dict) -> None:
        kind = ev["kind"]
        if kind == "fault":
            self.keys.add(("fault", ev.get("op")))
        elif kind == "invariant_check":
            self.keys.add(("site", ev.get("stage")))
            if ev.get("violations"):
                self.keys.add(("violated", ev.get("stage")))
                self._hot(ev["t"])
        elif kind == "lease_revoked":
            self.keys.add(("epoch", _bucket(ev.get("epoch", 0))))
        elif kind == "bcast_reparent":
            self._reparents += 1
            self.keys.add(("reparent", _bucket(self._reparents)))
        elif kind in self._EDGE_KINDS:
            self.keys.add(("edge", kind))
            if kind in self._HOT_KINDS:
                self._hot(ev["t"])

    def _hot(self, t: float) -> None:
        if len(self.hot_times) < self._HOT_CAP:
            self.hot_times.append(float(t))


# ---------------------------------------------------------------------------
# mutation


class Mutator:
    """All schedule mutations, drawn from one Philox stream keyed by
    the hunt seed — the whole search replays from ``(seed, budget)``."""

    def __init__(self, seed: int, nodes: int):
        import numpy as np

        self._rng = np.random.Generator(np.random.Philox(
            key=[int(seed) & (2 ** 64 - 1), _HUNT_KEY]))
        self.nodes = nodes

    # -- draws ---------------------------------------------------------------
    def pick_parent(self, corpus: list) -> Genome:
        return corpus[int(self._rng.integers(0, len(corpus)))]

    def _node(self) -> str:
        return f"n{int(self._rng.integers(0, self.nodes)):05d}"

    def _time(self, duration: float) -> float:
        return round(float(self._rng.uniform(
            duration * 0.05, duration * 0.85)), 3)

    def _fresh_op(self, duration: float) -> list:
        """One new fault (plus its heal twin where the op has one) —
        the same vocabulary :func:`campaign.build_schedule` emits."""
        rng = self._rng
        kind = ("kill_node", "drain", "gray_slow", "partition",
                "kill_head", "broadcast")[int(rng.integers(0, 6))]
        t = self._time(duration)
        heal = round(float(rng.uniform(8.0, 25.0)), 3)
        if kind == "kill_node" or kind == "drain":
            return [(t, kind, {"node": self._node()})]
        if kind == "gray_slow":
            addr = f"sim://{self._node()}"
            return [(t, "gray_slow", {"addr": addr}),
                    (t + heal, "gray_heal", {"addr": addr})]
        if kind == "partition":
            addr = f"sim://{self._node()}"
            shape = int(rng.integers(0, 4))
            if shape == 0:
                pairs = [["sim://head", addr]]
            elif shape == 1:
                pairs = [[addr, "sim://head"]]
            elif shape == 2:
                pairs = [["sim://standby", "sim://head"]]
            else:
                pairs = [["sim://head", addr], [addr, "sim://head"]]
            return [(t, "partition", {"pairs": pairs}),
                    (t + heal, "heal", {"pairs": pairs})]
        if kind == "kill_head":
            return [(t, "kill_head", {}),
                    (t + heal, "restart_head", {})]
        count = int(rng.integers(2, max(3, self.nodes // 2)))
        rows = sorted(int(x) for x in rng.choice(
            self.nodes, size=min(count, self.nodes), replace=False))
        return [(t, "broadcast", {
            "members": [f"n{r:05d}" for r in rows],
            "size_mb": int(rng.integers(64, 1025)),
            "fanout": int(rng.integers(2, 5))})]

    # -- mutations -----------------------------------------------------------
    def mutate(self, genome: Genome, corpus: list,
               hot_times=()) -> Genome:
        rng = self._rng
        ops = [(float(t), op, dict(kw)) for t, op, kw in genome.ops]
        applied = []
        for _ in range(1 + int(rng.integers(0, 3))):
            kind = _MUTATIONS[int(rng.integers(0, len(_MUTATIONS)))]
            if kind == "densify" and not hot_times:
                kind = "insert"
            if kind in ("retime", "retarget", "drop", "duplicate") \
                    and not ops:
                kind = "insert"
            if kind == "retime":
                i = int(rng.integers(0, len(ops)))
                t, op, kw = ops[i]
                jitter = float(rng.normal(0.0, 12.0))
                t2 = min(max(t + jitter, 0.5),
                         genome.duration * 0.95)
                ops[i] = (round(t2, 3), op, kw)
            elif kind == "retarget":
                idx = [i for i, (_, op, kw) in enumerate(ops)
                       if op in _NODE_OPS or op in _ADDR_OPS
                       or op == "partition" or op == "heal"]
                if not idx:
                    continue
                i = idx[int(rng.integers(0, len(idx)))]
                t, op, kw = ops[i]
                nid = self._node()
                if op in _NODE_OPS:
                    kw = {"node": nid}
                elif op in _ADDR_OPS:
                    kw = {"addr": f"sim://{nid}"}
                else:               # partition/heal: rewrite node ends
                    addr = f"sim://{nid}"
                    kw = {"pairs": [
                        [addr if a.startswith("sim://n") else a,
                         addr if b.startswith("sim://n") else b]
                        for a, b in kw["pairs"]]}
                ops[i] = (t, op, kw)
            elif kind == "drop":
                del ops[int(rng.integers(0, len(ops)))]
            elif kind == "duplicate":
                t, op, kw = ops[int(rng.integers(0, len(ops)))]
                ops.append((self._time(genome.duration), op,
                            dict(kw)))
            elif kind == "insert":
                ops.extend(self._fresh_op(genome.duration))
            elif kind == "splice":
                donor = self.pick_parent(corpus)
                if donor.ops:
                    n = int(rng.integers(1, min(6, len(donor.ops) + 1)))
                    lo = int(rng.integers(
                        0, len(donor.ops) - n + 1))
                    ops.extend((float(t), op, dict(kw)) for t, op, kw
                               in donor.ops[lo:lo + n])
            else:                   # densify around a prior near-miss
                t0 = float(hot_times[int(rng.integers(
                    0, len(hot_times)))])
                for t, op, kw in self._fresh_op(genome.duration):
                    t2 = min(max(t0 + float(rng.uniform(-4.0, 4.0)),
                                 0.5), genome.duration * 0.95)
                    ops.append((round(t2, 3), op, kw))
            applied.append(kind)
        ops.sort(key=lambda e: e[0])
        return replace(genome, ops=ops, parent=genome.key(),
                       mutation="+".join(applied))


# ---------------------------------------------------------------------------
# minimization + findings


def minimize_genome(genome: Genome, signature,
                    params: SimParams | None = None,
                    progress=None) -> tuple:
    """ddmin the genome's ops to a 1-minimal schedule that still
    reproduces ``signature`` (every named invariant still fires — the
    minimized run may surface MORE, never fewer).  Returns
    ``(minimized_genome, stats)``."""
    sig = frozenset(signature)

    def still_fails(ops: list) -> bool:
        res = run_genome(replace(genome, ops=ops), params=params)
        return sig <= violation_names(res.violations)

    min_ops, stats = ddmin(genome.ops, still_fails, progress=progress)
    return (replace(genome, ops=min_ops, parent=genome.key(),
                    mutation="ddmin"), stats)


@dataclass
class HuntFinding:
    """One deduped failure signature with its minimized reproduction."""

    signature: tuple            # sorted invariant names that fired
    genome: Genome              # as found
    minimized: Genome           # after ddmin (== genome if not run)
    found_after_runs: int
    ddmin_probes: int
    violations: list            # from the minimized replay
    trace_hash: str             # fingerprint of the minimized replay
    artifact: str | None = None

    def to_dict(self) -> dict:
        return {
            "format": FINDING_FORMAT,
            "signature": list(self.signature),
            "found_after_runs": self.found_after_runs,
            "fault_ops": len(self.genome.ops),
            "minimized_ops": len(self.minimized.ops),
            "ddmin_probes": self.ddmin_probes,
            "genome": self.genome.to_dict(),
            "minimized": self.minimized.to_dict(),
            "violations": list(self.violations),
            "trace_hash": self.trace_hash,
            "knobs": knob_snapshot(),
            "params": None,     # filled by _write_finding
            "artifact": self.artifact,
            "repro": "ray_tpu hunt --repro <this artifact>",
        }


@dataclass
class HuntResult:
    runs: int
    budget: int
    nodes: int
    seed: int
    findings: list = field(default_factory=list)
    coverage: int = 0
    coverage_keys: list = field(default_factory=list)
    corpus: int = 0
    new_cov_runs: int = 0

    def to_dict(self) -> dict:
        return {
            "runs": self.runs, "budget": self.budget,
            "nodes": self.nodes, "seed": self.seed,
            "findings": [f.to_dict() for f in self.findings],
            "signatures": [list(f.signature) for f in self.findings],
            "coverage": self.coverage,
            "coverage_keys": self.coverage_keys,
            "corpus": self.corpus,
            "new_cov_runs": self.new_cov_runs,
        }


def _write_finding(finding: HuntFinding, out_dir: str,
                   params: SimParams | None) -> str:
    import os

    path = os.path.join(out_dir,
                        f"finding-{finding.minimized.key()}.json")
    finding.artifact = path
    doc = finding.to_dict()
    doc["params"] = asdict(params or SimParams.from_config())
    doc["repro"] = f"ray_tpu hunt --repro {path}"
    os.makedirs(out_dir, exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1)
        f.write("\n")
    return path


def load_finding(path: str) -> dict:
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    if doc.get("format") != FINDING_FORMAT:
        raise ValueError(f"{path}: not a {FINDING_FORMAT} artifact "
                         f"(format={doc.get('format')!r})")
    return doc


def replay_finding(doc: dict, out: str | None = None) -> tuple:
    """Replay a finding's minimized genome under the artifact's own
    knobs and params — reproduction is a pure function of the
    artifact.  Returns ``(result, reproduced)`` where ``reproduced``
    means the trace hash matched AND every signature invariant fired
    again."""
    from ..common.config import Config, get_config

    snapshot = get_config().to_dict()
    Config.reset(doc.get("knobs") or {})
    try:
        params = None
        if doc.get("params"):
            names = {f.name for f in fields(SimParams)}
            params = SimParams(**{k: v for k, v in doc["params"].items()
                                  if k in names})
        genome = Genome.from_dict(doc["minimized"])
        res = run_genome(genome, params=params, out=out)
    finally:
        Config.reset(snapshot)
    reproduced = (res.trace_hash == doc["trace_hash"] and
                  frozenset(doc["signature"]) <=
                  violation_names(res.violations))
    return res, reproduced


# ---------------------------------------------------------------------------
# the hunt


def hunt(budget: int = 120, nodes: int = 24, seed: int = 0,
         faults: int = 24, duration: float = 160.0,
         campaigns=None, params: SimParams | None = None,
         out_dir: str | None = None, minimize: bool = True,
         progress=None) -> HuntResult:
    """Coverage-guided search for invariant violations.

    Evaluates the archetype seed genomes, then spends the remaining
    ``budget`` on mutants of coverage-increasing corpus members.  Each
    distinct failure signature (the set of invariant names that fired)
    is recorded once, ddmin-minimized, and — when ``out_dir`` is set —
    written as a ``ray_tpu-hunt-finding/1`` artifact.  Deterministic:
    the same arguments replay the same search, finding for finding.
    """
    seeds = seed_genomes(nodes, seed, faults, duration,
                         campaigns=campaigns)
    mut = Mutator(seed, nodes)
    corpus: list = []
    global_cov: set = set()
    hot_times: list = []
    found_sigs: set = set()
    result = HuntResult(runs=0, budget=budget, nodes=nodes, seed=seed)

    while result.runs < budget:
        if result.runs < len(seeds):
            genome = seeds[result.runs]
        elif corpus:
            genome = mut.mutate(mut.pick_parent(corpus), corpus,
                                hot_times=hot_times)
        else:                   # every archetype crashed the signature
            genome = mut.mutate(seeds[result.runs % len(seeds)],
                                seeds, hot_times=hot_times)
        cov = RunCoverage()
        res = run_genome(genome, params=params, coverage=cov)
        result.runs += 1
        for t in cov.hot_times:
            if len(hot_times) < 256:
                hot_times.append(t)

        new = cov.keys - global_cov
        if new:
            global_cov |= new
            result.new_cov_runs += 1
            if not res.violations:
                corpus.append(genome)
            if progress and result.runs % 20 == 0:
                progress(f"run {result.runs}: corpus {len(corpus)}, "
                         f"coverage {len(global_cov)}")

        if res.violations:
            sig = tuple(sorted(violation_names(res.violations))) or \
                ("unstructured",)
            if sig not in found_sigs:
                found_sigs.add(sig)
                if progress:
                    progress(f"run {result.runs}: violation "
                             f"{'+'.join(sig)} "
                             f"({len(genome.ops)} ops) — minimizing")
                mini, stats = genome, {"probes": 0}
                if minimize and len(genome.ops) > 1:
                    mini, stats = minimize_genome(
                        genome, sig, params=params, progress=progress)
                final = run_genome(mini, params=params)
                finding = HuntFinding(
                    signature=sig, genome=genome, minimized=mini,
                    found_after_runs=result.runs,
                    ddmin_probes=stats["probes"],
                    violations=final.violations,
                    trace_hash=final.trace_hash)
                if out_dir:
                    finding.artifact = _write_finding(
                        finding, out_dir, params)
                result.findings.append(finding)
                if progress:
                    progress(f"minimized {'+'.join(sig)}: "
                             f"{len(genome.ops)} -> "
                             f"{len(mini.ops)} ops "
                             f"({stats['probes']} probes)")

    result.coverage = len(global_cov)
    result.coverage_keys = sorted(f"{a}:{b}" for a, b in global_cov)
    result.corpus = len(corpus)
    return result
