"""The invariants a campaign checks after every injected event.

Each check returns a list of violation strings; the campaign runner
fails (and the trace artifact records the violation) the moment one is
non-empty.  Checks are grace-aware: a transiently broken state (a lease
on a just-killed node, a drain mid-flight) is NOT a violation until the
recovery machinery has had its deadline plus slack to act.  With the
head down, head-derived checks are skipped (the campaign always
restarts the head before the final strict pass).

The five invariants, and the machinery each one proves:

1. **no acked job lost** — persistence-before-ack + head restore
2. **no lease stuck** — lost-ack lease requeue + death declaration
3. **drains converge** — drain protocol + deadline force-removal
4. **lineage reconstruction completes** — object-loss repair by
   re-running producers (strict form: every acked job SUCCEEDED)
5. **lock-order digraph stays acyclic** — the runtime lock-order
   recorder (``common/lockorder.py``), when installed, over the real
   locks the simulation exercises (chaos links, breakers)
6. **serve plane conserves requests and reclaims loans** — when a
   ``serve_diurnal`` campaign installed a ``SimServePlane``: every
   accepted request is accounted for in some queue (strictly:
   completed), and capacity loans converge to reclaimed-or-booked-lost
7. **no double-executed lease after epoch revocation** — lease plane
   (r15): once the head revokes a node's epoch, no task may *start*
   on that node under the revoked epoch past the grace window.  The
   raylet self-fences at the same horizon the head uses to declare it
   dead, so every start in ``cluster.exec_log`` must carry an epoch
   that is current for its node — or predate the revocation + grace.
   Invariant 1 doubles as the failover check: acked jobs must survive
   a standby promotion, because promotion is just ``start_head()``
   over the same persisted tables.
"""

from __future__ import annotations

__all__ = ["check_invariants"]


def _check_exec_log(cluster, grace: float) -> tuple[list[str], int]:
    """Scan lease-plane starts against the revocation log.  Incremental:
    starts already audited are dropped, so a 10k-node campaign pays for
    each start once.  A start under epoch ``e`` on node ``n`` violates
    iff some revocation ``(e_r, t_r)`` of ``n`` has ``e_r > e`` and the
    start happened after ``t_r + grace`` (inside the window the
    recovery machinery is still allowed to race)."""
    violations: list[str] = []
    log = cluster.exec_log
    checks = len(log)
    for tid, nid, epoch, t_start in log:
        if epoch < 0:
            continue        # non-lease exec path start: out of scope
        revs = cluster.revocation_log.get(nid)
        if not revs:
            continue
        for e_r, t_r in revs:
            if e_r > epoch and t_start > t_r + grace:
                violations.append(
                    f"double-executed lease: {tid} started on "
                    f"{nid} at t={t_start:.3f} under epoch "
                    f"{epoch}, revoked to {e_r} at t={t_r:.3f}")
                break
    # a start can never become violating later (a future revocation's
    # t_r is >= now > t_start): audited entries are done for good
    cluster.exec_audited += checks
    del log[:]
    return violations, checks


def check_invariants(cluster, acked_jobs, strict: bool = False
                     ) -> tuple[list[str], int]:
    """Run every invariant; returns (violations, predicates_evaluated).

    ``strict`` is the end-of-campaign form: every acked job must have
    SUCCEEDED (which subsumes 'lineage reconstruction completes' — a
    job whose lost outputs were never rebuilt cannot finish).
    """
    violations: list[str] = []
    checks = 0
    head = cluster.head
    now = cluster.clock.monotonic()
    p = cluster.params
    grace = 2.0 * p.heartbeat_period_s

    if head is not None and head.alive:
        # 1. no acked job lost
        for jid in acked_jobs:
            checks += 1
            if jid not in head.jobs:
                violations.append(f"acked job lost: {jid}")
        # 2. no lease stuck (monitor requeues at lease_timeout)
        for nid in head._node_order:
            row = head.nodes.get(nid)
            if row is None:
                continue
            for tid in row["running"]:
                t = head.tasks.get(tid)
                if t is None or t["state"] != "running":
                    continue
                checks += 1
                if now - t["granted_at"] > p.lease_timeout_s + grace:
                    violations.append(
                        f"lease stuck: {tid} on {nid} for "
                        f"{now - t['granted_at']:.1f}s")
            # lease-plane form: a locally-admitted grant the raylet
            # stopped reporting must be revoked+requeued by the sweep
            for tid, last in row["leased"].items():
                checks += 1
                if now - last > p.lease_timeout_s + grace:
                    violations.append(
                        f"leased task stuck: {tid} on {nid} quiet "
                        f"for {now - last:.1f}s")
            # 3. drains converge (deadline force-removal backstop)
            if row["state"] == "draining":
                checks += 1
                started = row["drain_started"]
                if started is not None and \
                        now - started > p.drain_deadline_s + grace:
                    violations.append(
                        f"drain not converged: {nid} draining for "
                        f"{now - started:.1f}s")
        # 4. lineage: an output every incomplete job still needs must
        # have a copy, or its producer must already be requeued/running
        for jid, job in head.jobs.items():
            if job["status"] == "succeeded":
                continue
            for tid in job["tasks"]:
                t = head.tasks[tid]
                if t["state"] != "done":
                    continue        # pending/running == being rebuilt
                checks += 1
                obj = head.objects.get(t["oid"])
                if (obj is None or not obj["copies"]) and strict:
                    violations.append(
                        f"lineage hole: {t['oid']} of {jid} has no "
                        f"copies and producer {tid} is not requeued")
        if strict:
            for jid in acked_jobs:
                checks += 1
                job = head.jobs.get(jid)
                if job is not None and job["status"] != "succeeded":
                    n_done = sum(
                        1 for tid in job["tasks"]
                        if head.tasks[tid]["state"] == "done")
                    violations.append(
                        f"acked job incomplete after quiesce: {jid} "
                        f"({n_done}/{len(job['tasks'])} tasks done)")

    # 6. serve plane (when a serve_diurnal campaign installed one):
    # accepted requests are conserved — counter matches the structural
    # sum of every queue — and loan drains converge; strictly, every
    # accepted request completed and every loan was reclaimed or its
    # loss booked
    plane = getattr(cluster, "serve_plane", None)
    if plane is not None and plane.started:
        v, n = plane.check(strict=strict, now=now, grace=grace)
        violations.extend(v)
        checks += n

    # 7. no double-executed lease after epoch revocation (lease plane);
    # head-independent: the logs live on the cluster, so this audits
    # through head-down windows and across standby promotions
    if cluster.params.lease_plane:
        v, n = _check_exec_log(cluster, grace)
        violations.extend(v)
        checks += n

    # 5. runtime lock-order digraph stays acyclic (when the recorder
    # is armed — see rtlint_runtime_lock_order)
    from ..common import lockorder
    if lockorder.installed():
        checks += 1
        try:
            lockorder.assert_acyclic()
        except AssertionError as e:
            violations.append(f"lock-order cycle: {e}")

    return violations, checks
