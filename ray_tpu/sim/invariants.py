"""The invariants a campaign checks after every injected event.

Each check returns a list of violation strings; the campaign runner
fails (and the trace artifact records the violation) the moment one is
non-empty.  Checks are grace-aware: a transiently broken state (a lease
on a just-killed node, a drain mid-flight) is NOT a violation until the
recovery machinery has had its deadline plus slack to act.  With the
head down, head-derived checks are skipped (the campaign always
restarts the head before the final strict pass).

Every violation string is self-describing — ``[inv:<name> @t=<virtual
seconds>] <detail>`` — so a failing campaign surfaces WHICH invariant
fired and WHEN without digging through the trace; ``violation_names``
parses the name back out (the hunt's failure signature and the
minimizer's reproduction predicate both key on it).

The invariants, and the machinery each one proves:

- **acked-job-lost** — persistence-before-ack + head restore (doubles
  as the failover check: acked jobs must survive a standby promotion,
  because promotion is just ``start_head()`` over the same persisted
  tables)
- **lease-stuck** / **leased-quiet** — lost-ack lease requeue + death
  declaration; lease-plane form: a locally-admitted grant the raylet
  stopped reporting must be revoked+requeued by the TTL sweep
- **drain-stuck** — drain protocol + deadline force-removal
- **lineage-hole** / **job-incomplete** (strict) — object-loss repair
  by re-running producers (a job whose lost outputs were never rebuilt
  cannot finish)
- **lock-order-cycle** — the runtime lock-order recorder
  (``common/lockorder.py``), when installed, over the real locks the
  simulation exercises (chaos links, breakers)
- **serve-accounting** / **serve-conservation** / **loan-drain-stuck**
  / **loan-conservation** / **serve-incomplete** / **loans-outstanding**
  — the serve plane (when a ``serve_diurnal`` campaign installed one):
  every accepted request is accounted for in some queue (strictly:
  completed), and capacity loans conserve —
  ``loans_total == active + reclaimed + lost`` even across
  SIGKILL-mid-reclaim — and converge to reclaimed-or-booked-lost
- **lease-double-exec** — lease plane (r15): once the head revokes a
  node's epoch, no task may *start* on that node under the revoked
  epoch past the grace window.  The raylet self-fences at the same
  horizon the head uses to declare it dead, so every start in
  ``cluster.exec_log`` must carry an epoch that is current for its
  node — or predate the revocation + grace.
- **object-copies** (r16) — the head's object registry never claims a
  replica on a node it has itself declared DEAD/REMOVED: no object is
  "lost" behind a phantom copy while a real replica's node is alive.
  Death declaration, drain removal and late gray-window done-acks must
  all keep the copy map consistent with the node table.
- **bcast-reparent-cycle** (r16) — broadcast re-parenting never forms
  a cycle: every live member of an active wave reaches the root
  through finitely many parents.
- **bcast-wave-terminal** / **bcast-live-replica** (strict) — by
  quiesce every wave reached a terminal state and every live member
  holds a full replica (previously inline in the campaign runner).
- **revocation-epoch-monotonic** (r16) — a node's revocation epochs
  strictly increase, across head kills and standby promotions: a
  promoted head that re-issued a journaled epoch would break the
  at-most-once execution fence.
- **budget-conservation** (r17) — with the lease plane on, a raylet's
  locally-admitted count for a class never exceeds the budget the head
  emitted for it under the node's current epoch (the closed dispatch
  loop: budgets priced by the scheduling beat must bound what the
  local cache actually admits).  Nodes mid-revocation (cache epoch
  behind the grantor's) and classes the grantor LRU-evicted (eviction
  does not bump the epoch) are out of scope.
- **goodput-accounting** / **ckpt-durable** / **gang-terminal**
  (r19) — the training plane (when a ``train_diurnal`` campaign
  installed one): committed samples, the KV epoch journal and the
  acked-epoch counter agree, and acked epochs never regress (the
  journal is written only after checkpoint replication, so a head kill
  or standby promotion can stall an ack but never roll one back); the
  newest acked checkpoint always holds a live copy and re-replicates
  to ``train_ckpt_replicas`` within the replication grace after a
  copy-holder dies; strictly, training reaches its terminal state by
  quiesce with every borrowed serve row returned and every
  reservation released.
- **version-mixed-session** / **rollout-terminal** /
  **old-version-retained** (r18) — the model-version plane (when a
  ``serve_rolling_update`` campaign installed one): no accepted
  request is served off its session's pinned version (session-sticky
  routing keeps every live session on ONE version across the flip
  sequence; the pin migrates forward — at a request boundary — only
  when its version has no live replica left or is queuing
  wall-to-wall while the frontier has headroom, so per-request
  consistency holds either way); strictly, every rollout reaches
  SEALED or ROLLED_BACK by
  quiesce; and an in-flight rollout never drops the old version's
  retained artifact before seal (rollback must always have weights to
  re-flip onto).
"""

from __future__ import annotations

import re

__all__ = ["check_invariants", "INVARIANTS", "violation_names"]

# name -> what the invariant proves (the fire/quiet twin tests and the
# hunt's coverage signal both enumerate this registry)
INVARIANTS = {
    "acked-job-lost": "persist-before-ack + head restore/promotion",
    "lease-stuck": "lost-ack lease requeue by the monitor",
    "leased-quiet": "quiet locally-admitted grants revoked by TTL sweep",
    "drain-stuck": "drain convergence + deadline force-removal",
    "lineage-hole": "lost outputs rebuilt by re-running producers",
    "job-incomplete": "strict final: every acked job SUCCEEDED",
    "lock-order-cycle": "runtime lock acquisition digraph acyclic",
    "serve-accounting": "outstanding counter == structural queue sum",
    "serve-conservation": "accepted == completed + outstanding",
    "loan-drain-stuck": "loan reclaim drains converge by deadline",
    "loan-conservation": "loans_total == active + reclaimed + lost",
    "serve-incomplete": "strict final: every accepted request completed",
    "loans-outstanding": "strict final: no loan left unreclaimed",
    "lease-double-exec": "no start under a revoked epoch past grace",
    "object-copies": "no phantom replica on a DEAD/REMOVED node",
    "bcast-reparent-cycle": "broadcast parent chains stay acyclic",
    "revocation-epoch-monotonic": "revocation epochs strictly increase",
    "bcast-wave-terminal": "strict final: every wave reaches terminal",
    "bcast-live-replica": "strict final: live wave members hold replicas",
    "budget-conservation":
        "locally-admitted grants never exceed head-emitted budgets",
    "version-mixed-session":
        "no request served off its session's pinned model version",
    "rollout-terminal": "strict final: every rollout SEALED/ROLLED_BACK",
    "old-version-retained": "old weights retained until the seal",
    "goodput-accounting":
        "committed samples == journal; acked epochs never regress",
    "ckpt-durable": "newest acked checkpoint keeps live replicated copies",
    "gang-terminal": "strict final: training terminal, borrows returned",
}

_NAME_RE = re.compile(r"\[inv:([a-z0-9-]+) @t=")


def violation_names(violations) -> frozenset:
    """The set of invariant names present in a violation list — the
    failure signature the hunt dedupes on and the minimizer preserves."""
    names = set()
    for v in violations:
        m = _NAME_RE.search(v)
        if m:
            names.add(m.group(1))
    return frozenset(names)


def fmt_violation(name: str, now: float, msg: str) -> str:
    return f"[inv:{name} @t={now:.1f}] {msg}"


def _check_exec_log(cluster, grace: float, now: float
                    ) -> tuple[list[str], int]:
    """Scan lease-plane starts against the revocation log.  Incremental:
    starts already audited are dropped, so a 10k-node campaign pays for
    each start once.  A start under epoch ``e`` on node ``n`` violates
    iff some revocation ``(e_r, t_r)`` of ``n`` has ``e_r > e`` and the
    start happened after ``t_r + grace`` (inside the window the
    recovery machinery is still allowed to race)."""
    violations: list[str] = []
    log = cluster.exec_log
    checks = len(log)
    for tid, nid, epoch, t_start in log:
        if epoch < 0:
            continue        # non-lease exec path start: out of scope
        revs = cluster.revocation_log.get(nid)
        if not revs:
            continue
        for e_r, t_r in revs:
            if e_r > epoch and t_start > t_r + grace:
                violations.append(fmt_violation(
                    "lease-double-exec", now,
                    f"{tid} started on {nid} at t={t_start:.3f} under "
                    f"epoch {epoch}, revoked to {e_r} at t={t_r:.3f}"))
                break
    # a start can never become violating later (a future revocation's
    # t_r is >= now > t_start): audited entries are done for good
    cluster.exec_audited += checks
    del log[:]
    return violations, checks


def _check_object_copies(head, now: float) -> tuple[list[str], int]:
    """object-copies: every replica the registry claims lives on a node
    the head still considers ALIVE or DRAINING.  Death declaration and
    removal scrub synchronously, so no grace window is needed."""
    violations: list[str] = []
    checks = 0
    dead_rows = {nid for nid, row in head.nodes.items()
                 if row["state"] in ("dead", "removed")}
    for oid, obj in head.objects.items():
        checks += 1
        if not dead_rows:
            continue
        phantom = [nid for nid in obj["copies"] if nid in dead_rows]
        if phantom:
            violations.append(fmt_violation(
                "object-copies", now,
                f"{oid} claims replicas on dead/removed "
                f"{','.join(phantom)} (live copies: "
                f"{len(obj['copies']) - len(phantom)})"))
    return violations, checks


def _check_broadcast_cycles(cluster, now: float) -> tuple[list[str], int]:
    """bcast-reparent-cycle: in every active wave, each live member's
    parent chain reaches the root in <= |members|+1 hops."""
    violations: list[str] = []
    checks = 0
    waves = getattr(cluster, "broadcast_waves", None) or ()
    for w in waves:
        if w.t_done is not None:
            continue
        bound = len(w.members) + 1
        ok: set = {w.root}
        for m in w.members:
            if not w._alive(m):
                continue
            checks += 1
            node, path, hops = m, [], 0
            while node is not None and node not in ok and hops <= bound:
                path.append(node)
                node = w.parent_of.get(node)
                hops += 1
            if hops > bound:
                violations.append(fmt_violation(
                    "bcast-reparent-cycle", now,
                    f"wave {w.wave_id}: {m}'s parent chain cycles "
                    f"({'->'.join(path[:6])}...)"))
            else:
                ok.update(path)
    return violations, checks


def _check_waves_final(cluster, now: float) -> tuple[list[str], int]:
    """Strict final wave checks: every wave terminal, every live member
    holding a full replica (re-parenting converged, no lost chunks — a
    completed member received every chunk exactly once by construction
    of the delivery model)."""
    violations: list[str] = []
    checks = 0
    for w in (getattr(cluster, "broadcast_waves", None) or ()):
        checks += 1
        if not w.terminal:
            violations.append(fmt_violation(
                "bcast-wave-terminal", now,
                f"broadcast wave {w.wave_id} never became terminal"))
            continue
        left = w.unreached_live()
        if left:
            violations.append(fmt_violation(
                "bcast-live-replica", now,
                f"broadcast wave {w.wave_id}: {len(left)} live "
                f"members without a replica"))
    return violations, checks


def _check_epoch_monotonic(cluster, now: float) -> tuple[list[str], int]:
    """revocation-epoch-monotonic: per node, revocation epochs strictly
    increase in revocation order — across kills and promotions."""
    violations: list[str] = []
    checks = 0
    for nid, revs in cluster.revocation_log.items():
        checks += 1
        prev = None
        for epoch, t_r in revs:
            if prev is not None and epoch <= prev:
                violations.append(fmt_violation(
                    "revocation-epoch-monotonic", now,
                    f"{nid} revoked to epoch {epoch} at t={t_r:.3f} "
                    f"after already reaching {prev}"))
                break
            prev = epoch
    return violations, checks


def _check_budget_conservation(cluster, head, now: float
                               ) -> tuple[list[str], int]:
    """budget-conservation: for every alive lease-plane node whose
    cache epoch matches the grantor's, each class's locally-admitted
    count is bounded by the head-emitted budget.  Classes the grantor
    LRU-evicted (eviction never bumps the epoch) are skipped — the node
    may legitimately drain admissions the head no longer tracks."""
    violations: list[str] = []
    checks = 0
    grantor = head.grantor
    if grantor is None:
        return violations, checks
    for nid, node in cluster.nodes.items():
        lease = getattr(node, "lease", None)
        if lease is None or not node.alive:
            continue
        epoch, grants = grantor.snapshot_for(nid)
        if lease.epoch != epoch:
            continue    # revocation in flight: discard underway
        for ck, entry in lease._classes.items():
            emitted = grants.get(ck)
            if emitted is None:
                continue
            checks += 1
            if entry[1] > emitted:
                violations.append(fmt_violation(
                    "budget-conservation", now,
                    f"{nid} admitted {entry[1]} of class {ck} against "
                    f"head-emitted budget {emitted} (epoch {epoch})"))
    return violations, checks


def check_invariants(cluster, acked_jobs, strict: bool = False
                     ) -> tuple[list[str], int]:
    """Run every invariant; returns (violations, predicates_evaluated).

    ``strict`` is the end-of-campaign form: every acked job must have
    SUCCEEDED (which subsumes 'lineage reconstruction completes' — a
    job whose lost outputs were never rebuilt cannot finish).
    """
    violations: list[str] = []
    checks = 0
    head = cluster.head
    now = cluster.clock.monotonic()
    p = cluster.params
    grace = 2.0 * p.heartbeat_period_s

    def v(name: str, msg: str) -> None:
        violations.append(fmt_violation(name, now, msg))

    if head is not None and head.alive:
        # acked-job-lost
        for jid in acked_jobs:
            checks += 1
            if jid not in head.jobs:
                v("acked-job-lost", f"acked job lost: {jid}")
        # lease-stuck / leased-quiet (monitor requeues at lease_timeout)
        for nid in head._node_order:
            row = head.nodes.get(nid)
            if row is None:
                continue
            for tid in row["running"]:
                t = head.tasks.get(tid)
                if t is None or t["state"] != "running":
                    continue
                checks += 1
                if now - t["granted_at"] > p.lease_timeout_s + grace:
                    v("lease-stuck",
                      f"{tid} on {nid} running for "
                      f"{now - t['granted_at']:.1f}s")
            # lease-plane form: a locally-admitted grant the raylet
            # stopped reporting must be revoked+requeued by the sweep
            for tid, last in row["leased"].items():
                checks += 1
                if now - last > p.lease_timeout_s + grace:
                    v("leased-quiet",
                      f"{tid} on {nid} quiet for {now - last:.1f}s")
            # drain-stuck (deadline force-removal backstop)
            if row["state"] == "draining":
                checks += 1
                started = row["drain_started"]
                if started is not None and \
                        now - started > p.drain_deadline_s + grace:
                    v("drain-stuck",
                      f"{nid} draining for {now - started:.1f}s")
        # lineage: an output every incomplete job still needs must
        # have a copy, or its producer must already be requeued/running
        for jid, job in head.jobs.items():
            if job["status"] == "succeeded":
                continue
            for tid in job["tasks"]:
                t = head.tasks[tid]
                if t["state"] != "done":
                    continue        # pending/running == being rebuilt
                checks += 1
                obj = head.objects.get(t["oid"])
                if (obj is None or not obj["copies"]) and strict:
                    v("lineage-hole",
                      f"{t['oid']} of {jid} has no copies and "
                      f"producer {tid} is not requeued")
        if strict:
            for jid in acked_jobs:
                checks += 1
                job = head.jobs.get(jid)
                if job is not None and job["status"] != "succeeded":
                    n_done = sum(
                        1 for tid in job["tasks"]
                        if head.tasks[tid]["state"] == "done")
                    v("job-incomplete",
                      f"acked job incomplete after quiesce: {jid} "
                      f"({n_done}/{len(job['tasks'])} tasks done)")
        # object-copies: registry vs node-table consistency
        cv, cn = _check_object_copies(head, now)
        violations.extend(cv)
        checks += cn
        # budget-conservation: local admissions bounded by emitted
        # budgets (needs the live head's grantor book)
        if p.lease_plane and getattr(head, "grantor", None) is not None:
            gv, gn = _check_budget_conservation(cluster, head, now)
            violations.extend(gv)
            checks += gn

    # serve plane (when a serve_diurnal campaign installed one)
    plane = getattr(cluster, "serve_plane", None)
    if plane is not None and plane.started:
        sv, sn = plane.check(strict=strict, now=now, grace=grace)
        violations.extend(sv)
        checks += sn

    # training plane (when a train_diurnal campaign installed one)
    tplane = getattr(cluster, "train_plane", None)
    if tplane is not None and tplane.started:
        tv, tn = tplane.check(strict=strict, now=now, grace=grace)
        violations.extend(tv)
        checks += tn

    # model-version plane (when a serve_rolling_update campaign
    # installed one)
    rplane = getattr(cluster, "rollout_plane", None)
    if rplane is not None:
        rv, rn = rplane.check(strict=strict, now=now, grace=grace)
        violations.extend(rv)
        checks += rn

    # lease-double-exec; head-independent: the logs live on the
    # cluster, so this audits through head-down windows and across
    # standby promotions
    if cluster.params.lease_plane:
        ev, en = _check_exec_log(cluster, grace, now)
        violations.extend(ev)
        checks += en

    # bcast-reparent-cycle over the campaign's live waves
    bv, bn = _check_broadcast_cycles(cluster, now)
    violations.extend(bv)
    checks += bn
    if strict:
        wv, wn = _check_waves_final(cluster, now)
        violations.extend(wv)
        checks += wn

    # revocation-epoch-monotonic (head-independent, like the exec log)
    mv, mn = _check_epoch_monotonic(cluster, now)
    violations.extend(mv)
    checks += mn

    # lock-order-cycle (when the recorder is armed — see
    # rtlint_runtime_lock_order)
    from ..common import lockorder
    if lockorder.installed():
        checks += 1
        try:
            lockorder.assert_acyclic()
        except AssertionError as e:
            v("lock-order-cycle", str(e))

    # empty-lockset shared write (when the Eraser recorder is armed —
    # see rtlint_runtime_locksets)
    from ..common import locksets
    if locksets.installed():
        checks += 1
        try:
            locksets.assert_no_races()
        except AssertionError as e:
            v("lockset-race", str(e))

    return violations, checks
