"""ddmin delta debugging over fault schedules.

Bit-identical replay makes every probe exactly one deterministic sim
run: a subset of a failing schedule either still reproduces the failure
signature or it does not, with no flakiness to average over.  That
turns Zeller's ddmin into a practical minimizer for chaos campaigns —
a 40-op failing schedule typically shrinks to the 2–3 ops that matter
in a few dozen probes.

The algorithm here is the classic one (test subsets, then complements,
then double the granularity) followed by a one-minimality sweep: drop
each remaining item individually and keep the drop if the failure
still reproduces.  The sweep guarantees the result is 1-minimal —
removing ANY single op breaks reproduction — which is the property the
committed regression artifacts advertise.

Probes are memoised on the index subset, so the sweep never re-runs a
configuration ddmin already tried.
"""

from __future__ import annotations

__all__ = ["ddmin"]


def _split(idx: list, n: int) -> list:
    """``idx`` in ``n`` contiguous chunks, sizes differing by <= 1."""
    k, m = divmod(len(idx), n)
    out, pos = [], 0
    for i in range(n):
        size = k + (1 if i < m else 0)
        if size:
            out.append(idx[pos:pos + size])
        pos += size
    return out


def ddmin(items: list, test, progress=None) -> tuple[list, dict]:
    """Minimize ``items`` such that ``test(subset)`` stays True.

    ``test`` takes a sub-list of ``items`` (order preserved) and returns
    True iff the failure still reproduces; it must be True for the full
    list (asserted).  Returns ``(minimized_items, stats)`` where stats
    counts executed probes and memo hits.  The minimized list is
    1-minimal: dropping any single element stops reproduction.
    """
    stats = {"probes": 0, "cache_hits": 0}
    cache: dict = {}

    def probe(ids: tuple) -> bool:
        if ids in cache:
            stats["cache_hits"] += 1
            return cache[ids]
        stats["probes"] += 1
        r = bool(test([items[i] for i in ids]))
        cache[ids] = r
        if progress:
            progress(f"ddmin probe {stats['probes']}: "
                     f"{len(ids)}/{len(items)} ops -> "
                     f"{'fail' if r else 'pass'}")
        return r

    idx = tuple(range(len(items)))
    if not probe(idx):
        raise ValueError("ddmin: full input does not reproduce the "
                         "failure — nothing to minimize")

    n = 2
    while len(idx) >= 2:
        chunks = _split(list(idx), n)
        reduced = False
        for c in chunks:                    # try each subset alone
            if probe(tuple(c)):
                idx, n, reduced = tuple(c), 2, True
                break
        if not reduced and n > 2:
            for c in chunks:                # try each complement
                rest = tuple(i for i in idx if i not in set(c))
                if rest and probe(rest):
                    idx, n, reduced = rest, max(n - 1, 2), True
                    break
        if not reduced:
            if n >= len(idx):
                break
            n = min(2 * n, len(idx))

    # one-minimality sweep: every survivor must be load-bearing
    changed = True
    while changed and len(idx) > 1:
        changed = False
        for i in idx:
            rest = tuple(j for j in idx if j != i)
            if probe(rest):
                idx, changed = rest, True
                break

    return [items[i] for i in idx], stats
