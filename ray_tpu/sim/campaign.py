"""Scripted chaos campaigns over the simulated cluster.

A campaign is a deterministic schedule — built up-front from one Philox
generator keyed by the seed — of background job load plus injected
faults: rolling SIGKILLs, asymmetric partitions, gray-slow links,
drain-under-churn, autoscaler flapping.  The runner executes it on the
virtual clock, checks every invariant after every injected event, then
quiesces (heal everything, restart a dead head, let recovery finish)
and applies the strict final check: every acked job SUCCEEDED.

Every run emits a replayable trace artifact keyed by seed: re-running
``ray_tpu simulate`` with the same (nodes, seed, campaign, faults,
duration) reproduces the identical event trace, asserted by comparing
the sha256 trace hash.
"""

from __future__ import annotations

import json
import sys
import time
from dataclasses import dataclass, field

from dataclasses import replace

from ..rpc.chaos import _Params
from ..rpc.client import RpcConnectionError
from .cluster import HEAD_ADDR, STANDBY_ADDR, SimCluster, SimParams
from .invariants import check_invariants

__all__ = ["CAMPAIGNS", "CampaignResult", "run_campaign",
           "build_schedule"]

CAMPAIGNS = ("mixed", "rolling_kill", "partitions", "gray_slow",
             "drain_churn", "autoscaler_flap", "broadcast_storm",
             "serve_diurnal", "head_failover_storm",
             "serve_rolling_update", "train_diurnal")

# the failover storm snaps task durations to a small class set so the
# job stream is a repeat-class workload — the shape the lease plane's
# origin routing serves locally
_STORM_CLASSES = (2.0, 4.0, 6.0, 9.0, 12.0, 15.0)

_SETTLE_CAP_S = 900.0       # virtual budget for the quiesce phase


@dataclass
class CampaignResult:
    nodes: int
    seed: int
    campaign: str
    faults_injected: int
    jobs_acked: int
    jobs_completed: int
    events_fired: int
    invariant_checks: int
    violations: list = field(default_factory=list)
    trace_hash: str = ""
    virtual_s: float = 0.0
    wall_s: float = 0.0
    stats: dict = field(default_factory=dict)

    @property
    def ok(self) -> bool:
        return not self.violations

    def to_dict(self) -> dict:
        return {
            "nodes": self.nodes, "seed": self.seed,
            "campaign": self.campaign,
            "faults_injected": self.faults_injected,
            "jobs_acked": self.jobs_acked,
            "jobs_completed": self.jobs_completed,
            "events_fired": self.events_fired,
            "invariant_checks": self.invariant_checks,
            "violations": list(self.violations),
            "trace_hash": self.trace_hash,
            "virtual_s": round(self.virtual_s, 3),
            "wall_s": round(self.wall_s, 3),
            "events_per_sec": round(
                self.events_fired / max(self.wall_s, 1e-9)),
            "ok": self.ok,
            "stats": self.stats,
        }


def _node_addr(idx: int) -> str:
    return f"sim://n{idx:05d}"


def build_schedule(campaign: str, rng, num_nodes: int, faults: int,
                   duration: float) -> tuple[list, list]:
    """Deterministic (jobs, faults) schedules.  ``jobs`` is
    ``(t, jid, {tid: duration})``; ``faults`` is ``(t, op, kwargs)``,
    time-sorted with ties broken by build order.  All draws come from
    ``rng`` in a fixed order, so the schedule is a pure function of
    (campaign, seed, nodes, faults, duration)."""
    if campaign not in CAMPAIGNS:
        raise ValueError(f"unknown campaign {campaign!r}; "
                         f"choose from {', '.join(CAMPAIGNS)}")
    storm = campaign == "head_failover_storm"
    jobs = []
    n_jobs = max(8, min(400, num_nodes // 4))
    for k in range(n_jobs):
        t = float(rng.uniform(1.0, duration * 0.7))
        n_tasks = int(rng.integers(2, 9))
        jid = f"job{k:04d}"
        if storm:
            tasks = {f"{jid}.t{i}":
                     _STORM_CLASSES[int(rng.integers(
                         0, len(_STORM_CLASSES)))]
                     for i in range(n_tasks)}
        else:
            tasks = {f"{jid}.t{i}":
                     round(float(rng.uniform(2.0, 18.0)), 3)
                     for i in range(n_tasks)}
        jobs.append((t, jid, tasks))
    jobs.sort(key=lambda e: e[0])

    # fault mix per campaign archetype (weights over op kinds)
    mixes = {
        "mixed": (("kill_node", 0.3), ("partition", 0.25),
                  ("gray_slow", 0.15), ("drain", 0.2),
                  ("kill_head", 0.1)),
        "rolling_kill": (("kill_node", 0.9), ("kill_head", 0.1)),
        "partitions": (("partition", 0.85), ("kill_head", 0.15)),
        "gray_slow": (("gray_slow", 0.8), ("partition", 0.2)),
        "drain_churn": (("drain", 0.7), ("kill_node", 0.3)),
        "autoscaler_flap": (("drain", 0.4), ("kill_node", 0.4),
                            ("gray_slow", 0.2)),
        # weight-distribution waves racing relay-node/root kills and
        # gray links: the broadcast plane's re-parenting under fire
        "broadcast_storm": (("broadcast", 0.45), ("kill_node", 0.3),
                            ("gray_slow", 0.15), ("kill_head", 0.1)),
        # diurnal serve load driving loan->serve->reclaim while kills
        # land on replicas and LOANED rows: the capacity-loan state
        # machine and request re-dispatch under fire
        "serve_diurnal": (("kill_node", 0.5), ("gray_slow", 0.2),
                          ("drain", 0.2), ("kill_head", 0.1)),
        # rolling head SIGKILLs under churn + asymmetric partitions:
        # no scripted restarts — the hot standby must promote every
        # time, and the lease plane must keep dispatching through it
        "head_failover_storm": (("kill_head", 0.35),
                                ("partition", 0.3),
                                ("kill_node", 0.25),
                                ("gray_slow", 0.1)),
        # rolling weight hot-swaps landing mid-peak while kills hit
        # replicas (and the head, mid-broadcast): the model-version
        # plane's flip/rollback machinery and session pinning under fire
        "serve_rolling_update": (("rollout", 0.25), ("kill_node", 0.35),
                                 ("gray_slow", 0.15), ("drain", 0.15),
                                 ("kill_head", 0.1)),
        # train + serve sharing one pool under a diurnal day: rolling
        # SIGKILLs land on gang members, serve replicas and borrowed
        # rows, a head kill lands mid-epoch, drains force planned
        # resizes — loans must flow BOTH directions and acked epochs
        # must never regress
        "train_diurnal": (("kill_node", 0.5), ("drain", 0.2),
                          ("gray_slow", 0.15), ("kill_head", 0.15)),
    }
    ops, weights = zip(*mixes[campaign])
    sched = []
    window = (duration * 0.05, duration * 0.85)
    head_kills = 0
    rollouts = 0
    for _ in range(faults):
        t = float(rng.uniform(*window))
        u = float(rng.random())
        acc, op = 0.0, ops[-1]
        for name, w in zip(ops, weights):
            acc += w
            if u < acc:
                op = name
                break
        target = int(rng.integers(0, num_nodes))
        heal_after = float(rng.uniform(8.0, 25.0))
        if op == "kill_head":
            # bounded: restarts must not overlap (storm runs deeper —
            # the standby chain absorbs each kill, no scripted restart)
            if head_kills >= (4 if storm else 2):
                op = "kill_node"
            else:
                head_kills += 1
                sched.append((t, "kill_head", {}))
                if not storm:
                    sched.append((t + heal_after, "restart_head", {}))
                continue
        if op == "partition":
            kind = int(rng.integers(0, 4 if storm else 3))
            addr = _node_addr(target)
            if kind == 0:       # asymmetric: head cannot reach node
                pairs = [(HEAD_ADDR, addr)]
            elif kind == 1:     # asymmetric: node cannot reach head
                pairs = [(addr, HEAD_ADDR)]
            elif kind == 3:     # asymmetric: standby blind to a live
                pairs = [(STANDBY_ADDR, HEAD_ADDR)]     # head (no
            else:               # split-brain: nodes don't vote)
                pairs = [(HEAD_ADDR, addr), (addr, HEAD_ADDR)]
            sched.append((t, "partition", {"pairs": pairs}))
            sched.append((t + heal_after, "heal", {"pairs": pairs}))
            continue
        if op == "gray_slow":
            addr = _node_addr(target)
            sched.append((t, "gray_slow", {"addr": addr}))
            sched.append((t + heal_after, "gray_heal", {"addr": addr}))
            continue
        if op == "rollout":
            # land mid-peak (the acceptance window the bench measures);
            # a quarter of rollouts carry an injected probe failure so
            # the rollback path is exercised, not just the happy seal
            t_roll = float(rng.uniform(duration * 0.30,
                                       duration * 0.65))
            pf = float(rng.random())
            rollouts += 1
            sched.append((t_roll, "rollout", {
                "artifact": f"weights-{rollouts:03d}",
                "probe_fail_at": target % 8 if pf < 0.25 else -1,
            }))
            continue
        if op == "broadcast":
            count = int(rng.integers(max(2, num_nodes // 3),
                                     num_nodes + 1))
            rows = sorted(int(x) for x in rng.choice(
                num_nodes, size=min(count, num_nodes), replace=False))
            sched.append((t, "broadcast", {
                "members": [f"n{r:05d}" for r in rows],
                "size_mb": int(rng.integers(64, 1025)),
                "fanout": int(rng.integers(2, 5)),
            }))
            continue
        sched.append((t, op, {"node": f"n{target:05d}"}))
    sched.sort(key=lambda e: e[0])
    return jobs, sched


def run_campaign(num_nodes: int, seed: int = 0, campaign: str = "mixed",
                 faults: int = 50, duration: float | None = None,
                 params: SimParams | None = None,
                 autoscale: bool = True, lock_order: bool = False,
                 serve: dict | None = None,
                 train: dict | None = None,
                 out: str | None = None, progress=None,
                 schedule: list | None = None,
                 coverage=None) -> CampaignResult:
    """Execute one campaign; returns a :class:`CampaignResult` whose
    ``trace_hash`` is the replay fingerprint.

    ``schedule`` overrides the generated fault schedule with an explicit
    ``[(t, op, kwargs), ...]`` list (a hunt genome).  The job load is
    still a pure function of (campaign, seed, nodes, duration) — job
    draws precede fault draws on the Philox stream — so a (base args,
    schedule) pair replays bit-identically.  ``coverage`` is an optional
    sink (``hunt.RunCoverage``) attached to the trace; it observes every
    event but never feeds the replay hash."""
    import numpy as np

    if duration is None:
        duration = max(180.0, faults * 4.0)
    wall0 = time.perf_counter()
    old_limit = sys.getrecursionlimit()
    sys.setrecursionlimit(max(old_limit, 20000))

    rng = np.random.Generator(np.random.Philox(
        key=[int(seed) & (2 ** 64 - 1), 0xC0FFEE]))
    jobs, sched = build_schedule(campaign, rng, num_nodes, faults,
                                 duration)
    if schedule is not None:
        sched = sorted(((float(t), op, dict(kw))
                        for t, op, kw in schedule), key=lambda e: e[0])

    if campaign == "head_failover_storm":
        # the storm IS the lease plane + hot standby under fire
        params = replace(params or SimParams.from_config(),
                         lease_plane=True, standby=True)
    cluster = SimCluster(num_nodes, seed=seed, params=params)
    if coverage is not None:
        cluster.trace.cov = coverage
    plane = None
    rplane = None
    tplane = None
    if campaign in ("serve_diurnal", "serve_rolling_update",
                    "train_diurnal"):
        from .serve import SimServePlane
        plane = SimServePlane(cluster, seed=seed, duration=duration,
                              **(serve or {}))
        cluster.serve_plane = plane
    if campaign == "serve_rolling_update":
        from .rollout import SimRolloutPlane
        rplane = SimRolloutPlane(cluster, plane)
    if campaign == "train_diurnal":
        from .train import SimTrainPlane
        tplane = SimTrainPlane(cluster, duration=duration,
                               serve=plane, **(train or {}))
        cluster.train_plane = tplane
    if lock_order:
        from ..common import lockorder
        if not lockorder.installed():
            lockorder.install()
    acked: list[str] = []
    waves: list = []            # SimBroadcastWave, launch order
    # invariants (bcast-reparent-cycle) audit the live waves directly
    cluster.broadcast_waves = waves
    completed_cache = {"n": 0}
    fault_count = {"n": 0}
    inv_checks = {"n": 0}
    violations: list[str] = []
    clock, trace = cluster.clock, cluster.trace
    driver = cluster.transport.connect(HEAD_ADDR, _sim_src="driver")

    def submit(jid, tasks, attempt=0):
        try:
            if driver.call("job_submit", jid, tasks) == "ack":
                acked.append(jid)
                return
        except RpcConnectionError:
            pass
        if attempt < 40:        # head may be down: keep retrying
            clock.call_later(3.0, lambda: submit(jid, tasks,
                                                 attempt + 1))

    def check(stage):
        v, n = check_invariants(cluster, acked)
        inv_checks["n"] += n
        trace.rec(clock.monotonic(), "invariant_check", stage=stage,
                  checks=n, violations=len(v))
        for msg in v:
            if len(violations) < 100:
                violations.append(f"[{stage}] {msg}")

    def apply_fault(op, kw):
        t = clock.monotonic()
        if op == "kill_head":
            cluster.kill_head()
            for w in waves:
                w.on_node_killed("head")
            trace.rec(t, "fault", op=op)
        elif op == "restart_head":
            if cluster.head is None:
                cluster.start_head()
            trace.rec(t, "fault", op=op)
        elif op == "kill_node":
            hit = cluster.kill_node(kw["node"])
            if hit:
                for w in waves:
                    w.on_node_killed(kw["node"])
                if plane is not None:
                    plane.on_node_killed(kw["node"])
                if tplane is not None:
                    tplane.on_node_killed(kw["node"])
            trace.rec(t, "fault", op=op, node=kw["node"], hit=hit)
        elif op == "rollout":
            rid = ""
            if rplane is not None:
                rid = rplane.start_rollout(
                    kw["artifact"],
                    probe_fail_at=kw.get("probe_fail_at", -1))
            trace.rec(t, "fault", op=op, artifact=kw["artifact"],
                      probe_fail_at=kw.get("probe_fail_at", -1),
                      rollout=rid)
        elif op == "broadcast":
            from .broadcast import SimBroadcastWave
            w = SimBroadcastWave(cluster, f"w{len(waves)}",
                                 kw["members"], size_mb=kw["size_mb"],
                                 fanout=kw["fanout"])
            waves.append(w)
            w.start()
            trace.rec(t, "fault", op=op, wave=w.wave_id,
                      members=len(kw["members"]),
                      size_mb=kw["size_mb"], fanout=kw["fanout"])
        elif op == "drain":
            ok = False
            if cluster.head is not None and cluster.head.alive:
                ok = cluster.head.start_drain(kw["node"], "campaign")
            trace.rec(t, "fault", op=op, node=kw["node"], hit=ok)
        elif op == "partition":
            for pair in kw["pairs"]:
                cluster.chaos.partitions.add(tuple(pair))
            trace.rec(t, "fault", op=op, pairs=kw["pairs"])
        elif op == "heal":
            for pair in kw["pairs"]:
                cluster.chaos.partitions.discard(tuple(pair))
            trace.rec(t, "fault", op=op, pairs=kw["pairs"])
        elif op == "gray_slow":
            cluster.chaos.links[kw["addr"]] = _Params(
                drop_p=0.25, dup_p=0.05, delay_p=0.9, delay_ms=350.0)
            trace.rec(t, "fault", op=op, addr=kw["addr"])
        elif op == "gray_heal":
            cluster.chaos.links.pop(kw["addr"], None)
            trace.rec(t, "fault", op=op, addr=kw["addr"])
        fault_count["n"] += 1
        check(f"after:{op}")

    try:
        with cluster:
            if autoscale:
                cluster.enable_autoscaler(
                    min_nodes=num_nodes,
                    max_nodes=num_nodes + max(8, num_nodes // 10))
            if plane is not None:
                plane.start()
            if tplane is not None:
                tplane.start()
            for t, jid, tasks in jobs:
                clock.call_later(
                    t, lambda jid=jid, tasks=tasks: submit(jid, tasks))
            for t, op, kw in sched:
                clock.call_later(
                    t, lambda op=op, kw=kw: apply_fault(op, kw))

            clock.run_until(duration)
            if progress:
                progress(f"campaign phase done at t={duration:.0f}s "
                         f"virtual, {fault_count['n']} faults")

            # -- quiesce: heal the world, let recovery converge ----------
            cluster.chaos.partitions.clear()
            cluster.chaos.links.clear()
            if cluster.head is None and cluster.standby is None:
                # with a hot standby, promotion — not a scripted
                # restart — brings the head back (racing start_head
                # against it would double-bind the head address)
                cluster.start_head()
            trace.rec(clock.monotonic(), "quiesce")

            def all_done():
                head = cluster.head
                if head is None or not head.alive:
                    return False
                done = sum(1 for jid in acked
                           if head.jobs.get(jid, {}).get("status") ==
                           "succeeded")
                completed_cache["n"] = done
                return done == len(acked) and \
                    all(w.terminal for w in waves) and \
                    (plane is None or plane.terminal) and \
                    (rplane is None or rplane.all_terminal) and \
                    (tplane is None or tplane.terminal)

            settle_end = duration + _SETTLE_CAP_S
            while not all_done() and clock.monotonic() < settle_end:
                clock.advance(cluster.params.heartbeat_period_s)
            check("final")
            v, n = check_invariants(cluster, acked, strict=True)
            inv_checks["n"] += n
            trace.rec(clock.monotonic(), "invariant_check",
                      stage="final_strict", checks=n, violations=len(v))
            for msg in v:
                if len(violations) < 100:
                    violations.append(f"[final] {msg}")
            all_done()
    finally:
        cluster.close()
        sys.setrecursionlimit(old_limit)

    wall = time.perf_counter() - wall0
    result = CampaignResult(
        nodes=num_nodes, seed=int(seed), campaign=campaign,
        faults_injected=fault_count["n"], jobs_acked=len(acked),
        jobs_completed=completed_cache["n"],
        events_fired=clock.fired, invariant_checks=inv_checks["n"],
        violations=violations, trace_hash=trace.hash(),
        virtual_s=clock.monotonic(), wall_s=wall,
        stats=cluster.stats())
    if plane is not None:
        result.stats["serve"] = plane.stats()
    if rplane is not None:
        result.stats["rollout"] = rplane.stats()
    if tplane is not None:
        result.stats["train"] = tplane.stats()
    if out:
        write_artifact(out, result, trace, duration, faults,
                       schedule=schedule, params=cluster.params)
    return result


# config-knob prefixes snapshotted into every trace artifact: the full
# resolved values reproduction depends on, so a replay is a pure
# function of the artifact, never of the ambient env
_KNOB_PREFIXES = ("chaos_", "lease_", "serve_", "sim_", "standby_",
                  "rollout_", "version_", "train_", "collective_",
                  "rpc_breaker_", "rtlint_runtime_lock_order",
                  "rtlint_runtime_locksets")


def knob_snapshot() -> dict:
    """Resolved ``chaos_*``/``lease_*``/``serve_*``/``sim_*``/
    ``standby_*`` knob values at run time (env overrides folded in)."""
    from ..common.config import get_config
    cfg = get_config().to_dict()
    return {k: cfg[k] for k in sorted(cfg)
            if k.startswith(_KNOB_PREFIXES)}


def write_artifact(path: str, result: CampaignResult, cluster_trace,
                   duration: float | None, faults: int | None = None,
                   extra: dict | None = None,
                   schedule: list | None = None,
                   params: SimParams | None = None) -> None:
    """The replayable trace artifact: seed + parameters reproduce the
    run; the hash proves the reproduction matched.  ``replay`` holds
    the exact ``run_campaign`` arguments (``faults`` is the *requested*
    count — the schedule key — not the injected total; an explicit
    ``schedule`` override is embedded verbatim), ``knobs`` the full
    resolved config the run saw and ``params`` the resolved
    :class:`SimParams` — so reproduction is a pure function of the
    artifact, not of the ambient env."""
    from dataclasses import asdict

    doc = {
        "format": "ray_tpu-sim-trace/1",
        "replay": {"nodes": result.nodes, "seed": result.seed,
                   "campaign": result.campaign, "faults": faults,
                   "duration": duration},
        "knobs": knob_snapshot(),
        "result": result.to_dict(),
        "events_total": cluster_trace.total,
        "events_stored": len(cluster_trace.events),
        "events": cluster_trace.events,
    }
    if schedule is not None:
        doc["replay"]["schedule"] = [[t, op, kw]
                                     for t, op, kw in schedule]
    if params is not None:
        doc["params"] = asdict(params)
    if extra:
        doc.update(extra)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1)
        f.write("\n")
