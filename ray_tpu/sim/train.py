"""Simulated elastic training plane: a gang of workers sharing the
pool with serve, surviving worker SIGKILL, head SIGKILL and drains.

The live plane (``ray_tpu/train/elastic.py``) journals epoch state
through the GCS-snapshotted KV, syncs weights over the broadcast tree
and replicates checkpoints off the writing node.  The simulator models
the SAME control decisions as discrete events on the virtual clock:

* **Epoch pipeline.**  form gang -> weight sync (a real
  :class:`SimBroadcastWave` rooted at the head, appended to
  ``cluster.broadcast_waves`` so campaign kill loops and broadcast
  invariants cover it) -> train for ``train_epoch_s`` -> write the
  checkpoint on the first gang member -> replicate it off-node -> ack.
  The epoch is journaled into ``cluster.persist["train"]`` ONLY once
  the checkpoint holds ``train_ckpt_replicas`` live copies and the
  head is alive — so acked epochs never regress by construction, and a
  promoted standby inherits the journal (``cluster.persist`` is
  cluster-scoped, exactly like the GCS snapshot the live head journals
  through).  Samples are booked at ack time, never earlier: goodput is
  committed samples over wall time, Gavel's effective-throughput
  framing (PAPERS.md 2008.09213).
* **SIGKILL mid-epoch.**  A gang member killed while training blocks
  the collective for ``train_collective_timeout_s`` virtual seconds
  (the bounded-timeout contract of ``util/collective.GangMemberLost``),
  then the epoch aborts and the gang re-forms from the last acked
  epoch.  A kill during weight sync is cheaper: the broadcast layer
  notices the dead peer immediately.
* **Planned resizes.**  A draining member (campaign drain fault or
  autoscaler reclaim) is removed WITHOUT the collective-timeout burn —
  the drain notice arrives before the death, the live trainer's
  no-``max_failures``-burn contract.
* **Checkpoint durability.**  Copy-holder death triggers
  re-replication to another live node; the ``ckpt-durable`` invariant
  fires if the newest acked checkpoint ever loses every copy, or stays
  under-replicated past the replication grace.
* **Reverse loaning (Aryl both directions).**  At each epoch boundary
  the gang borrows idle serve replicas through
  ``SimServePlane.begin_lend`` while serve sits in its diurnal trough
  (up to ``train_borrow_max``), and returns them — drain-reclaim
  semantics, lender-side booked — the moment ``wants_back`` turns on.

Determinism contract: the plane draws NOTHING from the RNG — every
decision is a function of cluster state and the virtual clock — and it
only exists when a ``train_diurnal`` campaign installs it, so every
other campaign's replay hash is untouched.
"""

from __future__ import annotations

from ..common.config import get_config
from .broadcast import SimBroadcastWave

__all__ = ["SimTrainPlane"]

_FORM_RETRY_S = 1.0     # re-poll period while the gang is under-strength
_SYNC_POLL_S = 1.0      # weight-sync wave terminal poll period
_TICK_S = 2.5           # sweep period (drains, borrows, re-replication)
_ACK_RETRY_S = 1.0      # journal retry period while the head is down
_SAMPLES_PER_WORKER = 64    # samples one worker contributes per epoch


class SimTrainPlane:
    """The training overlay a ``train_diurnal`` campaign installs on a
    :class:`SimCluster` (as ``cluster.train_plane``)."""

    def __init__(self, cluster, duration: float = 200.0,
                 num_workers: int | None = None, serve=None):
        cfg = get_config()
        self.cluster = cluster
        self.serve = serve              # SimServePlane or None
        self.epoch_s = float(cfg.train_epoch_s)
        self.ckpt_replicas = int(cfg.train_ckpt_replicas)
        self.replicate_s = float(cfg.train_ckpt_replicate_s)
        self.borrow_max = int(cfg.train_borrow_max)
        self.coll_timeout_s = float(cfg.train_collective_timeout_s)
        self.target = num_workers if num_workers is not None else \
            max(2, len(cluster.nodes) // 16)
        self.t_end = duration * 0.85

        self.reserved: set[str] = set()     # gang + borrowed rows
        self.gang: list[str] = []           # sorted member node ids
        self.borrowed: list[str] = []       # serve rows we hold
        self._pending_borrows: list[str] = []   # lend draining at serve
        self.state = "idle"
        self.attempt = 0                # bumps cancel stale epoch events
        self._epoch_gang: list[str] = []    # members at epoch start

        self.acked_epoch = 0
        self._hwm_epoch = 0             # acked high-water mark
        self.epochs_committed = 0
        self.epochs_aborted = 0
        self.samples_committed = 0
        # epoch -> {copies, t_write, t_degraded, acked, repl}
        self.ckpts: dict[int, dict] = {}
        self.gang_losses = 0            # SIGKILL -> collective timeout
        self.planned_resizes = 0        # drain/reclaim, no timeout burn
        self.borrows_total = 0
        self.borrows_returned = 0
        self.borrows_lost = 0
        self.head_ack_stalls = 0
        self.resyncs = 0                # weight-sync waves launched
        self.blocked_s = 0.0            # virtual time lost to timeouts
        self.started = False

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> None:
        clock, trace = self.cluster.clock, self.cluster.trace
        self.started = True
        trace.rec(clock.monotonic(), "train_start", target=self.target,
                  epoch_s=self.epoch_s, t_end=round(self.t_end, 3))
        self.state = "forming"
        clock.call_later(0.1, self._form)
        clock.call_later(_TICK_S, self._tick)

    @property
    def terminal(self) -> bool:
        return self.started and self.state == "done" and \
            not self.borrowed and not self._pending_borrows and \
            not self.reserved

    # -- helpers -------------------------------------------------------------
    def _node_alive(self, nid: str) -> bool:
        node = self.cluster.nodes.get(nid)
        return node is not None and node.alive

    def _node_draining(self, nid: str) -> bool:
        node = self.cluster.nodes.get(nid)
        return node is not None and node.draining

    def _live_copies(self, entry: dict) -> list[str]:
        return [c for c in sorted(entry["copies"]) if self._node_alive(c)]

    def _free_nodes(self) -> list[str]:
        """Idle batch rows the gang may claim, deterministically ordered
        — never serve's rows, never rows running batch work."""
        out = []
        splane = self.serve
        for nid in sorted(self.cluster.nodes):
            node = self.cluster.nodes[nid]
            if not node.alive or node.draining:
                continue
            if nid in self.reserved:
                continue
            if splane is not None and nid in splane.reserved:
                continue
            if node.running or node.local_queue:
                continue
            out.append(nid)
        return out

    # -- the epoch pipeline --------------------------------------------------
    def _form(self) -> None:
        if not self.cluster.running or self.state == "done":
            return
        if self.state != "forming":
            return      # a stale retry; the pipeline moved on
        clock, trace = self.cluster.clock, self.cluster.trace
        now = clock.monotonic()
        if now >= self.t_end:
            self._finish()
            return
        # sweep members that died or started draining between epochs
        self.gang = [m for m in self.gang
                     if self._node_alive(m) and not self._node_draining(m)
                     and m in self.reserved]
        # return borrows the moment serve wants them back (epoch
        # boundary = the drain-reclaim point of the reverse direction)
        if self.serve is not None and self.borrowed and \
                self.serve.wants_back():
            for nid in list(self.borrowed):
                self._return_borrow(nid)
        # borrowed serve rows join the gang first (they are reserved
        # by us, so _free_nodes never surfaces them)
        for nid in self.borrowed:
            if self._node_alive(nid) and nid not in self.gang:
                self.gang.append(nid)
        # refill from the free pool up to target strength
        for nid in self._free_nodes():
            if len(self.gang) >= self.target:
                break
            self.reserved.add(nid)
            self.gang.append(nid)
        self.gang.sort()
        # opportunistic surge: borrow idle serve replicas at the trough
        if self.serve is not None and \
                len(self.borrowed) + len(self._pending_borrows) < \
                self.borrow_max and self.serve.can_lend() and \
                not self.serve.wants_back():
            nid = self.serve.begin_lend()
            if nid is not None:
                self._pending_borrows.append(nid)
                self.borrows_total += 1
                trace.rec(now, "train_borrow", node=nid)
        if len(self.gang) < 2:
            clock.call_later(_FORM_RETRY_S, self._form)
            return
        # weight sync: (re)joining workers get the current weights down
        # the broadcast tree, never point-to-point
        self.state = "syncing"
        self.attempt += 1
        token = self.attempt
        self.resyncs += 1
        wave = SimBroadcastWave(
            self.cluster, f"train-sync-a{token}", list(self.gang),
            root="head", size_mb=256, fanout=2)
        self.cluster.broadcast_waves.append(wave)
        wave.start()
        trace.rec(now, "train_sync", wave=wave.wave_id,
                  members=len(self.gang), epoch=self.acked_epoch + 1)
        clock.call_later(_SYNC_POLL_S, lambda: self._poll_sync(token, wave))

    def _poll_sync(self, token: int, wave) -> None:
        if not self.cluster.running or token != self.attempt or \
                self.state != "syncing":
            return
        clock = self.cluster.clock
        if not wave.terminal:
            clock.call_later(_SYNC_POLL_S,
                             lambda: self._poll_sync(token, wave))
            return
        synced = set(wave.completed)
        self.gang = [m for m in self.gang if m in synced and
                     self._node_alive(m)]
        if len(self.gang) < 2:
            self.state = "forming"
            clock.call_later(_FORM_RETRY_S, self._form)
            return
        self.state = "training"
        self._epoch_gang = list(self.gang)
        now = clock.monotonic()
        self.cluster.trace.rec(now, "train_epoch_start",
                               epoch=self.acked_epoch + 1,
                               gang=len(self.gang))
        clock.call_later(self.epoch_s, lambda: self._trained(token))

    def _trained(self, token: int) -> None:
        if not self.cluster.running or token != self.attempt or \
                self.state != "training":
            return
        if any(not self._node_alive(m) for m in self.gang):
            # a member died and the collective is blocked: the pending
            # _gang_lost (or the planned-resize sweep) aborts the epoch
            return
        clock, trace = self.cluster.clock, self.cluster.trace
        now = clock.monotonic()
        self.state = "ckpt"
        e = self.acked_epoch + 1
        writer = self.gang[0]
        self.ckpts[e] = {"copies": {writer}, "t_write": now,
                         "t_degraded": None, "acked": False, "repl": 0}
        trace.rec(now, "train_ckpt_write", epoch=e, writer=writer)
        self._replicate(e, self.ckpts[e], token)

    def _replicate(self, e: int, entry: dict, token: int) -> None:
        """Schedule one more off-node copy of checkpoint ``e``."""
        targets = [n for n in self._free_nodes() + self.gang
                   if n not in entry["copies"]]
        if not targets:
            return      # the sweep retries when a target appears
        entry["repl"] += 1
        tgt = targets[0]
        self.cluster.clock.call_later(
            self.replicate_s,
            lambda: self._replicated(e, entry, tgt, token))

    def _replicated(self, e: int, entry: dict, tgt: str,
                    token: int) -> None:
        if not self.cluster.running or self.ckpts.get(e) is not entry:
            return      # epoch aborted meanwhile
        entry["repl"] -= 1
        now = self.cluster.clock.monotonic()
        if self._node_alive(tgt):
            entry["copies"].add(tgt)
            self.cluster.trace.rec(now, "train_ckpt_replica", epoch=e,
                                   node=tgt,
                                   copies=len(self._live_copies(entry)))
        live = len(self._live_copies(entry))
        if live >= self.ckpt_replicas:
            entry["t_degraded"] = None
            if not entry["acked"]:
                self._try_ack(e, entry, token)
        elif live > 0:
            self._replicate(e, entry, token)
        # live == 0 on an unacked entry: the sweep aborts the epoch

    def _try_ack(self, e: int, entry: dict, token: int) -> None:
        if not self.cluster.running or self.ckpts.get(e) is not entry:
            return
        if token != self.attempt or self.state not in ("ckpt", "acking"):
            return
        clock, trace = self.cluster.clock, self.cluster.trace
        head = self.cluster.head
        if head is None or not head.alive:
            # journal write needs the GCS: retry until the restarted (or
            # promoted standby) head is back — the epoch journal rides
            # the snapshot, so the new head inherits it unchanged
            self.state = "acking"
            self.head_ack_stalls += 1
            clock.call_later(_ACK_RETRY_S,
                             lambda: self._try_ack(e, entry, token))
            return
        now = clock.monotonic()
        samples = len(self._epoch_gang) * _SAMPLES_PER_WORKER
        entry["acked"] = True
        self.acked_epoch = e
        self._hwm_epoch = max(self._hwm_epoch, e)
        self.epochs_committed += 1
        self.samples_committed += samples
        jt = self.cluster.persist.setdefault("train", {})
        jt["epoch"] = e
        jt["samples"] = self.samples_committed
        jt["gang"] = len(self._epoch_gang)
        # bounded state: only the newest acked checkpoint stays tracked
        for old in [k for k in self.ckpts if k < e]:
            self.ckpts.pop(old)
        trace.rec(now, "train_epoch_acked", epoch=e, samples=samples,
                  gang=len(self._epoch_gang),
                  copies=len(self._live_copies(entry)))
        self.state = "forming"
        clock.call_later(0.01, self._form)

    def _finish(self) -> None:
        clock, trace = self.cluster.clock, self.cluster.trace
        now = clock.monotonic()
        for nid in list(self.borrowed):
            self._return_borrow(nid)
        for nid in list(self._pending_borrows):
            self._pending_borrows.remove(nid)
            if self.serve is not None:
                self.serve.end_lend(nid)
            self.borrows_returned += 1
        # release the gang back to the batch market; the newest acked
        # checkpoint's copies stay where they are (durable objects, not
        # reservations)
        self.reserved.clear()
        self.gang = []
        self.state = "done"
        trace.rec(now, "train_done", epochs=self.epochs_committed,
                  samples=self.samples_committed,
                  goodput_sps=round(self.goodput_sps(), 3))

    # -- failure plumbing ----------------------------------------------------
    def on_node_killed(self, nid: str) -> None:
        if not self.started or self.state == "done":
            self._book_copy_death(nid)
            return
        clock, trace = self.cluster.clock, self.cluster.trace
        now = clock.monotonic()
        if nid in self.borrowed:
            # the lender (serve) pops its own record and books the loss
            # exactly once; our side just forgets the row
            self.borrowed.remove(nid)
            self.reserved.discard(nid)
            self.borrows_lost += 1
            trace.rec(now, "train_borrow_lost", node=nid)
        if nid in self._pending_borrows:
            self._pending_borrows.remove(nid)
            self.borrows_lost += 1
            trace.rec(now, "train_borrow_lost", node=nid,
                      phase="draining")
        self._book_copy_death(nid)
        if nid not in self.gang:
            return
        if self.state == "training":
            # SIGKILL between barrier and reduce: the collective blocks
            # for the bounded timeout, then GangMemberLost aborts
            token = self.attempt
            trace.rec(now, "train_member_killed", node=nid,
                      timeout_s=self.coll_timeout_s)
            clock.call_later(self.coll_timeout_s,
                             lambda: self._gang_lost(token, nid))
        elif self.state == "syncing":
            # broadcast layer sees the dead peer at once: drop the
            # member, let the wave reach terminal, re-check strength
            self.gang.remove(nid)
            self.reserved.discard(nid)
        elif self.state in ("ckpt", "acking"):
            self.gang.remove(nid)
            self.reserved.discard(nid)
            e = self.acked_epoch + 1
            entry = self.ckpts.get(e)
            if entry is not None and not entry["acked"] and \
                    not self._live_copies(entry):
                self._abort_epoch(planned=False, reason="ckpt-lost")
        else:   # forming
            self.gang.remove(nid)
            self.reserved.discard(nid)

    def _book_copy_death(self, nid: str) -> None:
        now = self.cluster.clock.monotonic()
        for e in sorted(self.ckpts):
            entry = self.ckpts[e]
            if nid not in entry["copies"]:
                continue
            entry["copies"].discard(nid)
            live = len(self._live_copies(entry))
            if live < self.ckpt_replicas and entry["t_degraded"] is None:
                entry["t_degraded"] = now
            self.cluster.trace.rec(now, "train_ckpt_copy_lost", epoch=e,
                                   node=nid, copies=live)

    def _gang_lost(self, token: int, nid: str) -> None:
        if not self.cluster.running or token != self.attempt or \
                self.state != "training":
            return
        self.gang_losses += 1
        self.blocked_s += self.coll_timeout_s
        self.cluster.trace.rec(self.cluster.clock.monotonic(),
                               "train_gang_lost", node=nid,
                               epoch=self.acked_epoch + 1)
        self._abort_epoch(planned=False, reason="gang-member-lost")

    def _abort_epoch(self, planned: bool, reason: str) -> None:
        """Drop the in-flight epoch and re-form from the last acked one
        — the journal is untouched, so acked epochs never regress."""
        clock, trace = self.cluster.clock, self.cluster.trace
        self.attempt += 1       # cancels stale _trained/_poll_sync
        self.epochs_aborted += 1
        e = self.acked_epoch + 1
        entry = self.ckpts.get(e)
        if entry is not None and not entry["acked"]:
            self.ckpts.pop(e)
        self.gang = [m for m in self.gang if self._node_alive(m)
                     and not self._node_draining(m)]
        self.reserved.intersection_update(
            set(self.gang) | set(self.borrowed))
        if planned:
            self.planned_resizes += 1
        trace.rec(clock.monotonic(), "train_epoch_aborted",
                  epoch=e, planned=planned, reason=reason,
                  gang=len(self.gang))
        self.state = "forming"
        clock.call_later(0.01, self._form)

    # -- borrows -------------------------------------------------------------
    def _return_borrow(self, nid: str) -> None:
        self.borrowed.remove(nid)
        self.reserved.discard(nid)
        if nid in self.gang:
            self.gang.remove(nid)
        if self.serve is not None:
            self.serve.end_lend(nid)
        self.borrows_returned += 1
        self.cluster.trace.rec(self.cluster.clock.monotonic(),
                               "train_borrow_return", node=nid)

    # -- the sweep -----------------------------------------------------------
    def _tick(self) -> None:
        if not self.cluster.running:
            return
        clock, trace = self.cluster.clock, self.cluster.trace
        now = clock.monotonic()
        # borrowed rows whose lend finished draining at serve join the
        # reserved set (the gang picks them up at the next _form)
        for nid in list(self._pending_borrows):
            if self.serve is None:
                break
            if self.serve.lend_ready(nid):
                self._pending_borrows.remove(nid)
                self.borrowed.append(nid)
                self.reserved.add(nid)
                trace.rec(now, "train_borrow_ready", node=nid)
            elif nid not in self.serve.lent:
                # died while draining: lender already booked the loss
                self._pending_borrows.remove(nid)
                self.borrows_lost += 1
                trace.rec(now, "train_borrow_lost", node=nid,
                          phase="draining")
        # planned resizes: draining members leave WITHOUT the
        # collective-timeout burn; silently-dead drained members too
        if self.state != "done":
            for nid in [m for m in self.gang
                        if self._node_draining(m)]:
                if nid not in self.gang:
                    continue    # an abort below already swept it
                self.gang.remove(nid)
                self.reserved.discard(nid)
                trace.rec(now, "train_planned_resize", node=nid,
                          state=self.state)
                if self.state in ("training", "syncing"):
                    self._abort_epoch(planned=True, reason="drain")
                else:
                    self.planned_resizes += 1
            # members that died without a kill callback (clean exits)
            for nid in [m for m in self.gang
                        if not self._node_alive(m)]:
                if nid not in self.gang:
                    continue
                self._book_copy_death(nid)
                if self.state == "training":
                    token = self.attempt
                    trace.rec(now, "train_member_killed", node=nid,
                              timeout_s=self.coll_timeout_s)
                    clock.call_later(
                        self.coll_timeout_s,
                        lambda n=nid, t=token: self._gang_lost(t, n))
                else:
                    self.gang.remove(nid)
                    self.reserved.discard(nid)
        # checkpoint repair: re-replicate degraded entries; abort the
        # in-flight epoch if its sole copy is gone
        token = self.attempt
        for e in sorted(self.ckpts):
            entry = self.ckpts[e]
            live = self._live_copies(entry)
            entry["copies"] = set(live)
            if not entry["acked"] and not live and \
                    self.state in ("ckpt", "acking") and \
                    e == self.acked_epoch + 1:
                self._abort_epoch(planned=False, reason="ckpt-lost")
                continue
            if len(live) < self.ckpt_replicas and live and \
                    entry["repl"] == 0:
                self._replicate(e, entry, token)
            if len(live) >= self.ckpt_replicas:
                entry["t_degraded"] = None
        clock.call_later(_TICK_S, self._tick)

    # -- invariants ----------------------------------------------------------
    def check(self, strict: bool = False, now: float | None = None,
              grace: float = 10.0) -> tuple[list[str], int]:
        """Train-plane invariants, called from
        :func:`sim.invariants.check_invariants`."""
        from .invariants import fmt_violation

        violations: list[str] = []
        checks = 0
        if now is None:
            now = self.cluster.clock.monotonic()
        # goodput accounting: committed samples, the journal and the
        # acked-epoch counter must agree, and acks never regress
        checks += 1
        jt = self.cluster.persist.get("train")
        if jt is not None and (jt.get("epoch") != self.acked_epoch or
                               jt.get("samples") !=
                               self.samples_committed):
            violations.append(fmt_violation(
                "goodput-accounting", now,
                f"journal epoch={jt.get('epoch')}/"
                f"samples={jt.get('samples')} != plane "
                f"epoch={self.acked_epoch}/"
                f"samples={self.samples_committed}"))
        checks += 1
        if self.acked_epoch < self._hwm_epoch:
            violations.append(fmt_violation(
                "goodput-accounting", now,
                f"acked epoch regressed: {self.acked_epoch} < "
                f"high-water {self._hwm_epoch}"))
        # checkpoint durability: the newest acked checkpoint always has
        # a live copy, and reaches full replication within grace
        if self.acked_epoch > 0:
            checks += 1
            entry = self.ckpts.get(self.acked_epoch)
            live = [] if entry is None else self._live_copies(entry)
            if not live:
                violations.append(fmt_violation(
                    "ckpt-durable", now,
                    f"acked epoch {self.acked_epoch} checkpoint has "
                    f"no live copy"))
            elif len(live) < self.ckpt_replicas and \
                    entry["t_degraded"] is not None and \
                    now - entry["t_degraded"] > \
                    2.0 * self.replicate_s + grace:
                violations.append(fmt_violation(
                    "ckpt-durable", now,
                    f"acked epoch {self.acked_epoch} stuck at "
                    f"{len(live)}/{self.ckpt_replicas} copies for "
                    f"{now - entry['t_degraded']:.1f}s"))
        if strict:
            checks += 1
            if not self.terminal:
                violations.append(fmt_violation(
                    "gang-terminal", now,
                    f"training not terminal after quiesce: "
                    f"state={self.state} borrowed={len(self.borrowed)} "
                    f"pending={len(self._pending_borrows)} "
                    f"reserved={len(self.reserved)}"))
        return violations, checks

    # -- reporting -----------------------------------------------------------
    def goodput_sps(self) -> float:
        return self.samples_committed / max(self.t_end, 1e-9)

    def stats(self) -> dict:
        return {
            "workers_target": self.target,
            "state": self.state,
            "acked_epoch": self.acked_epoch,
            "epochs_committed": self.epochs_committed,
            "epochs_aborted": self.epochs_aborted,
            "samples_committed": self.samples_committed,
            "goodput_sps": round(self.goodput_sps(), 3),
            "gang_losses": self.gang_losses,
            "planned_resizes": self.planned_resizes,
            "blocked_s": round(self.blocked_s, 3),
            "resyncs": self.resyncs,
            "head_ack_stalls": self.head_ack_stalls,
            "borrows_total": self.borrows_total,
            "borrows_returned": self.borrows_returned,
            "borrows_lost": self.borrows_lost,
        }
