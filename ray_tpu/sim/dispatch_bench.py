"""Dispatch-throughput storm: the lease plane's headline numbers.

Drives a steady repeat-class job stream through a simulated cluster
twice — head-only path vs lease plane — and reports dispatch throughput
over **modeled head service time** (deterministic virtual microseconds:
a scheduling RPC costs ``_HEAD_RPC_US``, a heartbeat touch
``_HEAD_TOUCH_US``, a batched item ``_HEAD_ITEM_US``; see
``sim/cluster.py``).  The ratio is a pure function of RPC counts and
those constants, so the same seed reproduces the same numbers and the
same trace hash, byte for byte.

The failover variant SIGKILLs the head mid-stream with the hot standby
armed and reports the kill→first-post-promotion-placement window.

Used by ``bench.py`` (the committed BENCH artifact) and
``tests/test_leasing.py`` (the acceptance thresholds).
"""

from __future__ import annotations

from .cluster import HEAD_ADDR, SimCluster, SimParams

__all__ = ["run_dispatch_storm", "run_dispatch_comparison"]

# repeat-class workload: durations stand in for interned resource
# request vectors (see SimHead._class_key) — 8 classes, short tasks
_CLASSES = (1.0, 1.5, 2.0, 2.5, 3.0, 3.5, 4.0, 4.5)


def run_dispatch_storm(num_nodes: int = 200, jobs: int = 200,
                       tasks_per_job: int = 16,
                       classes: tuple = _CLASSES, seed: int = 0,
                       lease_plane: bool = True, standby: bool = False,
                       kill_head_at: float | None = None,
                       submit_period_s: float = 0.25,
                       heartbeat_period_s: float = 5.0,
                       settle_cap_s: float = 1800.0) -> dict:
    """One storm run; returns the throughput/hit-rate/failover record."""
    import numpy as np

    from ..rpc.client import RpcConnectionError

    rng = np.random.Generator(np.random.Philox(
        key=[int(seed) & (2 ** 64 - 1), 0xD15C47C4]))
    params = SimParams(heartbeat_period_s=heartbeat_period_s,
                       lease_plane=lease_plane, standby=standby)
    cluster = SimCluster(num_nodes, seed=seed, params=params)
    clock = cluster.clock
    acked: list[str] = []
    completed = {"n": 0}

    # the whole job stream is drawn up-front so the submission order —
    # and therefore the trace — is a pure function of the seed.  Each
    # job is single-class (an actor pool / map wave of same-shaped
    # tasks): the repeat-class steady state the lease plane serves
    stream = []
    for k in range(jobs):
        jid = f"d{k:05d}"
        duration = classes[int(rng.integers(0, len(classes)))]
        tasks = {f"{jid}.t{i}": duration for i in range(tasks_per_job)}
        stream.append((jid, tasks))

    with cluster:
        driver = cluster.transport.connect(HEAD_ADDR,
                                           _sim_src="sim://driver")

        def submit(jid, tasks, attempt=0):
            try:
                if driver.call("job_submit", jid, tasks) == "ack":
                    acked.append(jid)
                    return
            except RpcConnectionError:
                pass
            if attempt < 60:        # head down (failover window)
                clock.call_later(1.0, lambda: submit(jid, tasks,
                                                     attempt + 1))

        t0 = heartbeat_period_s + 1.0   # past the registration stagger
        for k, (jid, tasks) in enumerate(stream):
            clock.call_later(t0 + k * submit_period_s,
                             lambda jid=jid, tasks=tasks:
                             submit(jid, tasks))
        if kill_head_at is not None:
            clock.call_later(float(kill_head_at), cluster.kill_head)

        def all_done():
            head = cluster.head
            if head is None or not head.alive:
                return False
            done = sum(1 for jid in acked
                       if head.jobs.get(jid, {}).get("status") ==
                       "succeeded")
            completed["n"] = done
            return len(acked) == len(stream) and done == len(acked)

        horizon = t0 + jobs * submit_period_s
        clock.run_until(horizon)
        settle_end = horizon + settle_cap_s
        while not all_done() and clock.monotonic() < settle_end:
            clock.advance(heartbeat_period_s)
        stats = cluster.stats()
    cluster.close()

    rec = {
        "mode": "lease" if lease_plane else "head_only",
        "nodes": num_nodes, "seed": int(seed),
        "jobs": jobs, "tasks": jobs * tasks_per_job,
        "jobs_completed": completed["n"],
        "tasks_done": stats["dispatch"]["tasks_done"],
        "head_busy_s": stats["dispatch"]["head_busy_s"],
        "head_dispatch_s": stats["dispatch"]["head_dispatch_s"],
        "dispatch_throughput_per_s":
            stats["dispatch"]["throughput_per_s"],
        "virtual_s": stats["virtual_s"],
        "trace_hash": cluster.trace.hash(),
    }
    if lease_plane:
        lz = stats["leasing"]
        rec.update({
            "lease_hit_rate": lz["lease_hit_rate"],
            "leases_granted_local": lz["leases_granted_local"],
            "spillbacks": lz["spillbacks"],
            "lease_revocations": lz["lease_revocations"],
            "promotions": lz["promotions"],
            "failover_ms": lz["failover_ms"],
        })
    return rec


def run_dispatch_comparison(num_nodes: int = 200, jobs: int = 200,
                            tasks_per_job: int = 16, seed: int = 0,
                            kill_head_at: float | None = None,
                            **kw) -> dict:
    """Head-only baseline vs lease plane on the identical job stream
    (+ optionally a standby-armed failover run).  The speedup ratio is
    the acceptance number: steady-state dispatch throughput of the
    lease plane over the head-only path."""
    base = run_dispatch_storm(num_nodes, jobs, tasks_per_job,
                              seed=seed, lease_plane=False, **kw)
    lease = run_dispatch_storm(num_nodes, jobs, tasks_per_job,
                               seed=seed, lease_plane=True, **kw)
    out = {
        "head_only": base,
        "lease": lease,
        "speedup": round(
            lease["dispatch_throughput_per_s"] /
            base["dispatch_throughput_per_s"], 3)
        if base["dispatch_throughput_per_s"] else 0.0,
    }
    if kill_head_at is not None:
        out["failover"] = run_dispatch_storm(
            num_nodes, jobs, tasks_per_job, seed=seed,
            lease_plane=True, standby=True,
            kill_head_at=kill_head_at, **kw)
    return out
