"""Simulated control plane: head, nodes and autoscaler as discrete-event
state machines.

These are the *control* state machines of the real runtime — register/
heartbeat/death declaration (``runtime/health.py``), lease grant and
lost-ack requeue (``runtime/raylet.py``), the breaker→quarantine→
soft-avoid chain (``rpc/breaker.py`` + ``runtime/health.py`` +
scheduler), drain convergence (``cluster_utils.drain_node``), snapshot
persistence and head failover (``runtime/head.py``), lineage
reconstruction (``runtime/recovery.py``) and the autoscaler sizing loop
— re-expressed over the ``Clock``/``Transport`` seams so 10k of them
run in one process.  Where the real modules have a reusable primitive
(``PeerBreaker``, the chaos plane's Philox link streams), the simulator
uses the real class, on virtual time.

Determinism contract: single-threaded, virtual clock, all randomness
from Philox (the chaos instance plus the campaign's own generator), no
iteration over unordered sets.  The same seed replays the same trace,
byte for byte.
"""

from __future__ import annotations

import hashlib
import json
from collections import deque
from dataclasses import dataclass

from ..common.clock import VirtualClock
from ..common.config import get_config
from ..rpc.breaker import CLOSED, OPEN, PeerBreaker
from ..rpc.chaos import _Chaos
from ..rpc.client import RpcConnectionError
from .transport import SimTransport

__all__ = ["SimCluster", "SimParams", "SimHead", "SimNode",
           "SimAutoscaler", "Trace", "ALIVE", "DRAINING", "DEAD",
           "REMOVED"]

ALIVE, DRAINING, DEAD, REMOVED = "alive", "draining", "dead", "removed"
HEAD_ADDR = "sim://head"

_TRACE_EVENT_CAP = 20000        # stored events; the hash covers ALL


class Trace:
    """Append-only campaign trace with an incremental sha256 over the
    canonical JSON of every event — the replay fingerprint.  Storage is
    capped (artifacts stay small at 10k nodes); the hash is not."""

    def __init__(self):
        self.events: list[dict] = []
        self.total = 0
        self._h = hashlib.sha256()

    def rec(self, t: float, kind: str, **fields) -> None:
        ev = {"t": round(t, 6), "kind": kind}
        ev.update(fields)
        self._h.update(json.dumps(
            ev, sort_keys=True, separators=(",", ":")).encode())
        self._h.update(b"\n")
        self.total += 1
        if len(self.events) < _TRACE_EVENT_CAP:
            self.events.append(ev)

    def hash(self) -> str:
        return self._h.hexdigest()


@dataclass
class SimParams:
    """Timing/shape knobs, defaulted from the ``sim_*`` config knobs."""

    heartbeat_period_s: float = 5.0
    miss_threshold: int = 3
    lease_timeout_s: float = 20.0
    drain_deadline_s: float = 45.0
    node_capacity: int = 4
    boot_delay_s: float = 3.0
    autoscaler_interval_s: float = 5.0
    autoscaler_idle_timeout_s: float = 60.0

    @classmethod
    def from_config(cls) -> "SimParams":
        cfg = get_config()
        return cls(
            heartbeat_period_s=cfg.sim_heartbeat_period_s,
            miss_threshold=cfg.sim_heartbeat_miss_threshold,
            lease_timeout_s=cfg.sim_lease_timeout_s,
            drain_deadline_s=cfg.sim_drain_deadline_s,
            node_capacity=cfg.sim_node_capacity,
            boot_delay_s=cfg.sim_boot_delay_s,
        )


class SimNode:
    """One simulated node agent: heartbeat loop, lease execution with
    idempotent re-grant handling, ack retry, drain participation."""

    def __init__(self, cluster: "SimCluster", nid: str):
        self.cluster = cluster
        self.nid = nid
        self.address = f"sim://{nid}"
        self.clock = cluster.clock
        self.params = cluster.params
        self.alive = True
        self.registered = False
        self.draining = False
        self.running: dict[str, float] = {}     # tid -> started (virtual)
        self.done: dict[str, str] = {}          # tid -> oid (ack cache)
        self.holds: dict[str, bool] = {}        # oid -> True
        self.server = cluster.transport.serve(
            {"exec": self._h_exec, "drain": self._h_drain,
             "ping": self._h_ping}, host=self.address).start()
        self.head = cluster.transport.connect(HEAD_ADDR,
                                              _sim_src=self.address)

    def start(self, stagger: float = 0.0) -> None:
        self.clock.call_later(stagger, self._beat)

    # -- heartbeat / (re-)register loop --------------------------------------
    def _beat(self) -> None:
        if not self.alive:
            return
        try:
            if not self.registered:
                self.head.call("register", self.nid, self.address,
                               self._report())
                self.registered = True
            else:
                reply = self.head.call("heartbeat", self.nid)
                if reply == "reregister":
                    # restarted head lost our row: rejoin with state
                    self.registered = False
                    self.head.call("register", self.nid, self.address,
                                   self._report())
                    self.registered = True
        except RpcConnectionError:
            pass        # head down/partitioned: keep beating
        self.clock.call_later(self.params.heartbeat_period_s, self._beat)

    def _report(self) -> dict:
        return {"running": list(self.running), "done": dict(self.done),
                "holds": list(self.holds), "draining": self.draining}

    # -- handlers ------------------------------------------------------------
    def _h_ping(self) -> str:
        return "pong"

    def _h_exec(self, tid: str, duration: float):
        if tid in self.done:
            # late re-grant of finished work: answer from the ack cache
            return {"op": "done", "oid": self.done[tid]}
        if tid in self.running:
            return {"op": "running"}        # dup delivery: idempotent
        if self.draining:
            return {"op": "rejected"}
        self.running[tid] = self.clock.monotonic()
        self.clock.call_later(duration, lambda: self._complete(tid))
        return {"op": "accepted"}

    def _h_drain(self) -> str:
        self.draining = True
        if not self.running:
            self._drain_done(0)
        return "ok"

    # -- completion / ack ----------------------------------------------------
    def _complete(self, tid: str) -> None:
        if not self.alive or tid not in self.running:
            return
        del self.running[tid]
        oid = "o:" + tid
        self.done[tid] = oid
        if len(self.done) > 512:            # bounded idempotency window
            self.done.pop(next(iter(self.done)))
        self.holds[oid] = True
        self._ack(tid, oid, 0)
        if self.draining and not self.running:
            self._drain_done(0)

    def _ack(self, tid: str, oid: str, attempt: int) -> None:
        if not self.alive:
            return
        try:
            self.head.call("task_done", self.nid, tid, oid)
        except RpcConnectionError:
            self.clock.call_later(min(8.0, 1.0 + attempt),
                                  lambda: self._ack(tid, oid, attempt + 1))

    def _drain_done(self, attempt: int) -> None:
        if not self.alive or not self.draining or self.running:
            return
        try:
            self.head.call("drain_done", self.nid)
        except RpcConnectionError:
            self.clock.call_later(min(8.0, 1.0 + attempt),
                                  lambda: self._drain_done(attempt + 1))
            return
        # drained and acknowledged: this node's process exits
        self.alive = False
        self.cluster.transport.kill(self.address)
        self.cluster.node_stopped(self.nid)


class SimHead:
    """The simulated head: node table, job/lease tables, snapshot-backed
    persistence (survives kill), death declaration, lost-ack lease
    requeue, drain convergence, breaker-driven quarantine with
    soft-avoid scheduling, and lineage reconstruction."""

    def __init__(self, cluster: "SimCluster"):
        self.cluster = cluster
        self.clock = cluster.clock
        self.params = cluster.params
        self.trace = cluster.trace
        self.persist = cluster.persist      # survives head kill
        self.alive = True
        self.nodes: dict[str, dict] = {}
        self._node_order: list[str] = []
        self._rr = 0
        self.jobs: dict[str, dict] = {}
        self.tasks: dict[str, dict] = {}
        self.objects: dict[str, dict] = {}  # oid -> {producer, copies}
        self.pending: deque[str] = deque()
        self.breakers: dict[str, PeerBreaker] = {}
        self._clients: dict[str, object] = {}
        self.server = cluster.transport.serve(
            {"register": self._h_register, "heartbeat": self._h_heartbeat,
             "job_submit": self._h_job_submit, "task_done": self._h_task_done,
             "drain_done": self._h_drain_done, "ping": self._h_ping,
             "status": self._h_status}, host=HEAD_ADDR).start()
        self._restore()
        self.clock.call_later(self.params.heartbeat_period_s,
                              self._monitor)

    # -- persistence ---------------------------------------------------------
    def _restore(self) -> None:
        restored = 0
        for jid, spec in self.persist["jobs"].items():
            tids = list(spec["tasks"])
            self.jobs[jid] = {"tasks": tids, "status": "running"}
            for tid in tids:
                done_oid = self.persist["done"].get(tid)
                t = {"job": jid, "duration": spec["tasks"][tid],
                     "state": "pending", "node": None, "granted_at": 0.0,
                     "attempts": 0, "oid": None}
                if done_oid is not None:
                    t["state"] = "done"
                    t["oid"] = done_oid
                    self.objects.setdefault(
                        done_oid, {"producer": tid, "copies": {}})
                else:
                    self.pending.append(tid)
                self.tasks[tid] = t
            self._refresh_job(jid)
            restored += 1
        if restored:
            self.trace.rec(self.clock.monotonic(), "head_restore",
                           jobs=restored, pending=len(self.pending))

    # -- handlers ------------------------------------------------------------
    def _h_ping(self) -> str:
        return "pong"

    def _h_register(self, nid: str, address: str, report: dict) -> str:
        now = self.clock.monotonic()
        known = nid in self.nodes
        self.nodes[nid] = {
            "address": address, "state": ALIVE, "last_hb": now,
            "suspect": False, "running": {}, "drain_started": None,
            "idle_since": now,
        }
        if not known:
            self._node_order.append(nid)
        row = self.nodes[nid]
        if report.get("draining"):
            row["state"] = DRAINING
            row["drain_started"] = now
        for tid, oid in report.get("done", {}).items():
            self._mark_done(tid, oid, nid)
        for oid in report.get("holds", ()):
            obj = self.objects.get(oid)
            if obj is not None:
                obj["copies"][nid] = True
        for tid in report.get("running", ()):
            t = self.tasks.get(tid)
            if t is not None and t["state"] != "done":
                t["state"] = "running"
                t["node"] = nid
                t["granted_at"] = now
                row["running"][tid] = True
        self._schedule()
        return "ok"

    def _h_heartbeat(self, nid: str) -> str:
        row = self.nodes.get(nid)
        if row is None or row["state"] in (DEAD, REMOVED):
            return "reregister"
        row["last_hb"] = self.clock.monotonic()
        # serve-plane piggyback: the load digest for this node's replica
        # folds on the heartbeat that carries its liveness — the same
        # no-extra-RPC contract as the live gossip board
        plane = self.cluster.serve_plane
        if plane is not None:
            plane.on_heartbeat(nid)
        return "ok"

    def _h_job_submit(self, jid: str, tasks: dict) -> str:
        if jid not in self.persist["jobs"]:
            # persist BEFORE acking: an acked job survives a head kill
            self.persist["jobs"][jid] = {"tasks": dict(tasks)}
            self.jobs[jid] = {"tasks": list(tasks), "status": "running"}
            for tid, duration in tasks.items():
                self.tasks[tid] = {
                    "job": jid, "duration": duration, "state": "pending",
                    "node": None, "granted_at": 0.0, "attempts": 0,
                    "oid": None}
                self.pending.append(tid)
            self.trace.rec(self.clock.monotonic(), "job_submit", job=jid,
                           tasks=len(tasks))
        self._schedule()
        return "ack"

    def _h_task_done(self, nid: str, tid: str, oid: str) -> str:
        self._mark_done(tid, oid, nid)
        self._schedule()
        return "ok"

    def _h_drain_done(self, nid: str) -> str:
        row = self.nodes.get(nid)
        if row is not None and row["state"] == DRAINING:
            self._remove_node(nid, "drained")
        return "ok"

    def _h_status(self) -> dict:
        states: dict[str, int] = {}
        for nid in self._node_order:
            row = self.nodes.get(nid)
            if row is not None:
                states[row["state"]] = states.get(row["state"], 0) + 1
        return {"nodes": states, "jobs": len(self.jobs),
                "pending": len(self.pending)}

    # -- bookkeeping ---------------------------------------------------------
    def _mark_done(self, tid: str, oid: str, nid: str) -> None:
        t = self.tasks.get(tid)
        if t is None:
            return
        prev = t["node"]
        if prev is not None:
            prow = self.nodes.get(prev)
            if prow is not None:
                prow["running"].pop(tid, None)
                if not prow["running"]:
                    prow["idle_since"] = self.clock.monotonic()
        nrow = self.nodes.get(nid)
        if nrow is not None:
            nrow["running"].pop(tid, None)
            if not nrow["running"]:
                nrow["idle_since"] = self.clock.monotonic()
        obj = self.objects.setdefault(oid,
                                      {"producer": tid, "copies": {}})
        obj["copies"][nid] = True
        if t["state"] != "done":
            t["state"] = "done"
            t["node"] = None
            t["oid"] = oid
            self.persist["done"][tid] = oid
            self._refresh_job(t["job"])

    def _refresh_job(self, jid: str) -> None:
        job = self.jobs.get(jid)
        if job is None or job["status"] == "succeeded":
            return
        if all(self.tasks[tid]["state"] == "done"
               for tid in job["tasks"]):
            job["status"] = "succeeded"
            self.trace.rec(self.clock.monotonic(), "job_complete",
                           job=jid)

    def _breaker(self, addr: str) -> PeerBreaker:
        b = self.breakers.get(addr)
        if b is None:
            cfg = get_config()
            b = self.breakers[addr] = PeerBreaker(
                addr, cfg.rpc_breaker_failure_threshold,
                cfg.rpc_breaker_reset_s)
        return b

    def _client(self, nid: str):
        c = self._clients.get(nid)
        if c is None:
            c = self._clients[nid] = self.cluster.transport.connect(
                self.nodes[nid]["address"], _sim_src=HEAD_ADDR)
        return c

    def _after_breaker(self, nid: str, b: PeerBreaker) -> None:
        """The quarantine chain: OPEN breaker -> suspect (scheduler
        soft-avoids), CLOSED again -> unquarantined."""
        row = self.nodes.get(nid)
        if row is None:
            return
        if b.state == OPEN and not row["suspect"]:
            row["suspect"] = True
            self.trace.rec(self.clock.monotonic(), "quarantine",
                           node=nid, opens=b.opens)
        elif b.state == CLOSED and row["suspect"]:
            row["suspect"] = False
            self.trace.rec(self.clock.monotonic(), "unquarantine",
                           node=nid)

    # -- scheduling ----------------------------------------------------------
    def _pick_node(self) -> str | None:
        plane = self.cluster.serve_plane
        for allow_suspect in (False, True):     # soft-avoid: two passes
            n = len(self._node_order)
            for off in range(n):
                nid = self._node_order[(self._rr + off) % n]
                row = self.nodes.get(nid)
                if row is None or row["state"] != ALIVE:
                    continue
                if plane is not None and nid in plane.reserved:
                    continue    # serve replica or LOANED: off the market
                if row["suspect"] and not allow_suspect:
                    continue
                if len(row["running"]) >= self.params.node_capacity:
                    continue
                if row["suspect"] and \
                        not self._breaker(row["address"]).allow():
                    continue        # open breaker: hard fail-fast
                self._rr = (self._rr + off + 1) % n
                return nid
        return None

    def _schedule(self) -> None:
        if not self.alive:
            return
        for _ in range(len(self.pending)):
            if not self.pending:
                break
            tid = self.pending.popleft()
            t = self.tasks.get(tid)
            if t is None or t["state"] != "pending":
                continue
            nid = self._pick_node()
            if nid is None:
                self.pending.appendleft(tid)
                break
            self._grant(tid, nid)

    def _grant(self, tid: str, nid: str) -> None:
        row = self.nodes[nid]
        b = self._breaker(row["address"])
        t = self.tasks[tid]
        try:
            reply = self._client(nid).call("exec", tid, t["duration"])
        except RpcConnectionError:
            b.record_failure()
            self._after_breaker(nid, b)
            self.pending.append(tid)
            return
        b.record_success()
        self._after_breaker(nid, b)
        if reply.get("op") == "done":
            self._mark_done(tid, reply["oid"], nid)
            return
        if reply.get("op") == "rejected":       # node started draining
            self.pending.append(tid)
            return
        t["state"] = "running"
        t["node"] = nid
        t["granted_at"] = self.clock.monotonic()
        t["attempts"] += 1
        row["running"][tid] = True

    # -- drain / death / removal ---------------------------------------------
    def start_drain(self, nid: str, reason: str) -> bool:
        row = self.nodes.get(nid)
        if row is None or row["state"] != ALIVE:
            return False
        row["state"] = DRAINING
        row["drain_started"] = self.clock.monotonic()
        self.trace.rec(self.clock.monotonic(), "drain_start", node=nid,
                       reason=reason)
        try:
            self._client(nid).call("drain")
        except RpcConnectionError:
            pass        # deadline in the monitor will force-remove
        return True

    def _on_node_dead(self, nid: str, reason: str) -> None:
        row = self.nodes[nid]
        row["state"] = DEAD
        requeued = self._requeue_node(nid)
        for oid in list(self.objects):
            self.objects[oid]["copies"].pop(nid, None)
        self.trace.rec(self.clock.monotonic(), "node_dead", node=nid,
                       reason=reason, requeued=requeued)
        self._remove_node(nid, "dead")

    def _requeue_node(self, nid: str) -> int:
        row = self.nodes[nid]
        requeued = 0
        for tid in list(row["running"]):
            t = self.tasks.get(tid)
            if t is not None and t["state"] == "running" and \
                    t["node"] == nid:
                t["state"] = "pending"
                t["node"] = None
                self.pending.append(tid)
                requeued += 1
        row["running"].clear()
        return requeued

    def _remove_node(self, nid: str, reason: str) -> None:
        row = self.nodes[nid]
        if row["state"] != DEAD:
            self._requeue_node(nid)
        row["state"] = REMOVED
        row["drain_started"] = None
        self.trace.rec(self.clock.monotonic(), "node_removed", node=nid,
                       reason=reason)

    # -- the periodic monitor ------------------------------------------------
    def _monitor(self) -> None:
        if not self.alive:
            return
        now = self.clock.monotonic()
        p = self.params
        hb_deadline = p.heartbeat_period_s * p.miss_threshold
        for nid in self._node_order:
            row = self.nodes.get(nid)
            if row is None:
                continue
            state = row["state"]
            if state in (ALIVE, DRAINING) and \
                    now - row["last_hb"] > hb_deadline:
                self._on_node_dead(nid, "heartbeat_timeout")
                continue
            if state == DRAINING and row["drain_started"] is not None \
                    and now - row["drain_started"] > p.drain_deadline_s:
                self._remove_node(nid, "drain_deadline")
                continue
            # lost-ack lease recovery
            for tid in list(row["running"]):
                t = self.tasks.get(tid)
                if t is None or t["state"] != "running":
                    row["running"].pop(tid, None)
                    continue
                if now - t["granted_at"] > p.lease_timeout_s:
                    row["running"].pop(tid, None)
                    t["state"] = "pending"
                    t["node"] = None
                    self.pending.append(tid)
                    self.trace.rec(now, "lease_requeued", task=tid,
                                   node=nid)
            # half-open probes for quarantined nodes
            if row["state"] == ALIVE and row["suspect"]:
                b = self._breaker(row["address"])
                if b.allow():
                    try:
                        self._client(nid).call("ping")
                        b.record_success()
                    except RpcConnectionError:
                        b.record_failure()
                    self._after_breaker(nid, b)
        # lineage: outputs of done tasks that lost every copy while the
        # job still needs them are reconstructed by re-running the task
        for jid, job in self.jobs.items():
            if job["status"] == "succeeded":
                continue
            for tid in job["tasks"]:
                t = self.tasks[tid]
                if t["state"] == "done":
                    obj = self.objects.get(t["oid"])
                    if obj is None or not obj["copies"]:
                        t["state"] = "pending"
                        t["node"] = None
                        self.pending.append(tid)
                        self.trace.rec(now, "reconstruct", task=tid,
                                       job=jid)
        self._schedule()
        self.clock.call_later(p.heartbeat_period_s, self._monitor)


class SimAutoscaler:
    """Sizing loop over the simulated head's node table: launches to
    cover pending demand and the min floor, drains idle surplus."""

    def __init__(self, cluster: "SimCluster", min_nodes: int,
                 max_nodes: int):
        self.cluster = cluster
        self.min_nodes = min_nodes
        self.max_nodes = max_nodes
        self.launched = 0
        self.drained = 0
        cluster.clock.call_later(cluster.params.autoscaler_interval_s,
                                 self._tick)

    def _tick(self) -> None:
        cl = self.cluster
        if not cl.running:
            return
        head = cl.head
        if head is not None and head.alive:
            p = cl.params
            now = cl.clock.monotonic()
            plane = cl.serve_plane
            alive = []
            free = 0
            for nid in head._node_order:
                row = head.nodes.get(nid)
                if row is not None and row["state"] == ALIVE:
                    alive.append(nid)
                    if plane is not None and nid in plane.reserved:
                        continue    # serve/LOANED rows add no batch slack
                    if not row["suspect"]:
                        free += p.node_capacity - len(row["running"])
            pending = len(head.pending)
            up = 0
            if pending > free:
                up = -(-(pending - free) // p.node_capacity)  # ceil
            if len(alive) < self.min_nodes:
                up = max(up, self.min_nodes - len(alive))
            up = max(0, min(up, self.max_nodes - len(alive)))
            if up:
                for _ in range(up):
                    cl.launch_node(booting=True)
                self.launched += up
                cl.trace.rec(now, "scale_up", count=up,
                             pending=pending)
            elif pending == 0 and len(alive) > self.min_nodes:
                surplus = len(alive) - self.min_nodes
                drained = 0
                for nid in alive:
                    if drained >= min(2, surplus):  # gentle: <=2/tick
                        break
                    if plane is not None and nid in plane.reserved:
                        continue    # never idle-drain a serve replica
                    row = head.nodes[nid]
                    if not row["running"] and \
                            now - row["idle_since"] > \
                            p.autoscaler_idle_timeout_s:
                        if head.start_drain(nid, "idle_surplus"):
                            drained += 1
                self.drained += drained
        cl.clock.call_later(cl.params.autoscaler_interval_s, self._tick)


class SimCluster:
    """Owns the virtual clock, the sim transport, the chaos instance and
    every simulated component.  ``install()``/``close()`` swap the
    process clock seam in and out (the campaign runner brackets runs
    with them)."""

    def __init__(self, num_nodes: int, seed: int = 0,
                 params: SimParams | None = None,
                 chaos_params: dict | None = None):
        self.seed = int(seed)
        self.clock = VirtualClock()
        self.params = params or SimParams.from_config()
        self.chaos = _Chaos(seed=self.seed, **(chaos_params or {}))
        self.transport = SimTransport(chaos=self.chaos)
        self.trace = Trace()
        self.persist: dict = {"jobs": {}, "done": {}}
        self.nodes: dict[str, SimNode] = {}
        self._next_node = 0
        self.alive_count = 0
        self.peak_nodes = 0
        self.running = True
        self.head: SimHead | None = None
        self.autoscaler: SimAutoscaler | None = None
        self.serve_plane = None     # installed by serve_diurnal campaigns
        self.start_head()
        period = self.params.heartbeat_period_s
        for i in range(num_nodes):
            # stagger first beats across one period so 10k registrations
            # don't land on a single timestamp
            self.launch_node(stagger=period * i / max(1, num_nodes))
        self.trace.rec(0.0, "cluster_start", nodes=num_nodes,
                       seed=self.seed)

    # -- clock seam management ----------------------------------------------
    def install(self) -> "SimCluster":
        from ..common import clock as _clk
        self._prev_clock = _clk.get_clock()
        _clk.install(self.clock)
        return self

    def close(self) -> None:
        from ..common import clock as _clk
        self.running = False
        if getattr(self, "_prev_clock", None) is not None:
            _clk.install(self._prev_clock)
            self._prev_clock = None
        else:
            _clk.uninstall()

    def __enter__(self) -> "SimCluster":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.close()

    # -- topology ------------------------------------------------------------
    def start_head(self) -> SimHead:
        self.head = SimHead(self)
        return self.head

    def kill_head(self) -> None:
        if self.head is not None:
            self.head.alive = False
            self.transport.kill(HEAD_ADDR)
            self.head = None

    def launch_node(self, stagger: float | None = None,
                    booting: bool = False) -> str:
        nid = f"n{self._next_node:05d}"
        self._next_node += 1
        delay = self.params.boot_delay_s if booting else (stagger or 0.0)
        if booting:
            self.clock.call_later(delay, lambda: self._boot(nid, 0.0))
        else:
            self._boot(nid, delay)
        return nid

    def _boot(self, nid: str, stagger: float) -> None:
        if not self.running:
            return
        node = SimNode(self, nid)
        self.nodes[nid] = node
        node.start(stagger=stagger)
        self.alive_count += 1
        self.peak_nodes = max(self.peak_nodes, self.alive_count)

    def kill_node(self, nid: str) -> bool:
        node = self.nodes.get(nid)
        if node is None or not node.alive:
            return False
        node.alive = False
        self.transport.kill(node.address)
        self.alive_count -= 1
        return True

    def node_stopped(self, nid: str) -> None:
        """A node exited cleanly (post-drain)."""
        self.alive_count -= 1

    def enable_autoscaler(self, min_nodes: int,
                          max_nodes: int) -> SimAutoscaler:
        self.autoscaler = SimAutoscaler(self, min_nodes, max_nodes)
        return self.autoscaler

    # -- convenience ---------------------------------------------------------
    def alive_node_ids(self) -> list[str]:
        return [nid for nid, n in self.nodes.items() if n.alive]

    def stats(self) -> dict:
        tr = self.transport
        return {
            "virtual_s": round(self.clock.monotonic(), 3),
            "events_fired": self.clock.fired,
            "rpc_calls": tr.calls,
            "rpc_dropped": tr.dropped,
            "rpc_dup": tr.dup_delivered,
            "rpc_unreachable": tr.unreachable,
            "chaos_partitioned": self.chaos.num_partitioned,
            "chaos_delayed": self.chaos.num_delayed,
            "peak_nodes": self.peak_nodes,
            "trace_events": self.trace.total,
        }
